"""Profiler (reference: `python/paddle/profiler/profiler.py:349` + C++
`fluid/platform/profiler/`).

TPU-native: host spans are recorded by this module (HostTracer parity); device activity
comes from `jax.profiler` (XPlane — the CudaTracer/CUPTI analog), exported as a
TensorBoard trace directory.  `export_chrome_tracing` writes the host span tree in
chrome-tracing JSON, like ChromeTracingLogger.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from enum import Enum
from typing import Callable, Iterable, Optional


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class _HostEvent:
    __slots__ = ("name", "start", "end", "tid")

    def __init__(self, name, start, end, tid):
        self.name = name
        self.start = start
        self.end = end
        self.tid = tid


# Host-span buffer cap: recording is a ring over the newest spans, like the
# serving engine's step-trace ring — a trace window left open over a soak run
# must not grow host memory without bound (~10 engine spans per serving step).
HOST_EVENT_CAP = 1_000_000

_events = deque(maxlen=HOST_EVENT_CAP)
_recording = False
_TRACE_ANNOTATION = None        # cached jax.profiler.TraceAnnotation lookup


def is_recording() -> bool:
    """Whether a Profiler is currently collecting host spans — callers with
    spans on a hot path (the serving engine's per-step phases) gate span
    construction on this instead of paying RecordEvent setup every step."""
    return _recording


def _trace_annotation():
    # resolve jax.profiler.TraceAnnotation once per process; False caches a
    # failed import so a jax-less environment doesn't retry on every span
    global _TRACE_ANNOTATION
    if _TRACE_ANNOTATION is None:
        try:
            import jax.profiler
            _TRACE_ANNOTATION = jax.profiler.TraceAnnotation
        except Exception:
            _TRACE_ANNOTATION = False
    return _TRACE_ANNOTATION


class RecordEvent:
    """Span annotation (reference `RecordEvent`); also forwards to jax named scopes so
    spans appear in the XLA device trace."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._t0 = None
        self._scope = None

    def begin(self):
        self._t0 = time.perf_counter_ns()
        cls = _trace_annotation()
        if cls:
            try:
                self._scope = cls(self.name)
                self._scope.__enter__()
            except Exception:
                self._scope = None

    def end(self):
        if self._scope is not None:
            self._scope.__exit__(None, None, None)
        if _recording and self._t0 is not None:
            _events.append(_HostEvent(self.name, self._t0, time.perf_counter_ns(),
                                      threading.get_ident()))

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        period = closed + ready + record
        if repeat > 0 and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def dump_chrome_trace(fname: str) -> None:
    """Serialize the host spans recorded so far (the module event buffer) as
    chrome-tracing JSON — usable mid-recording, so a capture window nested
    inside a longer-running Profiler can snapshot without stopping it."""
    traceEvents = [{
        "name": e.name, "ph": "X", "ts": e.start / 1000.0,
        "dur": (e.end - e.start) / 1000.0, "pid": 0, "tid": e.tid,
    } for e in _events]
    with open(fname, "w") as f:
        json.dump({"traceEvents": traceEvents}, f)


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        fname = os.path.join(dir_name, f"{worker_name or 'worker'}_trace.json")
        prof._export_chrome(fname)
        print(f"[profiler] chrome trace written to {fname}")
    return handler


def load_profiler_result(filename: str):
    with open(filename) as f:
        return json.load(f)


class Profiler:
    def __init__(self, targets: Optional[Iterable] = None, scheduler=None,
                 on_trace_ready=None, record_shapes=False, profile_memory=False,
                 timer_only=False, emit_nvtx=False, custom_device_types=None,
                 with_flops=False, log_dir="profiler_log"):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(*scheduler) if scheduler else (lambda step: ProfilerState.RECORD))
        self._on_trace_ready = on_trace_ready
        self._step = 0
        self._timer_only = timer_only
        self._log_dir = log_dir
        self._jax_dir = None
        self._state = ProfilerState.CLOSED

    def start(self):
        global _recording, _events
        _events = deque(maxlen=HOST_EVENT_CAP)
        _recording = True
        self._state = self._scheduler(self._step)
        if not self._timer_only:
            try:
                import jax.profiler
                self._jax_dir = os.path.join(self._log_dir, f"jaxtrace_{int(time.time())}")
                jax.profiler.start_trace(self._jax_dir)
            # tpu-lint: disable=TPL006 -- device capture is best-effort: ANY backend failure must degrade to host-only tracing, not kill the run
            except Exception:
                self._jax_dir = None

    def stop(self):
        global _recording
        _recording = False
        if self._jax_dir is not None:
            try:
                import jax.profiler
                jax.profiler.stop_trace()
            # tpu-lint: disable=TPL006 -- stop must mirror the best-effort start: a capture that failed to open raises here, host spans still flush
            except Exception:
                pass
            self._jax_dir = None
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        self._step += 1
        self._state = self._scheduler(self._step)

    def step_info(self, unit=None):
        return f"step {self._step}"

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def _export_chrome(self, fname):
        dump_chrome_trace(fname)

    def export(self, path, format="json"):
        self._export_chrome(path)

    def summary(self, sorted_by=SortedKeys.CPUTotal, op_detail=True, thread_sep=False,
                time_unit="ms"):
        from collections import defaultdict
        agg = defaultdict(lambda: [0, 0.0])
        for e in _events:
            agg[e.name][0] += 1
            agg[e.name][1] += (e.end - e.start) / 1e6
        lines = [f"{'name':40s} {'calls':>8s} {'total(ms)':>12s}"]
        for name, (calls, total) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
            lines.append(f"{name[:40]:40s} {calls:8d} {total:12.3f}")
        table = "\n".join(lines)
        print(table)
        return table
