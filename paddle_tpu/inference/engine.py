"""Continuous-batching LLM serving engine.

Reference lineage: the reference repo serves via `fluid/inference`'s
AnalysisPredictor + PaddleNLP `generation` — a one-shot, whole-batch API.  For
"heavy traffic from millions of users" (ROADMAP north star) that shape is
wrong: every (batch, prompt_len, max_new) combination compiles a fresh
program, cache memory is dense `B x max_seq_len`, and a long request blocks
the batch.  This engine follows vLLM's paged KV cache (Kwon et al., SOSP 2023)
and Orca's iteration-level scheduling (Yu et al., OSDI 2022), under the same
"one jitted step, static shapes" discipline as the pretraining hot loop:

- **Paged KV cache** — one static pool of `[num_pages, page_size, KVH, hd]`
  pages per layer (`models.gpt.init_paged_cache`) + per-slot page tables
  (`inference.cache.PagedKVCache`): memory scales with live tokens, pages
  recycle as requests retire.
- **Slot-indexed decode** — ONE compiled decode program of fixed batch
  `num_slots` (`models.gpt.decode_step_paged`) serves a churning request set;
  retired slots are refilled without recompiling.
- **Prefix cache** (vLLM copy-on-write page sharing) — prompt pages are
  content-hashed at page granularity as their KV lands; admission maps the
  longest cached page-aligned prefix read-only into the new slot's table
  (refcount++), COW-copies a matched partial page (one jitted page-copy
  executable), and only prefills the uncached tail.  Retired prefixes stay
  matchable until LRU-evicted under pool pressure.
- **Chunked prefill** (Sarathi-Serve, Agrawal et al. OSDI 2024) — prompts
  prefill in fixed-size chunks through ONE compiled chunk executable
  (`models.gpt.prefill_chunk_paged`, any q_offset), and `step()` interleaves
  at most one chunk with each decode iteration: a 4k-token prompt no longer
  stalls every decode slot for a whole bucket-padded pass, and the prefill
  program count collapses from #buckets to <= 2 (to 0 under the default
  fused step, where the chunk rides the fused batch).  The legacy bucketed
  one-shot path (`prefill_paged`, power-of-2 buckets) remains the default for
  uncached prompts when `prefill_chunk=None`.
- **Speculative decoding** (Leviathan et al. 2023; prompt-lookup drafting a la
  vLLM) — `spec_len=K` breaks the one-token-per-step decode bound: a pluggable
  `DraftProposer` (default: n-gram self-drafting from the slot's own
  prompt+generated history, `inference.spec.NgramProposer`) guesses up to K
  continuation tokens per slot, ONE fixed-shape verify executable
  (`models.gpt.verify_step_paged`) scores all K+1 positions through the same
  paged attention, and greedy longest-prefix acceptance emits 1..K+1 tokens
  with output exactly identical to vanilla decode whenever the verify and
  decode executables agree at argmax — guaranteed at matching kernel
  numerics (asserted token-exact on CPU in tests; under TPU bf16 matmuls a
  near-tie could in principle resolve differently between the two programs,
  still a valid greedy decode of the model).  Rejected candidates roll
  back as a per-slot length decrement (their KV is stale garbage inside the
  slot's own reserved pages, overwritten on reuse); slots with no draft ride
  at valid=1 (plain decode).  Under the default fused step the verify lane
  is part of the ONE fused program (decode-side count: 1); with `fuse=False`
  it is its own executable and sampled slots fall back to vanilla decode in
  the same iteration (decode-side count: 2).
- **Scheduler** — each `step()` admits queued requests into free slots
  (reservation-based page admission with prefix matching), advances at most
  one prefill chunk, runs one decode iteration over all fully-prefilled
  slots, and retires finished sequences (EOS or max_new_tokens), returning
  their pages to the refcounted pool.
- **One-dispatch fused step** (default, `fuse=True`; the reference's
  single-graph `AnalysisPredictor::ZeroCopyRun` step + true Sarathi
  piggybacking) — the steady-state step dispatches exactly ONE fixed-shape
  program (`models.gpt.serve_step_paged`): vanilla decode slots ride at
  valid=1, spec-verify slots at valid=1+K, and the interleaved prefill chunk
  rides the SAME batch at valid=chunk_len (instead of its own program), with
  per-slot mode implied by (q_offset, valid, page-table row).  Greedy argmax,
  temperature sampling (the shared `gpt.sample_token` split-key discipline)
  and the spec longest-prefix accept scan all run inside the program, so the
  per-step host fetch is a `[B, K+1] + [B]` int32 token/accept buffer —
  ~3 orders of magnitude smaller than `[B, V]` logits — and the decode-side
  compiled-program count is ONE.  `fuse=False` keeps the legacy
  three-program step (decode + chunk + verify, host-side sampling) as the
  A/B baseline (`bench_serve.py --no-fuse`).
- **Double-buffered scheduling** (`double_buffer=True`, fused mode only) —
  the fused dispatch returns un-synced: the host finishes its step-n
  bookkeeping and the caller's loop while the device computes, and the token
  fetch for step n happens at the TOP of step n+1 inside the
  `engine.sample.sync` span (by which time the result is usually ready, so
  the sync is off the critical path).  Host scheduler state (lengths, page
  tables, EOS/finish) is updated at harvest time, one step after dispatch;
  `abort()` harvests the in-flight batch first so bookkeeping stays exact.
  In-flight KV writes of a just-aborted slot are safe: the page pool threads
  through every dispatch as a donated buffer, so device writes are program-
  ordered — a page recycled to a new request is rewritten by the new owner's
  prefill before its attention can read any position the stale write
  touched.
- **Multi-chip serving** (vLLM's Megatron-style tensor parallelism) —
  `mp=N` shards the model over N chips: Megatron serving params placed once
  at init (`parallel.hybrid.serving_param_specs`), page pool sharded on its
  KVH axis (each chip holds kv_heads/mp heads of every page), paged
  attention per-chip on the local head slice.  The scheduler and the cache
  manager above are mp-oblivious — page tables/lengths/refcounts stay
  replicated host state — and greedy outputs are token-identical to
  single-chip serving.  Executables are AOT-compiled under mp (`_AotCache`)
  so the per-mesh-config program budget stays exact.

- **Observability** (Orca/vLLM-style serving metrics over the repo's own
  profiler subsystem) — every engine counter lives in a
  `inference.metrics.MetricsRegistry` (`engine.metrics`): Prometheus text
  exposition via `metrics.to_prometheus()`, JSON via `metrics.snapshot()`,
  and the flat `stats()` dict unchanged on top.  Each request is stamped at
  enqueue/admission/first-token/finish, feeding queue-time, TTFT, TPOT and
  e2e-latency histograms plus a per-request `RequestOutput.metrics` record
  (abort and prefix-hit paths included).  `step()` appends one record per
  iteration to a bounded ring (`step_trace()`): decode-batch occupancy,
  chunk interleave, verify dispatches, tokens emitted, page-pool levels —
  the victim-selection signal the ROADMAP's preemption work needs.
  `engine.trace(dir)` wraps a serving window in `profiler.RecordEvent` spans
  around the host phases (admit, chunk dispatch, proposer scan, verify/decode
  dispatch, acceptance, sample sync), exports them as a chrome trace next to
  the step timeline and a metrics dump, and starts/stops a `jax.profiler`
  device capture when available.  Instrumentation is host-only: zero new
  compiled programs, spans skipped entirely unless a trace is recording.

- **Health & perf signals** (the router-grade signal plane over the
  telemetry above) — sliding-window rates (`inference.metrics.RateWindow`,
  sampled once per step) derive tokens/s, admits/s, preemptions/s,
  timeouts/s and rejects/s over ~10s/1m/5m from the engine counters,
  exposed as pull gauges, `stats()["rates"]` and the Prometheus exposition;
  multi-window SLO burn rates over the deadline-attainment account fold
  with pool pressure, admission saturation and steady-state recompile
  anomalies into `health()` / the `engine_health` gauge
  (ok/degraded/overloaded against `analysis.registry.SERVE_SLO`, served by
  the obs server's ``/healthz`` with 200/503 semantics, fleet-merged
  worst-of); and the static roofline prediction goes live — `warm_decode()`
  traces `engine_step_cost(...).predicted_ms` once (abstract, zero extra
  dispatches or executables), steady-state step times feed an EWMA
  `measured_step_ms` gauge, and `roofline_drift` (measured/predicted) plus
  a drift-band alert counter and a `steady_state_recompiles` anomaly
  counter surface silent perf regressions while they happen.

- **Oversubscribed admission** (vLLM preempt-then-swap-or-recompute, Kwon et
  al. §4.3, over the Sarathi chunked-prefill machinery) —
  `admission="optimistic"` admits on the PROMPT footprint only and grows a
  slot's pages token-granularly as decode proceeds (`PagedKVCache.grow`), so
  live tokens — not worst-case `prompt + max_new_tokens` reservations —
  bound concurrency.  When a growth allocation fails, the engine preempts:
  victims picked by (priority, pages-held, progress), the in-flight
  double-buffered batch harvested first (the TPL007 discipline holds by
  construction: growth runs after the step-top harvest), then either
  **recompute** — the victim's pages are released and it re-queues at the
  head with prompt+generated replayed as a longer prompt through the prefix
  cache and chunked prefill — or **swap** (`preempt="swap"`): its pages are
  gathered into a standalone device buffer (`models.gpt.swap_out_pages`, ONE
  fixed-shape executable padded to the slot capacity), the d2h fetch
  overlapped against the next decode dispatch, content parked in a bounded
  host-side numpy pool (`swap_pool_pages`, the fourth `swapped` page
  partition in `PagedKVCache.check_invariants`), and restored by one h2d
  scatter on re-admission (`swap_in_pages`) — no prefill replay at all.
  Greedy outputs are byte-identical preempted-vs-undisturbed: recompute
  replays land on the same chunk/verify logits parity the prefix cache
  already guarantees, and swap restores bit-exact KV.  Requests whose
  worst-case footprint can never fit the pool are rejected at `add_request`
  (`finish_reason="rejected"`) instead of wedging the queue head; a
  per-request `deadline_s` retires overdue work as
  `finish_reason="timeout"`; and an injectable `inference.faults.FaultPlan`
  forces pool pressure / failing swap copies / clock skew so tests can drive
  every preempt interleaving deterministically.

- **KV tiering** (ROADMAP item 3: the swap pool generalized from a
  preemption escape hatch into a capacity tier; the serving-side analogue of
  the reference's save/load_inference_model persistence path) — with
  `kv_tier=True` (default), prefix-cache pages evicted under pool pressure
  spill device -> host instead of being dropped: `PagedKVCache._evict`
  routes them through the SAME fixed-shape `swap_out_pages` gather the
  preemption swap uses (d2h overlapped with the next dispatch via
  `_pending_d2h`), parking the content in a `HostKVTier` under the UNIFIED
  host-pool budget (`swap_pool_pages`, JXP009) shared with swap parking —
  and admission maps a prefix hit from ANY tier: a later request whose
  prefix lives on host (a returning chat session re-submitting its
  conversation) restores it with ONE `swap_in_pages` scatter, collapsing
  TTFT from O(context) prefill to one h2d + scatter.  Over-budget tier
  content cascades to a disk level (`spill_dir=`) or drops, oldest first;
  failed copies degrade spill -> drop and restore -> re-prefill with zero
  leaked pages.  The prefix index itself is upgraded to a ROLLING-HASH
  partial-page index: a prompt sharing only a partial tail of any cached
  page COW-copies (or tier-scatters) the matched fraction and prefills only
  the true remainder.  Zero new executables: spill/restore reuse the two
  swap programs.

`bench_serve.py` replays a Poisson request stream through this engine and
reports decode tokens/s/chip, TTFT percentiles, prefix-cache hit rate,
accepted tokens per verify step, compiled-program counts and — under
`--oversubscribe F` — preemptions/step, the swap-vs-recompute split and
goodput vs an unpressured replay.
"""
from __future__ import annotations

import contextlib
import dataclasses
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.registry import SERVE_SLO
from ..models import gpt as gpt_mod
from ..profiler import profiler as _prof
from .cache import PagedKVCache
from .faults import FaultInjected, FaultPlan
from .health import HEALTH_CODES, evaluate_engine_health
from .metrics import MetricsRegistry
from .spec import DraftProposer, NgramProposer
from .tracing import RequestTrace

# measured-step EWMA smoothing: ~the last 10 busy steps dominate, so the
# drift gauge reacts inside a scrape interval without tracking single-step
# scheduler noise
_EWMA_ALPHA = 0.2


@dataclasses.dataclass(eq=False)
class Request:
    """One generation request: prompt token ids + a decode budget.

    temperature=None inherits the engine's sampling mode; 0.0 forces the
    greedy fast path for this request (argmax, PRNG-key independent) even on
    a sampling engine.  priority orders preemption victims (LOWER priorities
    are preempted first; default 0); deadline is the absolute engine-clock
    instant past which the request is retired as finish_reason="timeout".
    eq=False: identity comparison only — the generated __eq__ would compare
    numpy prompts, whose truth value is ambiguous."""
    prompt: np.ndarray
    max_new_tokens: int = 16
    request_id: int = -1
    t_enqueue: float = 0.0
    temperature: Optional[float] = None
    priority: int = 0
    deadline: Optional[float] = None


@dataclasses.dataclass
class RequestMetrics:
    """Wall-clock lifecycle of one request, stamped with the engine clock
    (injectable, monotonic — absolute fields are engine-clock readings, not
    epoch time).  Answers "why was this request slow" after the fact: a large
    `queue_s` is admission pressure (pages or slots), a large `ttft_s` with a
    small `queue_s` is prefill cost, a large `tpot_s` is decode contention.
    Stage stamps are None for stages the request never reached (an abort
    while queued has only t_enqueue/t_finish)."""
    t_enqueue: float
    t_admit: Optional[float] = None         # popped from the queue into a slot
    t_first_token: Optional[float] = None   # joined the decode set
    t_finish: Optional[float] = None        # retired (stop/length/abort)
    queue_s: Optional[float] = None         # t_admit - t_enqueue
    ttft_s: Optional[float] = None          # t_first_token - t_enqueue
    tpot_s: Optional[float] = None          # decode time per token after first
    e2e_s: Optional[float] = None           # t_finish - t_enqueue
    cached_tokens: int = 0                  # prompt tokens from the prefix cache
    n_generated: int = 0
    preemptions: int = 0                    # times this request was preempted


@dataclasses.dataclass
class RequestOutput:
    request_id: int
    prompt: np.ndarray
    token_ids: List[int]            # generated tokens (prompt excluded)
    finish_reason: str              # "stop" (EOS) | "length" (budget) |
                                    # "abort" | "timeout" (deadline) |
                                    # "rejected" (footprint can never fit)
    cached_tokens: int = 0          # prompt tokens served from the prefix cache
    ttft_s: Optional[float] = None  # enqueue -> first generated token
    metrics: Optional[RequestMetrics] = None    # full lifecycle record
    trace: Optional[RequestTrace] = None        # structured event timeline
                                                # (None with tracing off)

    @property
    def tokens(self) -> np.ndarray:
        """prompt + generated, the `generate()`-compatible view.  Both inputs
        are host data by construction (add_request normalizes the prompt to
        numpy; token_ids are Python ints synced during step()), so these
        np.asarray calls never touch the device."""
        return np.concatenate(
            [np.asarray(self.prompt, np.int64), np.asarray(self.token_ids,
                                                           np.int64)])


@dataclasses.dataclass
class _Running:
    request: Request
    slot: int
    generated: List[int]
    cached_tokens: int = 0
    ttft_s: Optional[float] = None
    greedy: bool = True             # resolved request temperature == 0.0
    spec_zero_streak: int = 0       # consecutive verify events accepting 0
    spec_off: bool = False          # adaptive back-off: stop drafting


@dataclasses.dataclass
class _Prefilling:
    """A slot whose prompt KV is still landing: `filled` prompt tokens are in
    pages (prefix-cache hits + completed chunks); the slot joins the decode
    set only once filled == len(prompt).  `prompt` is the EFFECTIVE prompt
    being prefilled — for a preempted request resuming via recompute it is
    the original prompt + the tokens in `prior` (generation already banked),
    replayed as one longer prompt; `ttft`/`spec_off`/`streak` carry the
    pre-preemption state back into the decode set."""
    request: Request
    slot: int
    filled: int
    cached_tokens: int
    prompt: np.ndarray = None
    prior: Optional[List[int]] = None
    ttft: Optional[float] = None
    spec_off: bool = False
    streak: int = 0


def _pow2_buckets(lo: int, hi: int) -> List[int]:
    out = []
    b = lo
    while b <= hi:
        out.append(b)
        b *= 2
    return out


class _NullSpan:
    """Stand-in for `profiler.RecordEvent` when nothing is recording: the
    decode loop enters a span per host phase per step, so the off state must
    cost one attribute read and an empty context manager, not a
    perf_counter_ns + TraceAnnotation pair."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()

# Host-phase span names `engine.trace()` emits into the chrome trace — one
# tuple so tests and dashboards don't chase string literals through the
# scheduler.  admit covers prefix matching + reservation (+ the one-shot
# bucketed prefill when taken synchronously); dispatch spans end when the
# async call returns, sample/accept spans contain the blocking device sync.
# The fused step (default) dispatches through engine.fused.dispatch; the
# decode/verify/prefill dispatch spans belong to the legacy fuse=False path
# (prefill.dispatch also covers the bucketed cold path in fused mode).
ENGINE_SPANS = (
    "engine.step",
    "engine.admit",
    "engine.prefill.dispatch",
    "engine.spec.propose",
    "engine.fused.dispatch",
    "engine.verify.dispatch",
    "engine.spec.accept",
    "engine.decode.dispatch",
    "engine.sample.sync",
    "engine.swap.d2h",
    "engine.swap.h2d",
)


class _AotCache:
    """`jax.jit` replacement for the tensor-parallel serving path: one
    `lower().compile()` per input signature (shape/dtype of every leaf),
    cached here.

    Why not plain jit: with donated, committed-sharded inputs (the mp pool),
    jit's two dispatch layers (per-function fastpath + eval-path global cache)
    each build the SAME program once — every serving executable showed two
    XLA compilations and two cache entries for one program, which both wastes
    a warmup compile per program and breaks the compiled-program budget that
    `tools/check_program_count.py` enforces.  AOT-compiling keeps the program
    set exact: `_cache_size()` is the number of DISTINCT programs, the number
    the budget is about.  Inputs whose sharding diverges from the compiled
    signature fail loudly instead of recompiling — under mp every input is
    either host data (replicated) or pinned by the engine, so divergence is a
    bug, not traffic.

    skip_args: leading args excluded from the dispatch key — the params
    pytree (placed once at init, its shapes can never change) would otherwise
    be re-flattened into hundreds of (shape, dtype) tuples on every decode
    dispatch."""

    def __init__(self, fn, donate_argnums, skip_args=0):
        self._jit = jax.jit(fn, donate_argnums=donate_argnums)
        self._skip = skip_args
        self._cache: Dict = {}

    def __call__(self, *args):
        key = tuple((x.shape, str(x.dtype))
                    for x in jax.tree_util.tree_leaves(args[self._skip:]))
        exe = self._cache.get(key)
        if exe is None:
            exe = self._jit.lower(*args).compile()
            self._cache[key] = exe
        return exe(*args)

    def _cache_size(self) -> int:
        return len(self._cache)


class LLMEngine:
    """Continuous-batching serving engine over the functional GPT core.

    params/config: the `models.gpt` pytree + GPTConfig.  `num_slots` is the
    fixed decode batch; `num_pages`/`page_size` size the KV pool (default pool
    is half of the dense `num_slots * max_model_len` footprint — the paged
    cache's whole point is that this still serves full-length traffic as long
    as *live* tokens fit).  Greedy by default; temperature/top_k compile the
    sampling variant of the same executables.

    `prefix_cache=True` shares prompt pages across requests copy-on-write;
    `prefill_chunk=N` switches prompt processing from the bucketed one-shot
    ladder to N-token chunks interleaved one-per-step with decode.  Both are
    scheduler-level: the decode executable, page pool and table shapes are
    identical in every mode.  `prefill_chunk="auto"` picks the chunk width
    adaptively: `spec_len + 1` (the fused program is `max(spec_len+1,
    chunk)` tokens wide, so a wider chunk pads every decode row), or one
    page when spec is off.

    `spec_len=K` (> 0) enables speculative decoding: `draft_proposer`
    (default `NgramProposer`) guesses up to K continuation tokens per greedy
    slot each iteration, one fixed-shape verify executable scores K+1
    positions, and greedy longest-prefix acceptance emits 1..K+1 tokens per
    step with exact vanilla-decode token parity.  Drafting applies only to
    greedy slots — acceptance needs a deterministic pick — so sampled slots
    keep the vanilla decode program.  `spec_backoff_window=W` (adaptive
    spec_len, 0 disables): a slot whose drafts go W consecutive verify events
    without a single accepted token stops being drafted for — it skips the
    proposer scan and rides verify at valid=1 (`stats()["spec_backoffs"]`).

    `fuse=True` (default) collapses the steady-state step to ONE fixed-shape
    dispatch with on-device sampling/acceptance (`gpt.serve_step_paged`): a
    busy step's decode slots, verify slots and the interleaved prefill chunk
    share one `[num_slots, max(spec_len+1, prefill_chunk)]` batch, and the
    host fetches a small int token/accept buffer instead of `[B, V]` logits.
    `double_buffer=True` (default in fused mode) makes the dispatch return
    un-synced, moving the token fetch for step *n* to the top of step *n+1*
    (inside the `engine.sample.sync` span) so the device computes while the
    host schedules — finishes are then observed one `step()` later than in
    synchronous mode, which `run()`/`has_work` account for.  `fuse=False` is
    the legacy three-program step (`bench_serve.py --no-fuse`), byte-exact
    greedy-parity with the fused path.

    Observability: `engine.metrics` is the metrics registry (counters,
    page/queue gauges, latency histograms; `to_prometheus()` for scraping),
    `stats()` the flat dict benches consume, `step_trace()` the per-iteration
    ring timeline (`trace_ring` entries), and `engine.trace(dir)` a capture
    window writing chrome-trace + timeline + metrics dumps.  `clock` injects
    the monotonic clock behind every lifecycle stamp (default
    `time.perf_counter`) so tests drive deterministic latencies.

    Overload behavior: `admission="optimistic"` admits on the prompt
    footprint only and grows pages token-granularly as decode proceeds —
    live-token capacity, not worst-case reservations, bounds concurrency.
    On pool pressure (a failed growth) the engine preempts victims — lowest
    `priority` first, then most pages held, least progress, youngest —
    and either releases + re-queues them for recompute (prompt+generated
    replayed as a longer prompt through the prefix cache; the default) or
    swaps their KV pages to a bounded host-side pool (`preempt="swap"`,
    `swap_pool_pages` cap) restored by one h2d scatter on re-admission.
    Greedy outputs stay byte-identical preempted-vs-undisturbed.
    `admission="reservation"` (default) keeps the PR-1 full-footprint
    reservation discipline — no growth, no preemption.  Per-request
    `deadline_s` retires overdue work as `finish_reason="timeout"`; a
    request whose `prompt + max_new_tokens` footprint exceeds the whole pool
    is rejected at `add_request` (`finish_reason="rejected"`) instead of
    wedging the queue head.  `fault_plan` injects deterministic pool
    pressure / swap-copy failures / clock skew (tests only; see
    `inference.faults.FaultPlan`).

    KV tiering: `kv_tier=True` (default; needs the prefix cache and a
    positive `swap_pool_pages`) spills LRU-evicted prefix pages to a host
    tier under the unified host-pool budget instead of dropping them, and
    admission restores a matched prefix from host (or the optional
    `spill_dir=` disk level) with one `swap_in_pages` scatter — a
    returning session skips its re-prefill entirely.  `kv_tier=False`
    restores the PR-10 drop-on-evict behavior (`bench_serve.py
    --no-kv-tier`).

    Quantized serving: `weight_dtype="int8"` PTQ-quantizes the serving
    matmul weights once at init (symmetric per-channel,
    `quantization.serving.quantize_serving_params`; dequant rides per block
    inside the existing executables — zero program-count change) and
    `kv_dtype="int8"` stores the KV page pool as int8 pages + per-token
    scale lanes, quantized at every in-program write and dequantized per
    page on read inside the paged-attention kernels.  Both default off and
    the fp engine is byte-identical to a quantization-free build; the
    quantized engine keeps every internal parity bar (fused/mp/preempt)
    against itself, while outputs vs the fp engine are a top-1 agreement
    RATE (quantization is lossy) reported by `bench_serve.py
    --weight-dtype/--kv-dtype int8`.

    `mp=N` (or an explicit `mesh` with an 'mp' axis) serves tensor-parallel
    over N chips: params are placed ONCE at init in the Megatron serving
    layout (`parallel.hybrid.serving_param_specs` — qkv/fc1 column-, proj/fc2
    row-sharded, embedding/head VOCAB-sharded with the packed qkv permuted
    into the per-partition column layout), the page pool shards on its KVH
    axis (each chip holds kv_heads/mp heads of every page), and the paged
    attention runs per-chip on the local head slice.  The head never
    materializes replicated [B, V] logits: the embed is a masked local
    take + psum, the head matmul produces [.., V/mp] shards, and
    argmax/top-k/sampling merge per-chip (value, global index) pairs on
    device (`models.gpt.sharded_argmax` / `sample_token`).  All scheduler
    state (page tables, lengths, refcounts, prefix index) stays replicated
    host memory — the paging/prefix/COW logic is mp-oblivious — and greedy
    outputs are token-identical to single-chip serving.  Per-mesh-config the
    compiled decode-side program count is unchanged: the ONE fused step
    program (<= 2 with `fuse=False`).
    """

    def __init__(self, params, config: gpt_mod.GPTConfig, *,
                 num_slots: int = 4, page_size: int = 16,
                 num_pages: Optional[int] = None,
                 max_model_len: Optional[int] = None,
                 prefill_buckets: Optional[List[int]] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache: bool = True,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 spec_len: int = 0,
                 draft_proposer: Optional[DraftProposer] = None,
                 spec_backoff_window: int = 8,
                 fuse: bool = True,
                 double_buffer: Optional[bool] = None,
                 admission: str = "reservation",
                 preempt: str = "recompute",
                 swap_pool_pages: Optional[int] = None,
                 kv_tier: bool = True,
                 spill_dir: Optional[str] = None,
                 spill_disk_pages: Optional[int] = None,
                 page_store=None,
                 role: Optional[str] = None,
                 fault_plan: Optional[FaultPlan] = None,
                 weight_dtype: Optional[str] = None,
                 kv_dtype: Optional[str] = None,
                 mesh=None, mp: Optional[int] = None,
                 seed: int = 0,
                 clock: Optional[Callable[[], float]] = None,
                 trace_ring: int = 512,
                 request_tracing: bool = True,
                 trace_retention: Optional[int] = 4096):
        import jax.sharding as jsh

        from ..quantization.serving import (kv_page_bytes,
                                            normalize_quant_dtype,
                                            quantize_serving_params)

        # quantized serving (ref QAT/PTQ deployment form + int8 predictor):
        # weight_dtype="int8" PTQ-quantizes the serving matmul weights ONCE
        # at init (symmetric per-channel; dequant rides inside the existing
        # executables, so the program set is unchanged); kv_dtype="int8"
        # stores the KV page pool as int8 + per-token scale lanes (the
        # paged-attention kernels dequantize per page on read).  Both default
        # OFF — the fp engine is byte-identical to a quantization-free build.
        self.weight_dtype = normalize_quant_dtype(weight_dtype, "weight_dtype")
        self.kv_dtype = normalize_quant_dtype(kv_dtype, "kv_dtype")
        self._kv_page_bytes = kv_page_bytes(config, page_size, self.kv_dtype)
        if self.weight_dtype == "int8":
            # quantization is host numpy; re-place the tree ONCE here so no
            # dispatch ever pays an implicit h2d for a param leaf (the
            # steady-state loop runs under transfer_guard("disallow"))
            params = jax.tree_util.tree_map(
                jnp.asarray, quantize_serving_params(params, config))

        if mp is not None and mp > 1 and mesh is None:
            from ..parallel.hybrid import serving_mesh
            mesh = serving_mesh(mp)
        self.mesh = mesh
        self.mp = int(dict(mesh.shape).get("mp", 1)) if mesh is not None else 1
        if self.mp > 1:
            if config.num_heads % self.mp or config.kv_heads % self.mp:
                raise ValueError(
                    f"mp={self.mp} must divide num_heads "
                    f"({config.num_heads}) and kv_heads ({config.kv_heads})")
            if config.vocab_size % self.mp:
                raise ValueError(
                    f"mp={self.mp} must divide vocab_size "
                    f"({config.vocab_size}) — the embedding/head shard over "
                    f"the vocab axis")
            # place the serving params ONCE at init: Megatron block layout
            # with the embedding/head VOCAB-SHARDED
            # (parallel.hybrid.serving_param_specs); the packed qkv leaves
            # are permuted into the per-partition column layout first so each
            # chip's shard lands exactly on its own head slices — no
            # replicate→reslice staging at placement or inside the step
            from ..parallel.hybrid import (pack_qkv_partitions,
                                           serving_param_specs)
            params = pack_qkv_partitions(params, config, self.mp)
            specs = serving_param_specs(config, params)
            self._param_shardings = jax.tree_util.tree_map(
                lambda s: jsh.NamedSharding(mesh, s), specs,
                is_leaf=lambda x: isinstance(x, jsh.PartitionSpec))
            params = jax.device_put(params, self._param_shardings)
            # page pool sharded on the KVH axis: every chip holds
            # kv_heads/mp heads of EVERY page, so the host-side page tables /
            # lengths / refcounts (inference.cache) stay replicated and the
            # prefix-cache/COW/eviction logic is mp-oblivious.  NOTE the spec
            # leaves the trailing hd dim implicit: executables re-derive the
            # output sharding in this normalized form, and a trailing-None
            # variant hashes as a DIFFERENT executable-cache key (one silent
            # recompile per jit on the second call)
            self._pool_sharding = jsh.NamedSharding(
                mesh, jsh.PartitionSpec(None, None, None, "mp"))
            self._repl_sharding = jsh.NamedSharding(mesh, jsh.PartitionSpec())
        else:
            self._param_shardings = None
            self._pool_sharding = None
            self._repl_sharding = None
        self.params = params
        self.config = config
        self.eos_token_id = eos_token_id
        max_model_len = max_model_len or config.max_seq_len
        if max_model_len % page_size:
            raise ValueError("max_model_len must be a multiple of page_size")
        if not config.use_rope and max_model_len > config.max_seq_len:
            # learned positions: jnp.take clamps past wpe's last row, which
            # would be silently wrong — generate() raises here too
            raise ValueError(
                f"max_model_len {max_model_len} exceeds max_seq_len "
                f"{config.max_seq_len} (learned positions)")
        self.max_model_len = max_model_len
        max_pages_per_slot = max_model_len // page_size
        if num_pages is None:
            # default: half the dense footprint (+ the null page)
            num_pages = max(2, num_slots * max_pages_per_slot // 2 + 1)
        if prefill_buckets is None:
            prefill_buckets = _pow2_buckets(page_size, max_model_len)
            if not prefill_buckets or prefill_buckets[-1] != max_model_len:
                # non-power-of-2 max_model_len: cover the top tokens too
                prefill_buckets.append(max_model_len)
        self.buckets = sorted(prefill_buckets)
        for b in self.buckets:
            if b % page_size or b > max_model_len:
                raise ValueError(f"bucket {b} incompatible with page_size "
                                 f"{page_size} / max_model_len {max_model_len}")
        if spec_len < 0:
            raise ValueError(f"spec_len must be >= 0, got {spec_len}")
        if prefill_chunk == "auto":
            # adaptive chunk width: the fused program's token width is
            # max(spec_len+1, prefill_chunk), so any chunk wider than the
            # verify lane pads EVERY decode row of EVERY fused dispatch with
            # dead positions.  spec_len+1 makes the chunk ride the fused
            # batch at exactly the width verify already needs (zero decode
            # padding); with spec off there is no verify lane to hide
            # behind, so fall back to one page per chunk — page-granular KV
            # writes, and a bounded 1-page cost on decode rows.
            prefill_chunk = min(spec_len + 1 if spec_len else page_size,
                                max_model_len)
        if prefill_chunk is not None and not 1 <= prefill_chunk <= max_model_len:
            raise ValueError(f"prefill_chunk {prefill_chunk} outside "
                             f"[1, {max_model_len}]")
        self.prefill_chunk = prefill_chunk
        self.chunked = prefill_chunk is not None
        # chunk width also serves prefix-hit tails in bucketed mode, where the
        # largest bucket bounds any tail in one call
        self._chunk = prefill_chunk if self.chunked else self.buckets[-1]
        self.prefix_cache = prefix_cache
        if spec_len and spec_len + 1 > max_model_len:
            raise ValueError(f"spec_len {spec_len} + 1 exceeds max_model_len")
        self.spec_len = spec_len
        self.proposer = (draft_proposer or NgramProposer()) if spec_len \
            else draft_proposer
        if spec_backoff_window < 0:
            raise ValueError(
                f"spec_backoff_window must be >= 0, got {spec_backoff_window}")
        self.spec_backoff_window = spec_backoff_window
        # fused one-dispatch step (see module docstring): the program's token
        # width covers the widest lane that can ride it — K+1 verify rows
        # and, in chunked mode, the prefill chunk (choose prefill_chunk near
        # spec_len+1 to minimize decode-row padding)
        self.fused = bool(fuse)
        self.double_buffer = self.fused and \
            (True if double_buffer is None else bool(double_buffer))
        self._fused_T = max(self.spec_len + 1,
                            prefill_chunk if self.chunked else 1)
        if admission not in ("reservation", "optimistic"):
            raise ValueError(f"admission must be 'reservation' or "
                             f"'optimistic', got {admission!r}")
        if preempt not in ("recompute", "swap"):
            raise ValueError(f"preempt must be 'recompute' or 'swap', "
                             f"got {preempt!r}")
        self.admission = admission
        self.optimistic = admission == "optimistic"
        self.preempt = preempt
        self._faults = fault_plan or FaultPlan()
        self.cache = PagedKVCache(num_pages, page_size, num_slots,
                                  max_pages_per_slot)
        # UNIFIED host pool bound, in pages: preempt="swap" victim parking
        # AND the kv_tier spilled-prefix store share this one ceiling (the
        # JXP009 budget).  Default mirrors the device pool — the host
        # obligation can never exceed what the device could hold
        self.swap_pool_pages = (num_pages - 1) if swap_pool_pages is None \
            else int(swap_pool_pages)
        if self.swap_pool_pages < 0:
            raise ValueError(
                f"swap_pool_pages must be >= 0, got {swap_pool_pages}")
        # KV tiering (ROADMAP item 3): retired prefix-cache pages spill
        # device -> host (-> optional disk via spill_dir) instead of being
        # LRU-dropped, and admission restores a prefix hit from ANY tier
        # with one swap_in_pages scatter — no prefill replay.  Needs the
        # prefix index (the trie keys the tier) and host-pool room.
        self.kv_tier = bool(kv_tier) and prefix_cache and \
            self.swap_pool_pages > 0
        self.spill_dir = spill_dir if self.kv_tier else None
        # disaggregated serving role (ROADMAP item 2): "prefill" engines run
        # admission + chunked prefill and export finished prompts through the
        # tier store; "decode" engines tier-restore them.  None = colocated
        # (the classic engine).  The role changes ROUTING and HEALTH only —
        # every engine keeps the full executable set, so a degraded handoff
        # can always fall back to local re-prefill.
        if role not in (None, "prefill", "decode"):
            raise ValueError(f"role must be 'prefill', 'decode' or None, "
                             f"got {role!r}")
        self.role = role
        self._store_restored_nodes = 0
        if self.kv_tier:
            from .cache import HostKVTier
            self.cache.attach_tier(
                HostKVTier(spill_dir=self.spill_dir,
                           disk_pages=spill_disk_pages,
                           store=page_store),
                self._spill_prefix_nodes)
            # durable-index re-attach: merge any kvindex_* blobs a previous
            # process (or a prefill peer on the same store) published, so a
            # restarted engine's first returning session tier-restores with
            # one scatter instead of re-prefilling
            self._store_restored_nodes = self.cache.load_tier_index()
        # optimistic-admission watermark: global free-page headroom kept back
        # at admission (vLLM's watermark_blocks), ~1% of the pool
        self._watermark = max(1, (self.cache.num_pages - 1) // 100)
        self._pool = gpt_mod.init_paged_cache(config, num_pages, page_size,
                                              kv_dtype=self.kv_dtype)
        if self._pool_sharding is not None:
            self._pool = jax.device_put(
                self._pool, {n: self._pool_sharding for n in self._pool})
        self._queue: deque = deque()
        self._running: Dict[int, _Running] = {}
        self._prefilling: Dict[int, _Prefilling] = {}   # slot -> state, FIFO
        self._free_slots = list(range(num_slots - 1, -1, -1))
        self._ids = itertools.count()
        self._key = jax.random.key(seed)
        if self.mp > 1:
            # commit the key to the mesh (replicated) up front: an uncommitted
            # first-call key is a different executable-cache signature than the
            # committed key every later call carries — one silent recompile
            self._key = jax.device_put(
                self._key, jsh.NamedSharding(mesh, jsh.PartitionSpec()))
        self._outputs: Dict[int, RequestOutput] = {}

        # ---- observability state (all host-side: no executable sees any of
        # this, so the compiled-program budget is untouched) ----------------
        if trace_ring < 1:
            raise ValueError(f"trace_ring must be >= 1, got {trace_ring}")
        if trace_retention is not None and trace_retention < 0:
            raise ValueError(f"trace_retention must be >= 0 or None "
                             f"(unbounded), got {trace_retention}")
        m = MetricsRegistry(namespace="llm_engine",
                            clock=clock or time.perf_counter)
        self.metrics = m
        self._now = m.now
        self._decode_iters = m.counter("decode_iterations",
                                       "decode-side engine iterations")
        self._decode_tokens = m.counter("decode_tokens",
                                        "tokens emitted by decode/verify")
        self._prefill_chunks = m.counter("prefill_chunks",
                                         "chunk-prefill dispatches")
        self._prefilled_tokens = m.counter("prefilled_tokens",
                                           "prompt tokens actually computed")
        self._prefix_cached_tokens = m.counter(
            "prefix_cached_tokens", "prompt tokens served from the cache")
        self._prefix_hit_requests = m.counter(
            "prefix_hit_requests", "requests admitted with a prefix hit")
        self._cow_copies = m.counter("cow_page_copies",
                                     "copy-on-write page copies")
        self._verify_steps = m.counter("verify_steps",
                                       "verify-program dispatches")
        self._spec_events = m.counter(
            "spec_events", "per-slot verify events carrying a draft")
        self._spec_drafted = m.counter("spec_drafted_tokens",
                                       "drafted tokens offered to verify")
        self._spec_accepted = m.counter("spec_accepted_tokens",
                                        "drafted tokens accepted")
        self._spec_emitted = m.counter(
            "spec_emitted_tokens", "accepted + bonus tokens emitted")
        self._spec_backoffs = m.counter(
            "spec_backoffs", "slots that stopped drafting (adaptive back-off)")
        self._finished_requests = m.counter(
            "finished_requests", "requests retired by stop/length")
        self._aborted_requests = m.counter("aborted_requests",
                                           "requests retired by abort()")
        self._preemptions = m.counter(
            "preemptions", "running requests evicted under pool pressure")
        self._preempt_swaps = m.counter(
            "preempt_swaps",
            "preemptions whose KV swap-out d2h completed")
        self._preempt_recomputes = m.counter(
            "preempt_recomputes",
            "preemptions resolved by recompute (incl. degraded swaps)")
        self._swapped_pages_c = m.counter(
            "swapped_pages", "KV pages delivered to the host swap pool")
        self._swap_ms_c = m.counter(
            "swap_ms", "milliseconds spent in swap d2h/h2d copies")
        self._recomputed_tokens = m.counter(
            "recomputed_tokens",
            "prompt tokens re-prefilled because of preemption")
        self._timeouts = m.counter(
            "timeouts", "requests retired by deadline expiry")
        self._rejected_requests = m.counter(
            "rejected_requests",
            "requests rejected at intake (footprint can never fit)")
        self._intake_swap_rejects = m.counter(
            "intake_swap_rejects",
            "intake rejections because the worst-case footprint exceeds the "
            "host swap pool (the request could never be parked)")
        # KV-tier surface: spill/restore traffic between the device prefix
        # cache and the host (+disk) tier, plus the rolling-hash partial-
        # page index's hit counter
        self._tier_spills = m.counter(
            "kv_tier_spills",
            "evicted prefix pages delivered to the host KV tier (counted "
            "at d2h success, like swapped_pages)")
        self._tier_restores = m.counter(
            "kv_tier_restores",
            "tier restore scatters (one per admission resuming >= 1 page "
            "from the host/disk tier)")
        self._tier_restored_tokens = m.counter(
            "kv_tier_restored_tokens",
            "prompt tokens restored from the KV tier instead of re-prefilled")
        self._partial_hits = m.counter(
            "partial_page_hits",
            "admissions whose prefix match ended inside a cached page "
            "(rolling-hash partial index: COW copy or tier scatter of the "
            "matched fraction)")
        # disaggregated handoff surface: prompts a prefill-role engine
        # exported through the shared tier store for a decode peer
        self._handoff_exports = m.counter(
            "kv_handoff_exports",
            "finished prompts exported to the shared tier store for a "
            "decode-role peer")
        self._handoff_pages = m.counter(
            "kv_handoff_pages", "KV pages published to the store by exports")
        self._handoff_tokens = m.counter(
            "kv_handoff_tokens",
            "prompt tokens whose KV a decode peer can restore instead of "
            "re-prefilling")
        # SLO accounting (deadline attainment + per-priority-class goodput):
        # attainment's denominator is EVERY retired deadline-bearing request
        # (timeouts and aborts count as misses there), while the latency
        # histograms keep excluding them — two different questions
        self._deadline_requests = m.counter(
            "deadline_requests",
            "retired requests that carried a deadline (attainment "
            "denominator — timeouts/aborts/rejects included)")
        self._deadline_met = m.counter(
            "deadline_met",
            "deadline-bearing requests that finished (stop/length) on time")
        self._goodput_prio: Dict[int, object] = {}
        self._h_queue = m.histogram("queue_time_seconds",
                                    help="enqueue -> admission into a slot")
        self._h_ttft = m.histogram("ttft_seconds",
                                   help="enqueue -> first generated token")
        self._h_tpot = m.histogram(
            "tpot_seconds", help="decode seconds per token after the first")
        self._h_e2e = m.histogram("e2e_latency_seconds",
                                  help="enqueue -> finish (stop/length only)")
        self._h_step = m.histogram("step_seconds",
                                   help="wall time of one engine step()")
        m.gauge("queued", lambda: len(self._queue), "requests waiting")
        m.gauge("prefilling", lambda: len(self._prefilling),
                "slots mid-prefill")
        m.gauge("running", lambda: len(self._running), "slots decoding")
        m.gauge("kv_pool_bytes", self.kv_pool_bytes,
                "at-rest bytes of the device KV page pool (all lanes)")
        self.cache.attach_metrics(m)
        # ---- health & perf signal plane (all host-side) -------------------
        # windowed rates: sliding-window views over the counters above,
        # sampled once per step() — the router's freshness-weighted signal
        # (a counter answers "since reset", a probe needs "lately")
        self._admitted_requests = m.counter(
            "admitted_requests",
            "requests popped into a slot (recompute resumes included)")
        self._rw_tokens = m.rate_window(
            "tokens_per_sec", lambda: self._decode_tokens.value,
            help="decode tokens emitted per second")
        self._rw_admits = m.rate_window(
            "admits_per_sec", lambda: self._admitted_requests.value,
            help="requests admitted per second")
        self._rw_preemptions = m.rate_window(
            "preemptions_per_sec", lambda: self._preemptions.value,
            help="running requests preempted per second")
        self._rw_timeouts = m.rate_window(
            "timeouts_per_sec", lambda: self._timeouts.value,
            help="requests retired by deadline expiry per second")
        self._rw_rejects = m.rate_window(
            "rejects_per_sec", lambda: self._rejected_requests.value,
            help="requests rejected at intake per second")
        # the stats()["rates"] surface, captured once: registry-owned ring
        # state, independent of the per-signal handles health() evaluates
        self._rate_surface = (self._rw_tokens, self._rw_admits,
                              self._rw_preemptions, self._rw_timeouts,
                              self._rw_rejects)
        # burn-rate inputs: windowed deltas of the SLO account (not exposed
        # as per-window gauges themselves — the burn ratios below are the
        # signal; agg="max" because a burn is a fraction-of-budget ratio)
        self._rw_deadline_req = m.rate_window(
            "deadline_requests_window",
            lambda: self._deadline_requests.value, expose=False)
        self._rw_deadline_met = m.rate_window(
            "deadline_met_window",
            lambda: self._deadline_met.value, expose=False)
        for _lbl, _w in self._rw_deadline_req.windows:
            if _lbl in (SERVE_SLO["burn_window_fast"],
                        SERVE_SLO["burn_window_slow"]):
                m.gauge(f"slo_burn_rate_{_lbl}",
                        (lambda w=_w: self._burn_rate(w)),
                        f"deadline-attainment burn over the trailing {_lbl} "
                        f"(1.0 = consuming the error budget exactly as fast "
                        f"as the SLO allows)", agg="max")
        # live roofline drift: predicted_step_ms traced once at warmup
        # (lazy — never from a scrape), measured EWMA fed by busy steps
        self._predicted_ms: Optional[float] = None
        self._measured_ewma_ms: Optional[float] = None
        self._drift_violation = False
        self._exec_baseline: Optional[int] = None
        self._roofline_alerts = m.counter(
            "roofline_drift_alerts",
            "transitions of roofline_drift out of the declared band")
        self._ss_recompiles = m.counter(
            "steady_state_recompiles",
            "decode-side executable-count growth observed after warm")
        m.gauge("measured_step_ms",
                lambda: self._measured_ewma_ms or 0.0,
                "EWMA wall time of busy engine steps (harvest to harvest)",
                agg="max")
        m.gauge("roofline_drift", self._roofline_drift,
                "measured_step_ms / predicted_step_ms (0 until both exist)",
                agg="max")
        m.gauge("engine_health", self._health_code,
                "health state code: 0 ok, 1 degraded, 2 overloaded "
                "(fleet merge folds worst-of, not sum)", agg="max")
        self._lifecycles: Dict[int, RequestMetrics] = {}
        # per-request tracing (always-on observability plane; request_tracing
        # =False strips both the timelines and the exemplar attachment — the
        # bench's overhead A/B axis).  Live traces move to RequestOutput
        # .trace at retirement, so /requests/<rid> keeps resolving after —
        # for the last `trace_retention` retired requests: a long-running
        # server retires millions, and timelines held forever on the
        # RequestOutput ledger would grow host memory without bound, so the
        # oldest retired trace is dropped (its output keeps its tokens) once
        # the cap is passed.  trace_retention=None retains every timeline.
        self._req_tracing = bool(request_tracing)
        self._traces: Dict[int, RequestTrace] = {}
        self._trace_retention = trace_retention
        self._retired_traced: deque = deque()
        self._step_idx = 0
        self._step_trace: deque = deque(maxlen=trace_ring)
        self._tracing = False

        sample = bool(temperature and temperature > 0.0)
        self._sample = sample
        self._temperature = temperature

        cfg = config
        mesh_ = mesh if self.mp > 1 else None
        pool_sh = self._pool_sharding

        if sample:
            def pick(logits, key, greedy):
                # gpt.sample_token is shared with generate() — parity by
                # construction; the greedy mask routes per-request
                # temperature=0.0 slots through argmax (their output is
                # PRNG-independent; the batch-wide split still advances).
                # Under mp the logits arrive vocab-sharded and both picks
                # run as on-device sharded merges.
                ids, key = gpt_mod.sample_token(logits, key, sample=True,
                                                temperature=temperature,
                                                top_k=top_k, mesh=mesh_)
                greedy_ids = gpt_mod.sharded_argmax(logits, mesh_)
                return jnp.where(greedy, greedy_ids, ids), key
        else:
            def pick(logits, key, greedy):
                # fully greedy engine: argmax, the PRNG key is never consumed
                return gpt_mod.sample_token(logits, key, sample=False,
                                            temperature=temperature,
                                            top_k=top_k, mesh=mesh_)

        def pin_pool(pool):
            # pin the output pool to EXACTLY the committed input sharding (the
            # normalized spec): the donated buffer is reused in place and every
            # call after the first carries an identical executable-cache
            # signature — without the pin, GSPMD-inferred output shardings
            # drift and decode/chunk ping-pong recompiles (4 chunk compiles
            # observed for one engine)
            if pool_sh is None:
                return pool
            return {n: jax.lax.with_sharding_constraint(a, pool_sh)
                    for n, a in pool.items()}

        def decode_impl(params, tokens, pool, table, lengths, key, greedy):
            logits, pool = gpt_mod.decode_step_paged(params, tokens, pool,
                                                     table, lengths, cfg,
                                                     mesh=mesh_)
            nxt, key = pick(logits, key, greedy)
            return nxt, pin_pool(pool), key

        def prefill_impl(params, ids, pool, pages, length, key, greedy):
            logits, pool = gpt_mod.prefill_paged(params, ids, cfg, pool,
                                                 pages, length, mesh=mesh_)
            first, key = pick(logits, key, greedy)
            return first, pin_pool(pool), key

        def chunk_impl(params, ids, pool, table, q_offset, valid, key, greedy):
            logits, pool = gpt_mod.prefill_chunk_paged(params, ids, cfg, pool,
                                                       table, q_offset, valid,
                                                       mesh=mesh_)
            tok, key = pick(logits, key, greedy)
            return tok, pin_pool(pool), key

        def verify_impl(params, tokens, pool, table, lengths, valid):
            # greedy-only lane: acceptance compares argmax at every position,
            # no key threads through (spec parity requires determinism)
            logits, pool = gpt_mod.verify_step_paged(params, tokens, pool,
                                                     table, lengths, valid,
                                                     cfg, mesh=mesh_)
            return gpt_mod.sharded_argmax(logits, mesh_), pin_pool(pool)

        temp_, topk_ = temperature, top_k

        def fused_impl(params, tokens, pool, table, q_offset, valid, key,
                       greedy):
            # THE one-dispatch step: decode/verify/chunk slots in one batch,
            # sampling + accept scan on device, host-visible output O(B*K)
            # ints (never [B, V] logits — guarded by the JXP005 jaxpr audit)
            out, accept, pool, key = gpt_mod.serve_step_paged(
                params, tokens, pool, table, q_offset, valid, cfg, key=key,
                greedy=greedy, sample=sample, temperature=temp_, top_k=topk_,
                mesh=mesh_)
            return out, accept, pin_pool(pool), key

        def copy_impl(pool, src, dst):
            # COW page copy: one [page, KVH, hd] slab per layer, src -> dst
            # (page axis is unsharded, so the copy is collective-free under mp)
            return pin_pool({n: a.at[:, dst].set(a[:, src])
                             for n, a in pool.items()})

        def swap_out_impl(pool, ids):
            # preemption swap-out: gather the victim's pages into a fresh
            # buffer (pool NOT donated — it stays live) so the d2h fetch can
            # overlap the next decode dispatch; ids padded to the slot
            # capacity keep this ONE fixed-shape executable.  The pin keeps
            # the gathered buffer in the pool's KVH-sharded layout under mp
            # (the gather stays chip-local; the host fetch assembles).
            return pin_pool(gpt_mod.swap_out_pages(pool, ids))

        def swap_in_impl(pool, ids, data):
            # preemption swap-in: scatter the parked KV back into freshly
            # allocated pages, in place (`data` is the pool-keyed staging
            # dict — int8 pools restore their scale lanes in the same
            # dispatch).  Only the pool is donated — the staging uploads
            # cannot alias the pool-shaped output, so donating them would
            # just burn a "donation unusable" warning per swap-in
            return pin_pool(gpt_mod.swap_in_pages(pool, ids, data))

        # pool donated: each step updates it in place instead of copying the
        # whole page pool every iteration.  The mp path AOT-compiles (see
        # _AotCache) so the program set stays exact under committed-sharded
        # donated inputs; single-chip keeps plain jit.
        jit_ = (lambda fn, donate, skip=0: _AotCache(fn, donate, skip)) \
            if self.mp > 1 \
            else (lambda fn, donate, skip=0:
                  jax.jit(fn, donate_argnums=donate))
        if self.fused:
            # the fused program IS the decode-side executable; the legacy
            # verify program is never built (decode-side count: exactly 1),
            # and in chunked mode the chunk rides the fused batch so the
            # standalone chunk program goes too.  Bucketed mode keeps the
            # chunk program for prefix-hit tails (cold path, like the
            # bucketed one-shot prefill).
            self._decode_fn = jit_(fused_impl, (2,), 1)  # skip=1: params static
            self._verify_fn = None
            self._chunk_fn = None if self.chunked else jit_(chunk_impl, (2,), 1)
        else:
            self._decode_fn = jit_(decode_impl, (2,), 1)
            self._verify_fn = jit_(verify_impl, (2,), 1)
            self._chunk_fn = jit_(chunk_impl, (2,), 1)
        self._prefill_fn = jit_(prefill_impl, (2,), 1)
        self._copy_fn = jit_(copy_impl, (0,))
        self._swap_out_fn = jit_(swap_out_impl, ())
        self._swap_in_fn = jit_(swap_in_impl, (0,))
        self._seen_buckets = set()
        self._chunk_used = False
        self._copy_used = False
        self._swap_out_used = False
        self._swap_in_used = False
        self._decode_used = False       # any decode-side dispatch happened
        # preemption/overload state: rid -> resume record ("recompute" keeps
        # the banked generation for the longer-prompt replay; "swap" adds the
        # parked KV, first as un-synced device buffers then host numpy);
        # _pending_d2h holds swap records whose d2h fetch is deferred past
        # the next dispatch; _has_deadlines gates the per-step expiry scan
        self._preempted: Dict[int, Dict[str, object]] = {}
        self._pending_d2h: List[Dict[str, object]] = []
        self._has_deadlines = False
        self._step_preempted = 0
        # double-buffer state: the un-synced result of the last fused
        # dispatch (device arrays + the host metadata to interpret them) and
        # finishes surfaced outside step() (an abort-time harvest)
        self._inflight: Optional[Dict[str, object]] = None
        self._orphan_finished: List[RequestOutput] = []
        self._step_dispatches = 0
        self._step_sync_s = 0.0
        self._step_slots = {"decode": 0, "verify": 0, "chunk": 0}
        # serving-loop surface (front door / fleet): the engine itself is
        # single-threaded by design, so one RLock serializes the background
        # step() loop against submit/cancel/probe/result callers; the
        # condition (same lock) wakes the loop on intake and waiters on
        # every step's outputs
        self._serve_lock = threading.RLock()
        self._serve_cond = threading.Condition(self._serve_lock)
        self._serve_thread: Optional[threading.Thread] = None
        self._serve_stop = False
        self._serve_error: Optional[BaseException] = None
        self.reset_counters()

    def reset_counters(self) -> None:
        """Zero the throughput/prefix counters and latency histograms
        (stats(), not executables) — benches call this after warmup so
        compile-time traffic is excluded.  Also clears the step-trace ring and
        the proposer's drafting telemetry; the `prefix_evictions` int mirrors
        its registry counter so both zero together.

        Contract with an OPEN capture/trace window (audited; see
        tests/test_observability.py::test_reset_counters_mid_trace_window):

        - the chrome-trace host spans live in the profiler's own event
          buffer, which this method never touches — a reset inside an
          `engine.trace(dir)` window does not corrupt ``host_trace.json``;
        - the step-trace ring and `_step_idx` restart at zero, so the
          window's ``step_timeline.json`` holds only post-reset records
          (by design: the same warmup-exclusion semantics as the counters);
        - histogram resets clear their EXEMPLARS with their bucket counts
          (`Histogram.reset`) — the exposition can never carry a stale
          request handle on a bucket whose count says nothing was observed;
        - live per-request timelines (`RequestOutput.trace` /
          ``/requests/<rid>``) are request state, not counters: in-flight
          traces and already-retired outputs survive, so exemplar handles
          attached AFTER the reset keep resolving;
        - the signal plane restarts with the counters it derives from: rate
          windows clear their sample rings (`MetricsRegistry.reset`), the
          measured-step EWMA and the steady-state recompile baseline
          re-seed on the next busy step (warmup compiles stay excluded the
          same way warmup counter traffic does).  The static
          `predicted_step_ms` survives — it is a property of the engine's
          shapes, not of any run."""
        self.metrics.reset()
        self.cache.prefix_evictions = 0
        if self.cache._tier is not None:
            # the tier's own event mirrors zero with the registry counters
            # (its CONTENT — parked pages — is cache state and survives,
            # like the prefix index itself)
            self.cache._tier.disk_spills = 0
            self.cache._tier.disk_restores = 0
            self.cache._tier.tier_drops = 0
        getattr(self.proposer, "reset_stats", lambda: None)()
        self._step_idx = 0
        self._step_trace.clear()
        self._measured_ewma_ms = None
        self._drift_violation = False
        self._exec_baseline = None
        # seed every rate ring with (t_reset, 0): events between the reset
        # and the first step-end sample stay countable, and a young window
        # reads exactly events-since-reset / elapsed-since-reset
        self.metrics.sample_rates()

    # ---- request intake ---------------------------------------------------
    def add_request(self, prompt, max_new_tokens: int = 16,
                    temperature: Optional[float] = None,
                    priority: int = 0,
                    deadline_s: Optional[float] = None) -> int:
        """Enqueue one request.  temperature=None inherits the engine's
        sampling mode; 0.0 is the per-request greedy fast path (argmax pick,
        output independent of the PRNG stream — what speculative decoding
        verifies against).  A positive value must equal the engine's compiled
        temperature: the sampling variant is baked into the executables.

        `priority` orders preemption under optimistic admission (lower
        priorities are evicted first; default 0).  `deadline_s` bounds the
        request's total wall time: past `enqueue + deadline_s` it is retired
        with finish_reason="timeout" wherever it is (queued, prefilling,
        decoding, or swapped out).  A request whose worst-case footprint
        (prompt + max_new_tokens) exceeds the whole page pool can NEVER be
        served — it is rejected immediately (finish_reason="rejected",
        output available via outputs/run()) instead of wedging the queue
        head forever while it waits for pages that cannot exist."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        if temperature is not None and temperature < 0.0:
            raise ValueError(f"temperature must be >= 0.0, got {temperature}")
        if temperature is not None and temperature > 0.0:
            if not self._sample:
                raise ValueError(
                    "engine compiled greedy (temperature=0.0) cannot serve "
                    "sampled requests; construct it with temperature > 0")
            if temperature != self._temperature:
                raise ValueError(
                    f"per-request temperature {temperature} != engine "
                    f"temperature {self._temperature}; only the greedy fast "
                    f"path (temperature=0.0) overrides per request")
        if not self.chunked and prompt.size > self.buckets[-1]:
            raise ValueError(f"prompt length {prompt.size} exceeds largest "
                             f"prefill bucket {self.buckets[-1]}")
        total = prompt.size + max_new_tokens
        if total > self.max_model_len:
            raise ValueError(f"prompt + max_new_tokens = {total} exceeds "
                             f"max_model_len {self.max_model_len}")
        if deadline_s is not None and deadline_s <= 0.0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        rid = next(self._ids)
        t = self._now()
        deadline = None if deadline_s is None else t + deadline_s
        req = Request(prompt, max_new_tokens, rid, t, temperature,
                      priority, deadline)
        self._lifecycles[rid] = RequestMetrics(t_enqueue=t)
        if self._req_tracing:
            tr = RequestTrace(rid)
            tr.event(t, "enqueue", prompt_len=int(prompt.size),
                     max_new_tokens=int(max_new_tokens),
                     priority=int(priority),
                     deadline_s=deadline_s)
            self._traces[rid] = tr
        need = self.cache.pages_needed(total)
        if need > self.cache.num_pages - 1:
            # fail fast: even alone on an empty pool this footprint cannot
            # fit — queueing it would wedge the queue head forever in
            # _admit's wait-for-pages path
            self._rejected_requests.inc()
            self._finish_output(req, [], "rejected", 0, None)
            # anchor the reject in the rate rings at its true time (intake
            # runs outside step(), whose sampling would otherwise miss it)
            self.metrics.sample_rates(force=True)
            return rid
        if self.optimistic and self.preempt == "swap" and \
                self.swap_pool_pages > 0 and need > self.swap_pool_pages:
            # swap-pool intake admission (PR-10 follow-on): under swap-mode
            # oversubscription every admitted request is a preemption
            # candidate, and its worst-case footprint counts against the
            # HOST swap-pool budget at intake — a request that could never
            # be parked even in an empty pool would degrade EVERY preemption
            # of it to recompute (swap->recompute thrash), so it is rejected
            # here.  A request that merely finds the pool transiently full
            # queues as usual: parked victims re-queue at the head and drain
            # the pool before fresh work reaches it.  swap_pool_pages=0
            # declares parking disabled (pure recompute) — no gate.
            self._intake_swap_rejects.inc()
            self._rejected_requests.inc()
            self._finish_output(req, [], "rejected", 0, None)
            self.metrics.sample_rates(force=True)
            return rid
        if deadline is not None:
            self._has_deadlines = True
        self._queue.append(req)
        return rid

    def _req_greedy(self, req: Request) -> bool:
        t = req.temperature
        return (not self._sample) if t is None else t <= 0.0

    def abort(self, request_id: int) -> bool:
        """Cancel a queued or in-flight request and free/deref its pages
        immediately (a stuck client no longer leaks its reservation until
        max_new_tokens runs out).  Shared prefix pages are only
        deref-counted; the request lands in the outputs map with
        finish_reason="abort" and whatever tokens it had produced.  Returns
        False when the id is unknown or already finished.

        Under double-buffering the in-flight fused batch is harvested first,
        so the abort sees exact bookkeeping (a request the pending tokens
        just finished is reported as already done, not aborted); requests
        that finish during this harvest surface from the NEXT step() call."""
        if self._inflight is not None:
            self._harvest(self._orphan_finished)
        for i, req in enumerate(self._queue):
            if req.request_id == request_id:
                # del by index, NOT deque.remove: remove's equality scan would
                # run Request.__eq__ against every earlier entry, and numpy
                # prompt comparison has no scalar truth value (it raised for
                # any aborted request not at the head of the queue)
                del self._queue[i]
                rec = self._drop_preempted(request_id)
                if rec is not None:
                    # a preempted request keeps the tokens it had produced
                    self._finish_output(req, list(rec["generated"]), "abort",
                                        rec["cached_tokens"], rec["ttft"])
                else:
                    self._finish_output(req, [], "abort", 0, None)
                return True
        for slot, st in list(self._prefilling.items()):
            if st.request.request_id == request_id:
                del self._prefilling[slot]
                self.cache.release(slot)
                self._free_slots.append(slot)
                # a recompute-resume mid-replay keeps its banked generation
                # (same contract as the queued and timeout paths)
                self._finish_output(st.request, list(st.prior or []),
                                    "abort", st.cached_tokens, st.ttft)
                return True
        for slot, seq in list(self._running.items()):
            if seq.request.request_id == request_id:
                del self._running[slot]
                self.cache.release(slot)
                self._free_slots.append(slot)
                self._finish_output(seq.request, seq.generated, "abort",
                                    seq.cached_tokens, seq.ttft_s)
                return True
        return False

    def _finish_output(self, req: Request, token_ids: List[int], reason: str,
                       cached: int, ttft: Optional[float]) -> RequestOutput:
        """Close the request's lifecycle record and publish the output.
        Latency histograms only see stop/length retirements — an abort's (or
        timeout's) wall time measures the client/deadline, not the engine —
        but every retirement gets its full RequestMetrics record and its own
        counter.  (The "rejected" counter is incremented at intake, where
        the decision is made.)"""
        rid = req.request_id
        lc = self._lifecycles.pop(rid, None)
        if lc is not None:
            lc.t_finish = self._now()
            lc.e2e_s = lc.t_finish - lc.t_enqueue
            lc.cached_tokens = cached
            lc.n_generated = len(token_ids)
            if lc.t_first_token is not None and len(token_ids) > 1:
                lc.tpot_s = (lc.t_finish - lc.t_first_token) / \
                    (len(token_ids) - 1)
            if reason == "abort":
                self._aborted_requests.inc()
            elif reason == "timeout":
                self._timeouts.inc()
            elif reason == "rejected":
                pass                    # counted at the intake decision
            else:
                self._finished_requests.inc()
                ex = self._exemplar(rid)
                self._h_e2e.observe(lc.e2e_s, exemplar=ex)
                if lc.tpot_s is not None:
                    self._h_tpot.observe(lc.tpot_s, exemplar=ex)
            # SLO accounting: every retired deadline-bearing request lands in
            # the attainment denominator; only an on-time stop/length finish
            # counts as met.  Goodput credits FINAL-output tokens to the
            # request's priority class (replayed prefill work earns nothing,
            # same rule as the bench's goodput_tokens_per_sec).
            if req.deadline is not None:
                self._deadline_requests.inc()
                if reason in ("stop", "length") and \
                        lc.t_finish <= req.deadline:
                    self._deadline_met.inc()
            if reason in ("stop", "length") and token_ids:
                prio = int(req.priority)
                c = self._goodput_prio.get(prio)
                if c is None:
                    c = self.metrics.counter(
                        f"goodput_tokens_priority_{prio}",
                        f"final-output tokens from priority-{prio} requests")
                    self._goodput_prio[prio] = c
                c.inc(len(token_ids))
        self._tev(rid, "finish", reason=reason, n_generated=len(token_ids))
        out = RequestOutput(req.request_id, req.prompt, token_ids, reason,
                            cached, ttft, lc, self._traces.pop(rid, None))
        self._outputs[out.request_id] = out
        if out.trace is not None and self._trace_retention is not None:
            # bounded retirement ledger: drop the OLDEST retired timeline
            # past the cap (the output itself keeps its tokens/metrics)
            self._retired_traced.append(rid)
            while len(self._retired_traced) > self._trace_retention:
                old = self._outputs.get(self._retired_traced.popleft())
                if old is not None:
                    old.trace = None
        return out

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"no bucket for prompt length {n}")

    def _h2d(self, a, dtype=None):
        """Host->device for per-step scheduler inputs (tokens, page tables,
        lengths, flags): numpy-first + EXPLICIT placement, so the
        steady-state decode loop runs clean under
        `jax.transfer_guard("disallow")` — a bare Python list/int through
        `jnp.asarray` is an implicit transfer, and under mp a single-device
        array would be implicitly resharded to the mesh at every AOT
        dispatch."""
        a = np.asarray(a, dtype)
        if self._repl_sharding is not None:
            return jax.device_put(a, self._repl_sharding)
        return jnp.asarray(a)

    def _span(self, name: str):
        """A profiler span for one host phase — real only while a trace is
        recording (engine.trace() or a user Profiler); the steady-state step
        loop pays a flag check, nothing else."""
        if self._tracing or _prof.is_recording():
            return _prof.RecordEvent(name)
        return _NULL_SPAN

    # ---- per-request tracing ----------------------------------------------
    def _tev(self, rid: int, name: str, **attrs) -> None:
        """Stamp one event on a request's timeline (no-op with tracing off or
        for an unknown/finished rid).  Hot-path cost: one dict lookup, one
        clock read, one dict+list append — plain host data, inside whatever
        ENGINE_SPANS phase the caller already occupies (no new spans, no
        device access, no compiled-program change)."""
        tr = self._traces.get(rid)
        if tr is not None:
            tr.event(self._now(), name, **attrs)

    def _exemplar(self, rid: int) -> Optional[Dict[str, str]]:
        """Exemplar labels binding a histogram observation to its request:
        the id plus the obs-server handle that resolves it
        (``GET /requests/<rid>`` returns the chrome-trace span tree).  None
        with request tracing off — the exposition then carries no exemplars,
        matching the absent timelines."""
        if not self._req_tracing:
            return None
        return {"request_id": str(rid), "trace": f"/requests/{rid}"}

    def _trace_for(self, rid: int):
        """The request's timeline, live (`_traces`) or retired (riding its
        RequestOutput) — the single lookup behind `export_request_trace`
        and the debug bundle's per-request states; None when the id is
        unknown or tracing is off."""
        tr = self._traces.get(rid)
        if tr is None:
            out = self._outputs.get(rid)
            tr = out.trace if out is not None else None
        return tr

    def export_request_trace(self, rid: int) -> Optional[Dict[str, object]]:
        """The chrome-trace span tree of one request's timeline (live or
        retired — retired traces ride their RequestOutput, retained for the
        last `trace_retention` retirements), or None when the id is unknown,
        tracing is off, or the timeline aged out.  Served by the obs server
        as ``GET /requests/<rid>``; the raw event list is
        `RequestOutput.trace.events`."""
        tr = self._trace_for(rid)
        return None if tr is None else tr.to_chrome()

    # ---- scheduler --------------------------------------------------------
    def step(self) -> List[RequestOutput]:
        """One engine iteration: harvest the previous fused dispatch (double-
        buffered mode), admit queued requests into free slots (prefix-cache
        matching + page reservation), stage at most ONE prefill chunk, then
        dispatch decode work — ONE fused program covering every decode/
        verify/chunk slot (default), or the legacy per-mode programs
        (`fuse=False`).  Returns the requests that finished this iteration
        (under double-buffering a request finishes the step its tokens are
        harvested, one after its last dispatch).

        Each iteration appends one v2 record to the step-trace ring
        (`step_trace()`): what the step dispatched (decode-batch occupancy,
        per-mode slot counts, dispatch count, harvest-sync time, chunk
        interleaved, verify dispatches, tokens emitted) and the page pool it
        left behind — the timeline that answers "what was the engine doing
        when this request was slow"."""
        finished: List[RequestOutput] = self._orphan_finished
        self._orphan_finished = []
        t0 = self._now()
        tok0 = self._decode_tokens.value
        ver0 = self._verify_steps.value
        chunk0 = self._prefill_chunks.value
        self._step_dispatches = 0
        self._step_sync_s = 0.0
        self._step_preempted = 0
        self._step_slots = {"decode": 0, "verify": 0, "chunk": 0}
        with self._span("engine.step"):
            self._harvest(finished)     # step n-1's tokens land first
            if self._has_deadlines:
                # right after harvest: bookkeeping is exact, nothing in flight
                self._expire_deadlines(finished)
            with self._span("engine.admit"):
                self._admit(finished)
            if self.fused:
                if self.chunked:
                    chunk_job = self._stage_chunk()
                else:
                    # bucketed mode: prefix-hit tails keep the standalone
                    # chunk program (cold path, next to the one-shot prefill)
                    self._prefill_tick(finished)
                    chunk_job = None
                if self._running or chunk_job is not None:
                    self._fused_iter(chunk_job, finished)
            else:
                self._prefill_tick(finished)
                if self._running:
                    self._decode_iter(finished)
            # decode-batch occupancy of what actually DISPATCHED: on a
            # preemption step the pre-dispatch running count overstates the
            # batch (victims left before the program ran)
            decode_batch = self._step_slots["decode"] + \
                self._step_slots["verify"]
            # deferred swap-out fetches: the d2h lands while the device is
            # busy with the dispatch above, not before it
            if self._pending_d2h:
                self._drain_swap_d2h()
        dur = self._now() - t0
        self._h_step.observe(dur)
        if self._step_dispatches:
            # busy steps only: an idle/admission-only step measures the
            # scheduler, not the serving step the roofline predicts
            self._note_steady_state(dur)
        # one rate-window sample per step (throttled), FORCED on eventful
        # steps (retirements or preemptions) so the last event before the
        # engine goes idle is anchored at its true time — that is what
        # makes idle rates read exactly 0.0 once the window passes the
        # burst, instead of decaying against a stale reference
        self.metrics.sample_rates(
            force=bool(finished) or self._step_preempted > 0)
        self._step_idx += 1
        mgr = self.cache
        self._step_trace.append({
            # v2 record (PR "one-dispatch step"): v1 keys unchanged, plus
            # `v`/`fused`/`dispatches`/`sync_ms`/`slots` — consumers keyed on
            # the v1 schema keep working, fusion-aware ones check `v`
            "v": 2,
            "step": self._step_idx,
            "t": t0,
            "dur_s": dur,
            "queued": len(self._queue),
            "prefilling": len(self._prefilling),
            "running": len(self._running),
            "decode_batch": decode_batch,
            "chunk": self._prefill_chunks.value > chunk0,
            "verify_dispatches": self._verify_steps.value - ver0,
            "tokens_emitted": self._decode_tokens.value - tok0,
            "finished": len(finished),
            "pages_in_use": mgr.pages_in_use(),
            "pages_free": mgr.num_free_pages,
            "pages_evictable": mgr.num_evictable_pages,
            "fused": self.fused,
            # decode-path dispatches this step (fused/decode/verify/chunk-
            # interleave programs; the admission-time one-shot prefill is the
            # cold path and is not counted)
            "dispatches": self._step_dispatches,
            # blocking device->host sync time spent inside this step's
            # engine.sample.sync spans (harvest + legacy inline fetches)
            "sync_ms": self._step_sync_s * 1e3,
            # per-mode slot occupancy of this step's decode-path dispatches
            "slots": dict(self._step_slots),
            # overload lane (v2-additive): victims evicted this step and the
            # live pool-pressure fraction the decision saw
            "preempted": self._step_preempted,
            "pool_pressure": round(mgr.pool_pressure(), 4),
        })
        return finished

    def step_trace(self) -> List[Dict[str, object]]:
        """The per-step timeline ring, oldest first (bounded at `trace_ring`
        records; cleared by `reset_counters()`)."""
        return list(self._step_trace)

    # ---- fused one-dispatch step machinery --------------------------------
    def _stage_chunk(self) -> Optional[Dict[str, object]]:
        """Chunked+fused mode: pick the oldest mid-prefill slot's next chunk
        and describe it for the fused batch (no standalone dispatch).  The
        host bookkeeping that doesn't need the result — filled counter,
        prefix registration — happens here; a chunk that completes its
        prompt leaves `_prefilling` now and is resolved to a decode slot at
        harvest, when its first token is known."""
        if not self._prefilling:
            return None
        slot, st = next(iter(self._prefilling.items()))
        lp = st.prompt.size
        n = min(self._chunk, lp - st.filled)
        job = {"slot": slot, "n": n, "q_offset": st.filled, "st": st,
               "done": st.filled + n == lp}
        self._tev(st.request.request_id, "prefill_chunk",
                  q_offset=int(st.filled), n=int(n))
        st.filled += n
        self._prefill_chunks.inc()
        self._prefilled_tokens.inc(n)
        if self.prefix_cache:
            self.cache.register_prefix(slot, st.prompt, st.filled)
        if job["done"]:
            del self._prefilling[slot]      # resolved at harvest
        return job

    def _fused_iter(self, chunk_job: Optional[Dict[str, object]],
                    finished: List[RequestOutput]) -> None:
        """Build and dispatch the ONE fused program covering every active
        lane this step: decode slots at valid=1, drafted (greedy) slots at
        valid=1+len(draft), the staged prefill chunk at valid=chunk tokens.
        Inactive/mid-prefill slots get null table rows.  The dispatch
        returns un-synced; `_harvest` interprets the token/accept buffer —
        immediately (double_buffer=False) or at the top of the next step."""
        mgr = self.cache
        B, T = mgr.num_slots, self._fused_T
        if self.spec_len and self._running:
            with self._span("engine.spec.propose"):
                drafts = self._propose_drafts()
        else:
            drafts = {}
        # optimistic admission: every running slot must own pages for the
        # positions this dispatch writes — growth failures preempt victims
        # out of self._running (and out of drafts) before the batch is built
        self._grow_running(drafts)
        if not self._running and chunk_job is None:
            return                      # everything got preempted this step
        if self._running:
            self._decode_iters.inc()
        tokens = np.zeros((B, T), np.int32)
        valid = np.ones((B,), np.int32)
        qoff = np.zeros((B,), np.int32)
        greedy = np.zeros((B,), bool)
        table = mgr.page_table.copy()
        slots: List[int] = []
        nds: Dict[int, int] = {}
        chunk_slot = chunk_job["slot"] if chunk_job is not None else None
        for slot in range(B):
            seq = self._running.get(slot)
            if seq is not None:
                slots.append(slot)
                tokens[slot, 0] = seq.generated[-1]
                qoff[slot] = mgr.lengths[slot]
                greedy[slot] = seq.greedy
                d = drafts.get(slot)
                if d is not None:
                    tokens[slot, 1:1 + d.size] = d
                    valid[slot] = 1 + d.size
                    nds[slot] = d.size
            elif slot == chunk_slot:
                st = chunk_job["st"]
                n = chunk_job["n"]
                q0 = chunk_job["q_offset"]
                tokens[slot, :n] = st.prompt[q0:q0 + n]
                valid[slot] = n
                qoff[slot] = q0
                greedy[slot] = self._req_greedy(st.request)
            else:
                table[slot, :] = 0          # inactive: KV to the null page
        with self._span("engine.fused.dispatch"):
            out, accept, self._pool, self._key = self._decode_fn(
                self.params, self._h2d(tokens), self._pool,
                self._h2d(table), self._h2d(qoff), self._h2d(valid),
                self._key, self._h2d(greedy))
        self._decode_used = True
        self._step_dispatches += 1
        self._step_slots["verify"] += len(nds)
        self._step_slots["decode"] += len(slots) - len(nds)
        self._step_slots["chunk"] += int(chunk_job is not None)
        if nds:
            # the fused dispatch carried >= 1 draft: it IS this step's verify
            # dispatch (the counter keeps its "verify-program dispatches"
            # meaning for timeline/bench consumers)
            self._verify_steps.inc()
        inflight = {"out": out, "accept": accept, "slots": slots,
                    "drafts": {s: drafts[s] for s in nds},
                    "chunk": chunk_job}
        if self.double_buffer:
            self._inflight = inflight
        else:
            self._harvest(finished, inflight)

    def _harvest(self, finished: List[RequestOutput],
                 inflight: Optional[Dict[str, object]] = None) -> None:
        """Fetch and apply the result of a fused dispatch: the `[B, T] + [B]`
        int token/accept buffer (the step's ONLY device->host transfer —
        O(B*K) ints, not [B, V] logits).  Emits each running slot's accepted
        prefix + bonus (or its single decode/sampled token), resolves a
        completed chunk into the decode set, and retires finishers."""
        inf = inflight if inflight is not None else self._inflight
        if inflight is None:
            self._inflight = None
        if inf is None:
            return
        t_sync = self._now()
        with self._span("engine.sample.sync"):
            out = np.asarray(inf["out"])        # blocks on the device result
            accept = np.asarray(inf["accept"])
        self._step_sync_s += self._now() - t_sync
        drafts = inf["drafts"]
        with self._span("engine.spec.accept"):
            for slot in inf["slots"]:
                seq = self._running[slot]
                d = drafts.get(slot)
                nd = 0 if d is None else d.size
                a = int(accept[slot])           # on-device prefix match, <= nd
                # accepted drafts equal the predictions they matched, so the
                # emitted run is out[:a] + the bonus token out[a]
                emitted = [int(x) for x in out[slot, :a + 1]]
                if self._emit_slot(seq, slot, emitted, nd, a, finished):
                    del self._running[slot]
            cj = inf["chunk"]
            if cj is not None and cj["done"]:
                st = cj["st"]
                tok = int(out[cj["slot"], cj["n"] - 1])
                self._start_decoding(st.request, cj["slot"], tok,
                                     st.cached_tokens, finished,
                                     prompt_len=st.prompt.size,
                                     prior=st.prior, ttft=st.ttft,
                                     spec_off=st.spec_off, streak=st.streak)

    def _emit_slot(self, seq: _Running, slot: int, emitted: List[int],
                   nd: int, a: int, finished: List[RequestOutput]) -> bool:
        """Apply one slot's decode/verify emission — budget-room truncation,
        EOS cut, length advance (rejected candidate KV above it is stale
        garbage inside the slot's own reservation), token/spec counters, the
        zero-accept back-off streak — and retire the slot if it finished.
        The ONE copy both the fused harvest and the legacy `_verify_iter` go
        through, so their byte parity cannot drift.  Returns True when the
        caller must drop the slot from the running set."""
        room = seq.request.max_new_tokens - len(seq.generated)
        emitted = emitted[:room]
        if self.eos_token_id is not None and self.eos_token_id in emitted:
            emitted = emitted[:emitted.index(self.eos_token_id) + 1]
        self.cache.lengths[slot] += len(emitted)
        seq.generated.extend(emitted)
        self._decode_tokens.inc(len(emitted))
        if nd:
            self._spec_events.inc()
            self._spec_drafted.inc(nd)
            self._spec_accepted.inc(a)
            self._spec_emitted.inc(len(emitted))
            self._tev(seq.request.request_id, "spec_verify", drafted=int(nd),
                      accepted=int(a), emitted=len(emitted))
            # adaptive spec back-off: a slot whose drafts are NEVER accepted
            # (acceptance rate ~0 over the window) stops paying the proposer
            # scan and the wasted candidate positions — it keeps riding the
            # decode-side program at valid=1.  Output parity is untouched:
            # greedy acceptance is lossless either way.
            if a == 0:
                seq.spec_zero_streak += 1
                if self.spec_backoff_window and not seq.spec_off and \
                        seq.spec_zero_streak >= self.spec_backoff_window:
                    seq.spec_off = True
                    self._spec_backoffs.inc()
            else:
                seq.spec_zero_streak = 0
        return self._maybe_finish(seq, finished)

    # ---- oversubscription: growth, preemption, swap, deadlines ------------
    def _grow_running(self, drafts: Dict[int, np.ndarray]) -> None:
        """Optimistic admission's pre-dispatch capacity pass: every running
        slot must own pages covering the positions this step will write
        (its last token's KV at lengths, plus one slot per drafted
        candidate).  A failed growth is THE preemption trigger: victims are
        evicted until the growth fits, the growing slot itself last of all
        (it re-queues at the head and replays later).  Runs strictly after
        the step-top harvest, so no fused batch is in flight while page
        state moves (the TPL007 discipline).  `drafts` is pruned of any slot
        that got preempted.  Reservation mode returns immediately — every
        slot's full footprint is already reserved."""
        if not self.optimistic or not self._running:
            return
        forced = self._faults.pool_pressure(self._step_idx)
        for slot in list(self._running):
            while slot in self._running:
                d = drafts.get(slot)
                need = int(self.cache.lengths[slot]) + 1 + \
                    (d.size if d is not None else 0)
                try:
                    if forced:
                        forced = False
                        raise RuntimeError("fault-injected pool pressure")
                    self.cache.grow(slot, need)
                    break
                except RuntimeError:
                    # the growing slot is a candidate too: if IT ranks worst
                    # (lowest priority), preempting it both respects the
                    # policy and resolves the failure — and alone it always
                    # fits eventually (add_request rejected any footprint
                    # larger than the pool), so its replay cannot loop
                    self._tev(self._running[slot].request.request_id,
                              "grow_fail", need_tokens=int(need))
                    self._preempt_slot(self._pick_victim())
        for slot in list(drafts):
            if slot not in self._running:
                del drafts[slot]

    def _pick_victim(self) -> int:
        """The next preemption victim among ALL running slots: lowest
        priority first, then most pages held (frees the most), least
        progress (least work at stake), youngest last-arrived."""
        return min(
            self._running.items(),
            key=lambda kv: (kv[1].request.priority,
                            -self.cache.pages_held(kv[0]),
                            len(kv[1].generated) /
                            kv[1].request.max_new_tokens,
                            -kv[1].request.request_id))[0]

    def _preempt_slot(self, slot: int) -> None:
        """Evict one running slot: bank its generation, park its KV (swap
        mode, pool room permitting) or mark it for recompute, release its
        pages, and re-queue it at the HEAD (preempted work outranks fresh
        arrivals — starving a half-done request wastes the pages it already
        burned)."""
        seq = self._running.pop(slot)
        req = seq.request
        rid = req.request_id
        mgr = self.cache
        self._preemptions.inc()
        self._step_preempted += 1
        rec: Dict[str, object] = {
            "rid": rid, "kind": "recompute",
            "generated": list(seq.generated),
            "cached_tokens": seq.cached_tokens, "ttft": seq.ttft_s,
            "spec_off": seq.spec_off, "streak": seq.spec_zero_streak,
        }
        L = int(mgr.lengths[slot])
        n = mgr.pages_needed(L)
        if self.preempt == "swap":
            # live victims outrank cached prefixes in the unified host pool:
            # reclaim tier room (demote to disk or drop) before giving up
            room = mgr.host_pool_room(self.swap_pool_pages)
            if n > room:
                room += mgr.tier_make_room(n - room)
        else:
            room = -1
        if self.preempt == "swap" and n <= room:
            # gather the victim's pages into a standalone buffer NOW (the
            # pages are about to be handed to a new owner); the blocking
            # d2h fetch is deferred until after the next dispatch
            ids = np.zeros((mgr.max_pages_per_slot,), np.int32)
            ids[:n] = mgr.slot_pages(slot)[:n]
            data = self._swap_out_fn(self._pool, self._h2d(ids))
            self._swap_out_used = True
            rec.update(kind="swap", L=L, n=n, data=data, fetched=False)
            mgr.note_swap_out(rid, n)
            self._pending_d2h.append(rec)
            # swapped_pages/preempt_swaps count at d2h SUCCESS (in
            # _materialize_swap): a copy that fails and degrades to
            # recompute never delivered KV to the host pool, and the
            # bench's swap-vs-recompute split must not claim it did
        else:
            self._preempt_recomputes.inc()
        self._tev(rid, "preempt", kind=rec["kind"], pages=int(n),
                  progress=len(seq.generated))
        self._preempted[rid] = rec
        lc = self._lifecycles.get(rid)
        if lc is not None:
            lc.preemptions += 1
        mgr.release(slot)
        self._free_slots.append(slot)
        self._queue.appendleft(req)

    def _materialize_swap(self, rec: Dict[str, object]) -> None:
        """Fetch a swap record's gathered pages into host numpy (idempotent;
        pads discarded).  Raises FaultInjected under an injected d2h
        failure — the caller degrades the record to recompute."""
        if rec.get("fetched"):
            return
        self._faults.d2h()
        t0 = self._now()
        with self._span("engine.swap.d2h"):
            rec["data"] = {name: np.asarray(a)[:, :rec["n"]]
                           for name, a in rec["data"].items()}
        self._swap_ms_c.inc((self._now() - t0) * 1e3)
        rec["fetched"] = True
        self._swapped_pages_c.inc(rec["n"])
        self._preempt_swaps.inc()
        self._tev(rec["rid"], "swap_out", pages=int(rec["n"]))

    def _degrade_to_recompute(self, rec: Dict[str, object]) -> None:
        """A swap whose d2h/h2d copy failed falls back to recompute: drop
        the parked KV, clear the host-pool obligation, keep the banked
        generation — nothing leaks, the replay just costs prefill again."""
        rec["kind"] = "recompute"
        rec.pop("data", None)
        self.cache.note_swap_in(rec["rid"])
        self._preempt_recomputes.inc()
        self._tev(rec["rid"], "swap_degrade")

    def _drain_swap_d2h(self) -> None:
        """Materialize deferred swap-out fetches — called after the step's
        dispatch so the d2h overlaps device compute instead of stalling the
        schedule."""
        while self._pending_d2h:
            rec = self._pending_d2h.pop()
            if rec["kind"] == "spill":
                if not rec.get("fetched"):
                    try:
                        self._materialize_spill(rec)
                    except FaultInjected:
                        self._degrade_spill_to_drop(rec)
                continue
            if rec["kind"] != "swap" or rec.get("fetched"):
                continue            # consumed, degraded or dropped already
            try:
                self._materialize_swap(rec)
            except FaultInjected:
                self._degrade_to_recompute(rec)

    # ---- KV tiering: prefix spill (device -> host -> disk) and restore ----
    def _spill_prefix_nodes(self, nodes) -> set:
        """`PagedKVCache._evict`'s spill callback: gather the evicted
        prefix pages into standalone device buffers (the PR-10
        `swap_out_pages` executable, one fixed-shape dispatch per
        `max_pages_per_slot` pages) and defer the blocking d2h fetch past
        the next dispatch (`_pending_d2h`), exactly the preemption-swap
        discipline.  Room comes from the UNIFIED host pool: what swap
        parking has not claimed, reclaiming host-tier room downward (disk
        or drop) first.  Returns the node ids accepted — the cache drops
        the rest."""
        mgr = self.cache
        room = mgr.host_pool_room(self.swap_pool_pages)
        if room < len(nodes):
            room += mgr.tier_make_room(len(nodes) - room)
        if room <= 0:
            return set()
        accept = nodes[-room:] if room < len(nodes) else nodes
        P = mgr.max_pages_per_slot
        for i in range(0, len(accept), P):
            chunk = accept[i:i + P]
            ids = np.zeros((P,), np.int32)
            ids[:len(chunk)] = [nd.page for nd in chunk]
            data = self._swap_out_fn(self._pool, self._h2d(ids))
            self._swap_out_used = True
            self._pending_d2h.append({"kind": "spill", "nodes": list(chunk),
                                      "n": len(chunk), "data": data,
                                      "fetched": False})
        return {nd.node_id for nd in accept}

    def _materialize_spill(self, rec: Dict[str, object]) -> None:
        """Fetch a spill record's gathered pages into the host tier
        (idempotent; pads discarded).  Raises FaultInjected under an
        injected d2h failure — the caller degrades spill -> drop."""
        if rec.get("fetched"):
            return
        self._faults.d2h()
        t0 = self._now()
        with self._span("engine.swap.d2h"):
            data = {name: np.asarray(a) for name, a in rec["data"].items()}
        self._swap_ms_c.inc((self._now() - t0) * 1e3)
        rec["fetched"] = True
        tier = self.cache._tier
        landed = 0
        for i, node in enumerate(rec["nodes"]):
            if tier is not None and tier.is_pending(node.node_id):
                tier.fill(node.node_id,
                          {name: np.ascontiguousarray(a[:, i])
                           for name, a in data.items()})
                landed += 1
        self._tier_spills.inc(landed)

    def _degrade_spill_to_drop(self, rec: Dict[str, object]) -> None:
        """A spill whose d2h copy failed drops its nodes from the index —
        the pages were already reclaimed, so the only cost is that a later
        match re-prefills instead of restoring.  Nothing leaks."""
        rec["fetched"] = True           # never retried
        tier = self.cache._tier
        pend = [nd for nd in rec["nodes"]
                if tier is not None and tier.is_pending(nd.node_id)]
        self.cache.drop_tier_nodes(pend)

    def _flush_pending_spills(self) -> None:
        """Materialize every deferred spill fetch NOW (a tier restore needs
        the bytes) — swap records stay deferred for their usual
        post-dispatch drain."""
        rest: List[Dict[str, object]] = []
        for rec in self._pending_d2h:
            if rec["kind"] == "spill" and not rec.get("fetched"):
                try:
                    self._materialize_spill(rec)
                except FaultInjected:
                    self._degrade_spill_to_drop(rec)
            else:
                rest.append(rec)
        self._pending_d2h = rest

    def _tier_restore(self, slot: int, plan, rid: int) -> bool:
        """Scatter a matched prefix's parked KV from the host/disk tier into
        `slot`'s freshly allocated pages — ONE `swap_in_pages` dispatch for
        the whole plan, after which the restored full pages are device
        prefix pages again (`commit_restore`).  Returns False when the
        restore degraded (failed h2d copy, vanished tier data): the plan's
        nodes are dropped and the caller re-matches — the request
        re-prefills those positions instead, nothing leaks."""
        mgr = self.cache
        tier = mgr._tier
        if any(tier.is_pending(node.node_id) for _, node, _ in plan):
            self._flush_pending_spills()
        nodes = [node for _, node, _ in plan]
        try:
            datas = [mgr.tier_data(node) for node in nodes]
            self._faults.h2d()
        except (KeyError, RuntimeError):
            # FaultInjected is a RuntimeError; real vanished-data errors
            # degrade identically — drop the nodes, let the caller re-match
            mgr.drop_tier_nodes(nodes)
            return False
        k = len(plan)
        ids = np.zeros((mgr.max_pages_per_slot,), np.int32)
        staging: Dict[str, np.ndarray] = {}
        for name, a in datas[0].items():
            staging[name] = np.zeros(
                (a.shape[0], mgr.max_pages_per_slot) + a.shape[1:], a.dtype)
        for i, ((dst, node, ntok), d) in enumerate(zip(plan, datas)):
            ids[i] = dst
            for name, a in d.items():
                staging[name][:, i] = a
        t0 = self._now()
        with self._span("engine.swap.h2d"):
            up = {name: self._h2d(a) for name, a in staging.items()}
            self._pool = self._swap_in_fn(self._pool, self._h2d(ids), up)
        self._swap_in_used = True
        self._swap_ms_c.inc((self._now() - t0) * 1e3)
        mgr.commit_restore(slot, plan)
        tokens = sum(ntok for _, _, ntok in plan)
        self._tier_restores.inc()
        self._tier_restored_tokens.inc(tokens)
        self._tev(rid, "tier_restore", slot=slot, pages=int(k),
                  tokens=int(tokens))
        return True

    def export_prefix(self, tokens: np.ndarray,
                      rid: Optional[int] = None) -> Dict[str, int]:
        """Disaggregated handoff (send side): publish the cached KV chain of
        `tokens` to the shared tier store so a DECODE-role peer can restore
        it with one scatter.  Device-resident chain nodes that are parked in
        the LRU (refcount 0 — the finished prompt just released them) spill
        through the ordinary `_spill_prefix_nodes` gather, the pending d2h
        is flushed, host entries are pushed to the store level, and the
        durable index is re-published.  Zero new programs: the export rides
        the same two swap executables the tier already warmed.  Returns
        {"pages", "tokens", "index_nodes"} — all 0 when no store is
        attached or nothing was exportable (the peer then degrades to local
        re-prefill, parity-lossless)."""
        from .cache import HOST_PAGE
        out = {"pages": 0, "tokens": 0, "index_nodes": 0}
        with self._serve_lock:
            mgr = self.cache
            tier = mgr._tier
            if not self.kv_tier or tier is None or tier.store is None:
                return out
            full, partial = mgr._match(np.asarray(tokens, np.int32))
            chain = list(full) + ([partial[0]] if partial else [])
            if not chain:
                return out
            todo = [nd for nd in chain
                    if nd.page >= 0 and nd.node_id in mgr._lru]
            accepted = self._spill_prefix_nodes(todo) if todo else set()
            for nd in todo:
                if nd.node_id not in accepted:
                    continue
                # mirror _evict's accept bookkeeping: the page goes back to
                # the free pool, the node becomes an off-device tier entry
                mgr._lru.pop(nd.node_id)
                mgr._free.append(nd.page)
                del mgr._page_node[nd.page]
                nd.page = HOST_PAGE
                mgr._tier_nodes[nd.node_id] = nd
                tier.add_pending(nd.node_id)
            self._flush_pending_spills()
            pages = tokens_out = 0
            for nd in chain:
                if nd.page >= 0 or tier.is_pending(nd.node_id):
                    continue            # still on device / spill degraded
                if nd.node_id in tier._host:
                    tier.to_disk(nd.node_id)
                if nd.node_id in tier._disk:
                    pages += 1
                    tokens_out += nd.n_tokens
            out["pages"] = pages
            out["tokens"] = tokens_out
            out["index_nodes"] = mgr.save_tier_index(tag=tier.tag)
            if pages:
                self._handoff_exports.inc()
                self._handoff_pages.inc(pages)
                self._handoff_tokens.inc(tokens_out)
                if rid is not None:
                    # the prefill request has already retired (export runs
                    # after result()), so its trace rides the RequestOutput
                    # — _tev only sees live traces and would drop the event
                    tr = self._trace_for(rid)
                    if tr is not None:
                        tr.event(self._now(), "handoff", pages=int(pages),
                                 tokens=int(tokens_out))
        return out

    def refresh_store_index(self) -> int:
        """Disaggregated handoff (receive side): re-merge the shared store's
        published index so the NEXT admission can tier-restore prefixes a
        prefill peer just exported.  Idempotent and cheap (already-known
        nodes are skipped).  Returns nodes newly imported."""
        if not self.kv_tier:
            return 0
        with self._serve_lock:
            n = self.cache.load_tier_index()
        self._store_restored_nodes += n
        return n

    def _drop_preempted(self, rid: int) -> Optional[Dict[str, object]]:
        """Remove a resume record on abort/timeout, clearing any host swap
        obligation; returns the record (its banked generation feeds the
        output) or None."""
        rec = self._preempted.pop(rid, None)
        if rec is None:
            return None
        if rec["kind"] == "swap":
            self.cache.note_swap_in(rid)
            rec["kind"] = "dropped"     # _drain_swap_d2h skips it
        return rec

    def _swap_in(self, req: Request, rec: Dict[str, object],
                 slot: int) -> bool:
        """Restore a swapped victim into `slot`: allocate fresh pages for
        its parked footprint and scatter the KV back in one h2d dispatch —
        the request rejoins the decode set with NO prefill replay.  Returns
        True when running again; False when it must keep waiting for pages
        or was degraded to recompute (the caller re-examines the record)."""
        rid = req.request_id
        mgr = self.cache
        try:
            self._materialize_swap(rec)
        except FaultInjected:
            self._degrade_to_recompute(rec)
            return False
        try:
            mgr.allocate(slot, rec["L"])
        except RuntimeError:            # no pages yet — stay queued
            return False
        try:
            self._faults.h2d()
        except FaultInjected:
            mgr.release(slot)
            self._degrade_to_recompute(rec)
            return False
        n = rec["n"]
        ids = np.zeros((mgr.max_pages_per_slot,), np.int32)
        ids[:n] = mgr.slot_pages(slot)[:n]
        data = {}
        t0 = self._now()
        with self._span("engine.swap.h2d"):
            # staging uploads count as h2d cost: swap_ms and the span cover
            # the host->device copies AND the scatter dispatch, as in the
            # single-lane (k, v) form this generalizes
            for name, a in rec["data"].items():
                pad = np.zeros(
                    (a.shape[0], mgr.max_pages_per_slot) + a.shape[2:],
                    a.dtype)
                pad[:, :n] = a
                data[name] = self._h2d(pad)
            self._pool = self._swap_in_fn(self._pool, self._h2d(ids), data)
        self._swap_in_used = True
        self._swap_ms_c.inc((self._now() - t0) * 1e3)
        mgr.note_swap_in(rid)
        self._preempted.pop(rid)
        self._tev(rid, "swap_in", slot=slot, pages=int(n))
        mgr.lengths[slot] = rec["L"]
        seq = _Running(req, slot, list(rec["generated"]),
                       rec["cached_tokens"], rec["ttft"],
                       self._req_greedy(req))
        seq.spec_off = rec["spec_off"]
        seq.spec_zero_streak = rec["streak"]
        self._running[slot] = seq
        return True

    def _expire_deadlines(self, finished: List[RequestOutput]) -> None:
        """Retire every request past its deadline, wherever it lives
        (queued/swapped, prefilling, decoding), as finish_reason="timeout".
        Runs right after the step-top harvest so page bookkeeping is exact;
        injected clock skew (FaultPlan.skew) shifts only this evaluation.
        Also re-derives `_has_deadlines` so an engine that served one
        deadlined request long ago stops paying this scan once no
        deadline-bearing request remains."""
        now = self._now() + self._faults.skew()
        live = False
        for i in range(len(self._queue) - 1, -1, -1):
            req = self._queue[i]
            if req.deadline is not None and now >= req.deadline:
                del self._queue[i]
                rec = self._drop_preempted(req.request_id)
                gen = list(rec["generated"]) if rec is not None else []
                finished.append(self._finish_output(
                    req, gen, "timeout",
                    rec["cached_tokens"] if rec is not None else 0,
                    rec["ttft"] if rec is not None else None))
            elif req.deadline is not None:
                live = True
        for slot, st in list(self._prefilling.items()):
            req = st.request
            if req.deadline is not None and now >= req.deadline:
                del self._prefilling[slot]
                self.cache.release(slot)
                self._free_slots.append(slot)
                finished.append(self._finish_output(
                    req, list(st.prior or []), "timeout",
                    st.cached_tokens, st.ttft))
            elif req.deadline is not None:
                live = True
        for slot, seq in list(self._running.items()):
            req = seq.request
            if req.deadline is not None and now >= req.deadline:
                del self._running[slot]
                self.cache.release(slot)
                self._free_slots.append(slot)
                finished.append(self._finish_output(
                    req, seq.generated, "timeout", seq.cached_tokens,
                    seq.ttft_s))
            elif req.deadline is not None:
                live = True
        self._has_deadlines = live

    def _admit(self, finished: List[RequestOutput]) -> None:
        mgr = self.cache
        while self._queue and self._free_slots:
            req = self._queue[0]
            rid = req.request_id
            slot = self._free_slots[-1]
            rec = self._preempted.get(rid)
            if rec is not None and rec["kind"] == "swap":
                # swap resume: one h2d scatter, no prefill replay
                if self._swap_in(req, rec, slot):
                    self._queue.popleft()
                    self._free_slots.pop()
                    continue
                if rec["kind"] == "swap":
                    break               # no pages yet — wait at the head
                continue                # degraded to recompute: retry now
            prior = list(rec["generated"]) if rec is not None else None
            if prior:
                # recompute resume: the banked generation is just a longer
                # prompt — replayed through the prefix cache (its own pages
                # are usually still indexed) and chunked prefill
                prompt = np.concatenate(
                    [req.prompt, np.asarray(prior, np.int32)])
            else:
                prompt = req.prompt
            lp = prompt.size
            remaining = req.max_new_tokens - len(prior or ())
            # optimistic admission: reserve the PROMPT footprint only —
            # decode growth allocates the rest token-granularly
            total = lp if self.optimistic else lp + remaining
            if self.optimistic and rec is None and \
                    (self._running or self._prefilling) and \
                    mgr.pages_needed(lp) + self._watermark > \
                    mgr.num_free_pages + mgr.num_evictable_pages:
                # vLLM-style admission watermark: a small GLOBAL headroom
                # (~1% of the pool, >= 1 page) so a fresh admission cannot
                # consume the very last page a running slot needs this step;
                # beyond that, preemption — not admission control — is the
                # pressure valve (a per-slot headroom would just re-create
                # reservation admission with extra steps).  Only enforced
                # while something is actually active: on an idle engine
                # there is no slot to protect, and holding back a prompt
                # whose footprint sits within the watermark of the whole
                # pool would wedge the queue head forever
                break
            tokens = prompt if self.prefix_cache else None
            alloc = None
            restored = ()
            while True:
                try:
                    # one shot: the prefix match and the reservation happen in
                    # the same call (a failed attempt rolls its sharing back),
                    # instead of re-hashing the prompt in a can_allocate probe
                    # every step
                    alloc = mgr.allocate_prefixed(slot, total, tokens)
                except RuntimeError:        # out of KV pages
                    alloc = None
                    break
                plan = mgr.take_restore(slot)
                if not plan:
                    break
                # the match reached into the KV tier: ONE swap_in scatter
                # restores the parked prefix into the slot's fresh pages —
                # no prefill replay.  A degraded restore (failed copy,
                # vanished data) dropped the offending nodes; roll the slot
                # back and re-match without them.
                if self._tier_restore(slot, plan, rid):
                    restored = plan
                    break
                mgr.release(slot)
            if alloc is None:
                if not self._running and not self._prefilling and \
                        mgr.pages_in_use() == 0:
                    # backstop (near-unreachable since add_request rejects
                    # impossible footprints): nothing will ever free
                    raise ValueError(
                        f"request {rid} needs "
                        f"{mgr.pages_needed(total)} pages but the pool only "
                        f"has {mgr.num_pages - 1}; raise num_pages")
                break                       # wait for pages to free up
            row, matched, cow = alloc
            self._queue.popleft()
            self._free_slots.pop()
            lc = self._lifecycles.get(rid)
            if lc is not None and lc.t_admit is None:
                lc.t_admit = self._now()
                lc.queue_s = lc.t_admit - lc.t_enqueue
                self._h_queue.observe(lc.queue_s, exemplar=self._exemplar(rid))
                lc.cached_tokens = matched
            self._admitted_requests.inc()
            self._tev(rid, "admit", slot=slot, prefix_hit_tokens=int(matched),
                      cow=cow is not None, resume=rec is not None)
            if rec is not None:
                self._preempted.pop(rid)
                self._recomputed_tokens.inc(lp - matched)
            # resume-state fan-out, computed ONCE for both branches below
            cached_out = rec["cached_tokens"] if rec is not None else matched
            r_ttft = rec["ttft"] if rec is not None else None
            r_spec_off = rec["spec_off"] if rec is not None else False
            r_streak = rec["streak"] if rec is not None else 0
            if cow is not None:
                # the matched partial page is shared: copy it into the slot's
                # own page before anything is appended into it
                src, dst = cow
                self._pool = self._copy_fn(self._pool,
                                           self._h2d(src, np.int32),
                                           self._h2d(dst, np.int32))
                self._cow_copies.inc()
                self._copy_used = True
            if cow is not None or any(ntok < mgr.page_size
                                      for _, _, ntok in restored):
                # rolling-hash partial-page hit: the match ended INSIDE a
                # cached page (device COW copy or tier scatter of the
                # matched fraction)
                self._partial_hits.inc()
            if matched:
                self._prefix_cached_tokens.inc(matched)
                self._prefix_hit_requests.inc()
            if not self.chunked and matched == 0:
                # legacy one-shot bucketed prefill, synchronous at admission
                bucket = self._bucket_for(lp)
                self._tev(rid, "prefill", n=int(lp), bucket=int(bucket))
                ids = np.zeros((1, bucket), np.int32)
                ids[0, :lp] = prompt
                pages = row[:bucket // mgr.page_size][None, :]
                with self._span("engine.prefill.dispatch"):
                    first, self._pool, self._key = self._prefill_fn(
                        self.params, self._h2d(ids), self._pool,
                        self._h2d(pages), self._h2d([lp], np.int32),
                        self._key, self._h2d([self._req_greedy(req)]))
                self._seen_buckets.add(bucket)
                self._prefilled_tokens.inc(lp)
                if self.prefix_cache:
                    mgr.register_prefix(slot, prompt, lp)
                t_sync = self._now()
                with self._span("engine.sample.sync"):
                    first = int(np.asarray(first)[0])   # blocks on the result
                self._step_sync_s += self._now() - t_sync
                self._start_decoding(
                    req, slot, first, cached_out, finished, prompt_len=lp,
                    prior=prior, ttft=r_ttft, spec_off=r_spec_off,
                    streak=r_streak)
            else:
                self._prefilling[slot] = _Prefilling(
                    req, slot, matched, cached_out, prompt=prompt,
                    prior=prior, ttft=r_ttft, spec_off=r_spec_off,
                    streak=r_streak)

    def _prefill_tick(self, finished: List[RequestOutput]) -> None:
        """Advance the oldest admitted prompt by ONE chunk through the
        standalone chunk program (the Sarathi interleave cap: long prompts
        share each iteration with decode instead of stalling it).  Legacy
        `fuse=False` path, plus prefix-hit tails in fused bucketed mode; in
        fused chunked mode the chunk rides the fused batch instead
        (`_stage_chunk`)."""
        if not self._prefilling:
            return
        slot, st = next(iter(self._prefilling.items()))
        mgr = self.cache
        lp = st.prompt.size
        C = self._chunk
        n = min(C, lp - st.filled)
        self._tev(st.request.request_id, "prefill_chunk",
                  q_offset=int(st.filled), n=int(n))
        ids = np.zeros((1, C), np.int32)
        ids[0, :n] = st.prompt[st.filled:st.filled + n]
        with self._span("engine.prefill.dispatch"):
            tok, self._pool, self._key = self._chunk_fn(
                self.params, self._h2d(ids), self._pool,
                self._h2d(mgr.page_table[slot][None, :]),
                self._h2d([st.filled], np.int32),
                self._h2d([n], np.int32),
                self._key, self._h2d([self._req_greedy(st.request)]))
        self._chunk_used = True
        self._step_dispatches += 1
        self._step_slots["chunk"] += 1
        self._prefill_chunks.inc()
        self._prefilled_tokens.inc(n)
        st.filled += n
        if self.prefix_cache:
            mgr.register_prefix(slot, st.prompt, st.filled)
        if st.filled == lp:
            del self._prefilling[slot]
            t_sync = self._now()
            with self._span("engine.sample.sync"):
                tok = int(np.asarray(tok)[0])           # blocks on the result
            self._step_sync_s += self._now() - t_sync
            self._start_decoding(st.request, slot, tok, st.cached_tokens,
                                 finished, prompt_len=lp, prior=st.prior,
                                 ttft=st.ttft, spec_off=st.spec_off,
                                 streak=st.streak)

    def _start_decoding(self, req: Request, slot: int, first: int,
                        cached: int, finished: List[RequestOutput],
                        prompt_len: Optional[int] = None,
                        prior: Optional[List[int]] = None,
                        ttft: Optional[float] = None,
                        spec_off: bool = False, streak: int = 0) -> None:
        """Prompt fully in pages + first token picked: join the decode set.
        A recompute resume passes the EFFECTIVE prompt length (original +
        banked generation in pages) and its `prior` tokens — the new `first`
        token continues that stream, and TTFT/back-off state carry over from
        before the preemption instead of being re-stamped."""
        self.cache.lengths[slot] = \
            req.prompt.size if prompt_len is None else prompt_len
        now = self._now()
        lc = self._lifecycles.get(req.request_id)
        if prior:
            generated = list(prior) + [first]
        else:
            generated = [first]
            ttft = now - req.t_enqueue
            if lc is not None:
                lc.t_first_token = now
                lc.ttft_s = ttft
            self._h_ttft.observe(ttft,
                                 exemplar=self._exemplar(req.request_id))
            self._tev(req.request_id, "first_token")
        seq = _Running(req, slot, generated, cached, ttft,
                       self._req_greedy(req))
        seq.spec_off = spec_off
        seq.spec_zero_streak = streak
        if not self._maybe_finish(seq, finished):
            self._running[slot] = seq

    def _decode_iter(self, finished: List[RequestOutput]) -> None:
        """One decode iteration over every fully-prefilled slot: when any
        greedy slot has a draft, greedy slots ride the verify executable
        (undrafted ones at valid=1 — plain decode through the same program)
        and sampled slots fall back to the vanilla decode executable in the
        same iteration; otherwise everything takes the vanilla path."""
        if self.spec_len:
            with self._span("engine.spec.propose"):
                drafts = self._propose_drafts()
        else:
            drafts = {}
        self._grow_running(drafts)
        if not self._running:
            return                      # everything got preempted this step
        self._decode_iters.inc()
        if drafts:
            self._verify_iter(drafts, finished)
            rest = [s for s, seq in self._running.items() if not seq.greedy]
        else:
            rest = list(self._running)
        if rest:
            self._vanilla_decode_iter(rest, finished)

    def _propose_drafts(self) -> Dict[int, np.ndarray]:
        """Ask the proposer for up to spec_len continuation tokens per greedy
        slot, capped at the slot's remaining decode budget so speculative KV
        writes stay inside the reservation (prompt + max_new_tokens).  When
        the proposer declares a bounded lookback, only that history tail is
        materialized — this runs on the host every decode iteration, so the
        work per slot must not grow with context length."""
        drafts: Dict[int, np.ndarray] = {}
        win = getattr(self.proposer, "max_lookback", 0)
        for slot, seq in self._running.items():
            if not seq.greedy:
                continue            # acceptance needs a deterministic pick
            if seq.spec_off:
                continue            # adaptive back-off: drafting never landed
                                    # for this slot, skip the proposer scan
                                    # (the slot rides verify at valid=1)
            cap = min(self.spec_len,
                      seq.request.max_new_tokens - len(seq.generated))
            if cap < 1:
                continue
            if win:
                gen = np.asarray(seq.generated[-win:], np.int32)
                head = seq.request.prompt[-(win - gen.size):] \
                    if gen.size < win else seq.request.prompt[:0]
                ctx = np.concatenate([head, gen])
            else:
                ctx = np.concatenate([seq.request.prompt,
                                      np.asarray(seq.generated, np.int32)])
            d = self.proposer.propose(ctx, cap)
            if d is not None and len(d):
                drafts[slot] = np.asarray(d, np.int32).reshape(-1)[:cap]
        return drafts

    def _verify_iter(self, drafts: Dict[int, np.ndarray],
                     finished: List[RequestOutput]) -> None:
        """Score spec_len + 1 positions for every greedy slot in ONE verify
        dispatch, then accept the longest drafted prefix the model agrees
        with plus the bonus token from the first disagreeing position.
        Rollback of rejected candidates is a length decrement: lengths only
        ever advances past KV that is certainly correct, and the stale
        candidate KV above it sits in the slot's own reserved pages where the
        next decode/verify write overwrites it before it can be attended."""
        mgr = self.cache
        B, T = mgr.num_slots, self.spec_len + 1
        tokens = np.zeros((B, T), np.int32)
        valid = np.ones((B,), np.int32)
        qoff = np.zeros((B,), np.int32)
        # free/prefilling/sampled slots must look inactive: null table rows
        # route their (garbage) KV writes to the null page
        table = mgr.page_table.copy()
        active = []
        for slot in range(B):
            seq = self._running.get(slot)
            if seq is None or not seq.greedy:
                table[slot, :] = 0
                continue
            active.append(slot)
            tokens[slot, 0] = seq.generated[-1]
            d = drafts.get(slot)
            if d is not None:
                tokens[slot, 1:1 + d.size] = d
                valid[slot] = 1 + d.size
            qoff[slot] = mgr.lengths[slot]
        with self._span("engine.verify.dispatch"):
            preds, self._pool = self._verify_fn(
                self.params, self._h2d(tokens), self._pool,
                self._h2d(table), self._h2d(qoff), self._h2d(valid))
        self._decode_used = True
        self._step_dispatches += 1
        self._step_slots["verify"] += len(active)
        t_sync = self._now()
        with self._span("engine.sample.sync"):
            preds = np.asarray(preds)       # blocks on the device result
        self._step_sync_s += self._now() - t_sync
        self._verify_steps.inc()
        with self._span("engine.spec.accept"):
            for slot in active:
                seq = self._running[slot]
                d = drafts.get(slot)
                nd = 0 if d is None else d.size
                a = 0
                while a < nd and int(d[a]) == int(preds[slot, a]):
                    a += 1          # greedy longest-prefix acceptance
                emitted = [int(x) for x in d[:a]] if nd else []
                emitted.append(int(preds[slot, a]))        # bonus token
                if self._emit_slot(seq, slot, emitted, nd, a, finished):
                    del self._running[slot]

    def _vanilla_decode_iter(self, slots: List[int],
                             finished: List[RequestOutput]) -> None:
        mgr = self.cache
        active = set(slots)
        tokens = np.zeros((mgr.num_slots,), np.int32)
        greedy = np.zeros((mgr.num_slots,), bool)
        for slot in active:
            seq = self._running[slot]
            tokens[slot] = seq.generated[-1]
            greedy[slot] = seq.greedy
        table = mgr.page_table
        # mid-prefill slots and running slots already served by this
        # iteration's verify dispatch must look inactive to the decode
        # executable: a null table row routes its (garbage) KV write to the
        # null page instead of a position inside the slot's REAL pages
        masked = [s for s in range(mgr.num_slots)
                  if s in self._prefilling or
                  (s in self._running and s not in active)]
        if masked:
            table = table.copy()
            for slot in masked:
                table[slot, :] = 0
        with self._span("engine.decode.dispatch"):
            nxt, self._pool, self._key = self._decode_fn(
                self.params, self._h2d(tokens), self._pool,
                self._h2d(table), self._h2d(mgr.lengths), self._key,
                self._h2d(greedy))
        self._decode_used = True
        self._step_dispatches += 1
        self._step_slots["decode"] += len(active)
        self._decode_tokens.inc(len(active))
        t_sync = self._now()
        with self._span("engine.sample.sync"):
            nxt = np.asarray(nxt)           # blocks on the device result
        self._step_sync_s += self._now() - t_sync
        for slot in slots:
            seq = self._running[slot]
            mgr.lengths[slot] += 1          # the token we just fed is cached
            seq.generated.append(int(nxt[slot]))
            if self._maybe_finish(seq, finished):
                del self._running[slot]

    def warm_spec(self) -> None:
        """Compile the verify executable against inert inputs (all slots
        masked to the null page) — benches call this during warmup so the
        one-off compile stays out of timed counters.  Fused engines have no
        standalone verify program (`warm_decode` already compiled the one
        fused executable every lane rides), so this is a no-op there — which
        also keeps the PRNG stream of a sampled spec-on pass aligned with
        its spec-off comparison pass."""
        if not self.spec_len or self._verify_fn is None:
            return
        B, T = self.cache.num_slots, self.spec_len + 1
        _, self._pool = self._verify_fn(
            self.params, self._h2d(np.zeros((B, T), np.int32)), self._pool,
            self._h2d(np.zeros((B, self.cache.max_pages_per_slot), np.int32)),
            self._h2d(np.zeros((B,), np.int32)),
            self._h2d(np.ones((B,), np.int32)))

    def warm_decode(self) -> None:
        """Compile the decode-side executable against inert inputs (all
        slots masked to the null page) — a 1-token warmup request picks its
        only token at prefill and retires without ever decoding, so benches
        warm the decode program explicitly.  In fused mode this compiles THE
        one fused program (decode/verify/chunk share its fixed shape).  On a
        sampling engine this advances the PRNG stream by one split, like any
        real decode dispatch would."""
        B = self.cache.num_slots
        tbl = np.zeros((B, self.cache.max_pages_per_slot), np.int32)
        if self.fused:
            _, _, self._pool, self._key = self._decode_fn(
                self.params, self._h2d(np.zeros((B, self._fused_T), np.int32)),
                self._pool, self._h2d(tbl),
                self._h2d(np.zeros((B,), np.int32)),
                self._h2d(np.ones((B,), np.int32)), self._key,
                self._h2d(np.zeros((B,), bool)))
        else:
            _, self._pool, self._key = self._decode_fn(
                self.params, self._h2d(np.zeros((B,), np.int32)), self._pool,
                self._h2d(tbl), self._h2d(np.zeros((B,), np.int32)),
                self._key, self._h2d(np.zeros((B,), bool)))
        self._decode_used = True
        # warmup is also where the live roofline arms: one abstract trace of
        # the decode-side program (cached; zero dispatches, zero programs)
        # so the drift gauge reads real from the first steady-state step
        _ = self.predicted_step_ms

    def warm_swap(self) -> None:
        """Compile the swap gather/scatter against null-page ids (all
        content lands on the never-read page 0) — benches call this in
        warmup so the first preemption swap-out OR KV-tier spill/restore
        (both ride the SAME two executables) doesn't pay a compile inside
        the timed section.  No-op unless the engine can reach them
        (optimistic admission + preempt="swap", or kv_tier on)."""
        if not ((self.optimistic and self.preempt == "swap") or self.kv_tier):
            return
        mgr = self.cache
        ids = np.zeros((mgr.max_pages_per_slot,), np.int32)
        data = self._swap_out_fn(self._pool, self._h2d(ids))
        self._swap_out_used = True
        # round-trip through host numpy so the swap-in signature matches the
        # real resume path (replicated staging uploads, not device outputs)
        staged = {n: self._h2d(np.asarray(a)) for n, a in data.items()}
        self._pool = self._swap_in_fn(self._pool, self._h2d(ids), staged)
        self._swap_in_used = True

    def _maybe_finish(self, seq: _Running,
                      finished: List[RequestOutput]) -> bool:
        reason = None
        if self.eos_token_id is not None and \
                seq.generated[-1] == self.eos_token_id:
            reason = "stop"
        elif len(seq.generated) >= seq.request.max_new_tokens:
            reason = "length"
        if reason is None:
            return False
        if self.prefix_cache:
            # finish-time registration (tier follow-on): publish the
            # GENERATED pages next to the prompt pages before the slot
            # releases, so a returning session's last reply is a prefix hit
            # (device trie or tier restore) instead of a full re-prefill.
            # KV completeness bound: `cache.lengths[slot]` counts positions
            # whose KV actually landed — (prompt ++ generated) minus the
            # final sampled token, whose KV is never computed — so the
            # registered content is exactly that written prefix, tail
            # partial page included (filled == tokens.size).
            kvlen = int(self.cache.lengths[seq.slot])
            conv = np.concatenate([
                np.asarray(seq.request.prompt, np.int32),
                np.asarray(seq.generated, np.int32)])[:kvlen]
            self.cache.register_prefix(seq.slot, conv, kvlen, upgrade=True)
        self.cache.release(seq.slot)
        self._free_slots.append(seq.slot)
        out = self._finish_output(seq.request, seq.generated, reason,
                                  seq.cached_tokens, seq.ttft_s)
        finished.append(out)
        return True

    def host_pool_bytes(self) -> int:
        """Worst-case HOST memory the unified host pool may hold — the
        declared bound `swap_pool_pages` (shared by preemption swap parking
        AND the kv_tier spilled-prefix store; disk pages are off-budget)
        times the bytes one page occupies across all layers and pool lanes
        (k + v, plus the per-token scale lanes of an int8 pool,
        `quantization.serving.kv_page_bytes`) — the number
        `tools/tpu_cost.py` audits against
        `SERVE_RESOURCE_BUDGET["host_pool_bytes"]` (JXP009; int8 pools park
        int8 pages, so their bound shrinks with the pool).  Occupancy is
        `kv_pages_swapped` + `kv_tier_pages_host`; this is the ceiling."""
        return self.swap_pool_pages * self._kv_page_bytes

    def swap_pool_bytes(self) -> int:
        """Legacy alias for `host_pool_bytes` (the PR-10 name, kept for
        external consumers — the budget it maps to is now the unified
        host-pool ceiling)."""
        return self.host_pool_bytes()

    def kv_pool_bytes(self) -> int:
        """At-rest bytes of the device KV page pool (all lanes — the number
        the quantized-serving capacity math is about: int8 pools hold the
        same token geometry in ~2-4x fewer bytes)."""
        return int(sum(int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
                       for a in self._pool.values()))

    def at_rest_bytes(self) -> Dict[str, int]:
        """Cached at-rest memory account for this engine's params, classified
        by the serving layout (`analysis.cost_model.params_at_rest` over
        `serving_param_specs` — the SAME account `tools/tpu_cost.py` audits
        under JXP006): `{replicated_bytes_per_device, sharded_bytes_per_device,
        wte_bytes}`.  Host-side arithmetic over leaf shapes — no trace, no
        dispatch, no new executable — so bench rows report the sharded-head
        memory win for free.  `wte_bytes` is the FP embedding-table size (the
        pre-shard replicated ceiling this layout retired): at mp>1 the
        per-device replicated remainder must sit strictly below it."""
        if getattr(self, "_at_rest_bytes", None) is None:
            from ..analysis.cost_model import AtRestAccount, params_at_rest
            a = AtRestAccount(max(self.mp, 1),
                              params_at_rest(self.params, self.config,
                                             self.mp))
            c = self.config
            wte_bytes = int(c.vocab_size * c.hidden_size
                            * np.dtype(c.dtype).itemsize)
            self._at_rest_bytes = {
                "replicated_bytes_per_device": int(a.param_bytes_replicated),
                "sharded_bytes_per_device": int(a.param_bytes_sharded_per_device),
                "wte_bytes": wte_bytes,
            }
        return dict(self._at_rest_bytes)

    # ---- health & perf signal plane ---------------------------------------
    @property
    def predicted_step_ms(self) -> float:
        """Static roofline prediction for the decode-side program at this
        engine's shapes (`analysis.cost_model.engine_step_cost` over the
        nameplate `device_spec()`), traced abstractly ONCE and cached — no
        dispatch, no new executable, program counts untouched.
        `warm_decode()` takes the trace during warmup so the drift gauge is
        live from the first steady-state step; reading the property earlier
        pays the one-off trace right here."""
        if self._predicted_ms is None:
            from ..analysis.cost_model import device_spec, engine_step_cost
            self._predicted_ms = engine_step_cost(self).predicted_ms(
                device_spec(), mp=self.mp)
        return self._predicted_ms

    def _roofline_drift(self) -> float:
        """measured_step_ms EWMA / predicted roofline ms — the live drift
        gauge.  0.0 until BOTH exist (never triggers the trace itself: a
        metrics scrape must stay a pure read)."""
        if not self._predicted_ms or not self._measured_ewma_ms:
            return 0.0
        return self._measured_ewma_ms / self._predicted_ms

    def _note_steady_state(self, dur_s: float) -> None:
        """Per-busy-step bookkeeping of the live perf signals: the
        measured-step EWMA, the drift-band alert counter (TRANSITIONS into
        violation, not steps spent there) and the steady-state recompile
        anomaly (decode-side executable count growing after the first busy
        step fixed the baseline — a fixed-shape engine must never do that)."""
        ms = dur_s * 1e3
        self._measured_ewma_ms = ms if self._measured_ewma_ms is None else \
            _EWMA_ALPHA * ms + (1.0 - _EWMA_ALPHA) * self._measured_ewma_ms
        try:
            n = self._decode_fn._cache_size()
        except AttributeError:
            n = 1 if self._decode_used else 0
        if self._exec_baseline is None:
            self._exec_baseline = n
        elif n > self._exec_baseline:
            self._ss_recompiles.inc(n - self._exec_baseline)
            self._exec_baseline = n
        drift = self._roofline_drift()
        lo, hi = SERVE_SLO["roofline_drift_band"]
        bad = bool(drift) and not (lo <= drift <= hi)
        if bad and not self._drift_violation:
            self._roofline_alerts.inc()
        self._drift_violation = bad

    def _burn_rate(self, window_s: float) -> float:
        """Deadline-attainment burn over one window (`health.burn_rate`
        semantics): in-window miss fraction over the declared error budget."""
        from .health import burn_rate
        return burn_rate(self._rw_deadline_req, self._rw_deadline_met,
                         window_s, SERVE_SLO["deadline_attainment_target"])

    def health(self) -> Dict[str, object]:
        """The engine's health report — state (ok/degraded/overloaded),
        numeric code, per-signal detail and reasons — evaluated against
        `analysis.registry.SERVE_SLO` from host state only (see
        `inference.health`).  The obs server's ``/healthz`` serves it with
        200/503 semantics; `stats()["health"]` carries the compact pair."""
        return evaluate_engine_health(self)

    def _health_code(self) -> float:
        """The `engine_health` gauge read: 0 ok / 1 degraded / 2 overloaded.
        A health evaluation that cannot run at all reads as the worst state
        — a wedged engine must never scrape as healthy — and the exception
        is preserved for ``/healthz``, which re-evaluates and reports it."""
        try:
            return float(self.health()["code"])
        except Exception:
            return float(max(HEALTH_CODES.values()))

    def run(self) -> Dict[int, RequestOutput]:
        """Drain the queue: step until every request completes.  Returns
        {request_id: RequestOutput} for everything finished so far."""
        while self.has_work:
            self.step()
        return dict(self._outputs)

    @property
    def has_work(self) -> bool:
        return bool(self._queue or self._running or self._prefilling or
                    self._inflight is not None or self._orphan_finished)

    # ---- serving-loop surface (front door / fleet) ------------------------
    # One replica = one LLMEngine + one background step() thread.  Every
    # entry point below takes `_serve_lock`, so a fleet router (or the HTTP
    # front door's event loop) can submit/stream/abort from any thread while
    # the loop steps; the lock is re-entrant, so single-threaded callers
    # (benches, tests) can keep driving step()/run() directly.

    def start_loop(self, idle_wait_s: float = 0.002) -> None:
        """Start the background step() loop (idempotent).  The loop parks on
        the serve condition when idle — submit()/cancel() wake it — and
        re-checks `has_work` every `idle_wait_s` as a fallback heartbeat."""
        with self._serve_lock:
            if self._serve_thread is not None and \
                    self._serve_thread.is_alive():
                return
            self._serve_stop = False
            self._serve_error = None
            self._serve_thread = threading.Thread(
                target=self._serve_loop, args=(float(idle_wait_s),),
                name="llm-serve-loop", daemon=True)
            self._serve_thread.start()

    def stop_loop(self, timeout: float = 30.0) -> None:
        """Stop the loop thread (idempotent; queued work stays queued —
        call drain() first for a clean flush)."""
        with self._serve_cond:
            self._serve_stop = True
            self._serve_cond.notify_all()
        t = self._serve_thread
        if t is not None and t.is_alive():
            t.join(timeout)
        self._serve_thread = None

    @property
    def loop_running(self) -> bool:
        t = self._serve_thread
        return t is not None and t.is_alive()

    def _serve_loop(self, idle_wait_s: float) -> None:
        while True:
            with self._serve_cond:
                if self._serve_stop:
                    return
                if not self.has_work:
                    self._serve_cond.wait(idle_wait_s)
                    continue
                try:
                    self.step()
                except BaseException as exc:    # noqa: BLE001 — surfaced to
                    self._serve_error = exc     # result()/drain() waiters
                    self._serve_cond.notify_all()
                    return
                self._serve_cond.notify_all()

    def _check_loop(self) -> None:
        if self._serve_error is not None:
            raise RuntimeError("serve loop died") from self._serve_error

    def submit(self, prompt, **kwargs) -> int:
        """Thread-safe add_request(): enqueue under the serve lock and wake
        the loop.  Same signature/validation/rejection semantics."""
        with self._serve_cond:
            self._check_loop()
            rid = self.add_request(prompt, **kwargs)
            self._serve_cond.notify_all()
            return rid

    def cancel(self, request_id: int) -> bool:
        """Thread-safe abort() (client disconnect propagation: frees the
        request's pages immediately)."""
        with self._serve_cond:
            ok = self.abort(request_id)
            if ok:
                self._serve_cond.notify_all()
            return ok

    def progress(self, request_id: int) -> Dict[str, object]:
        """Streaming snapshot: the tokens a request has produced so far and
        whether it finished (`output` carries the final RequestOutput then).
        Under double-buffering the snapshot may lag the device by one
        in-flight step — exact at finish, which is what streaming needs."""
        with self._serve_lock:
            out = self._outputs.get(request_id)
            if out is not None:
                return {"known": True, "finished": True,
                        "token_ids": list(out.token_ids), "output": out}
            for seq in self._running.values():
                if seq.request.request_id == request_id:
                    return {"known": True, "finished": False,
                            "token_ids": list(seq.generated), "output": None}
            for st in self._prefilling.values():
                if st.request.request_id == request_id:
                    return {"known": True, "finished": False,
                            "token_ids": list(st.prior or []), "output": None}
            rec = self._preempted.get(request_id)
            if rec is not None:
                return {"known": True, "finished": False,
                        "token_ids": list(rec.get("generated") or []),
                        "output": None}
            for req in self._queue:
                if req.request_id == request_id:
                    return {"known": True, "finished": False,
                            "token_ids": [], "output": None}
            return {"known": False, "finished": False,
                    "token_ids": [], "output": None}

    def result(self, request_id: int,
               timeout: Optional[float] = None) -> Optional[RequestOutput]:
        """Block until `request_id` finishes (or `timeout` elapses — then
        None).  With the loop running this waits on its step notifications;
        without it, the caller's own thread drives step() inline, so the
        surface also works single-threaded."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._serve_cond:
            while True:
                out = self._outputs.get(request_id)
                if out is not None:
                    return out
                self._check_loop()
                if not self.loop_running:
                    if not self.has_work:
                        return None
                    self.step()
                    continue
                rem = 0.5 if deadline is None \
                    else deadline - time.monotonic()
                if rem <= 0.0:
                    return None
                self._serve_cond.wait(rem)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait until the engine is fully idle (False on timeout)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._serve_cond:
            while self.has_work:
                self._check_loop()
                if not self.loop_running:
                    self.step()
                    continue
                rem = 0.5 if deadline is None \
                    else deadline - time.monotonic()
                if rem <= 0.0:
                    return False
                self._serve_cond.wait(rem)
            return True

    def queue_depth(self) -> int:
        """Live request count (queued + prefilling + decoding) — the
        router's load signal, cheap enough to read per routing decision."""
        with self._serve_lock:
            return (len(self._queue) + len(self._prefilling) +
                    len(self._running))

    def probe_affinity(self, tokens) -> Dict[str, int]:
        """Router probe: longest cached prefix of `tokens` this replica
        holds, split into total matched tokens and the portion that is
        tier-resident (host/disk — a hit there restores via one scatter
        instead of re-prefilling).  Pure read — no LRU touch, no COW, no
        refcount; the admission-time `_match` in step() remains the only
        mutating matcher."""
        if not self.prefix_cache:
            return {"cached_tokens": 0, "tier_tokens": 0}
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        with self._serve_lock:
            full, partial = self.cache._match(tokens)
        page = self.cache.page_size
        matched = len(full) * page
        tier = sum(page for n in full if n.page < 0)
        if partial is not None:
            node, j = partial
            matched += j
            if node.page < 0:
                tier += j
        return {"cached_tokens": int(matched), "tier_tokens": int(tier)}

    # ---- observability ----------------------------------------------------
    @contextlib.contextmanager
    def trace(self, dir_name: str, device: bool = True):
        """Capture a serving trace window into `dir_name`:

        - ``host_trace.json`` — chrome-tracing export of the engine's host
          phase spans (`ENGINE_SPANS`: admit, prefill/verify/decode dispatch,
          proposer scan, acceptance, sample sync) recorded through
          `paddle_tpu.profiler.RecordEvent`, so it opens in the same
          ``chrome://tracing`` / Perfetto flow as the trainer's traces;
        - ``step_timeline.json`` — the step-trace ring as captured at exit;
        - ``metrics.json`` — a full `metrics.snapshot()` (plus the proposer's
          drafting telemetry when available);
        - ``device/`` — a `jax.profiler` trace (TensorBoard XPlane) when
          `device=True` and the runtime supports capture; spans also forward
          as TraceAnnotations so engine phases land in the device timeline.

        Tracing is additive-only: no executable recompiles (the spans wrap
        host code), and the spans themselves exist only inside this window.
        When a user `Profiler` is ALREADY recording, this window rides it
        instead of starting its own (a nested start would wipe the outer
        profiler's event buffer and a nested stop would end its recording):
        the outer recording continues untouched and ``host_trace.json``
        snapshots everything collected so far, engine spans included.
        """
        os.makedirs(dir_name, exist_ok=True)
        prof = None
        if not _prof.is_recording():
            prof = _prof.Profiler(timer_only=not device,
                                  log_dir=os.path.join(dir_name, "device"))
            prof.start()
        self._tracing = True
        try:
            yield prof
        finally:
            self._tracing = False
            if prof is not None:
                prof.stop()     # the event buffer survives stop()
            _prof.dump_chrome_trace(os.path.join(dir_name,
                                                 "host_trace.json"))
            with open(os.path.join(dir_name, "step_timeline.json"), "w") as f:
                json.dump(self.step_trace(), f)
            snap = self.metrics.snapshot()
            snap["proposer"] = getattr(self.proposer, "stats", dict)()
            with open(os.path.join(dir_name, "metrics.json"), "w") as f:
                json.dump(snap, f)

    def stats(self) -> Dict[str, object]:
        def execs(fn, fallback):
            # only the expected miss — a plain-jit wrapper without
            # _cache_size — falls back to the tracked approximation; a real
            # bug INSIDE _cache_size must raise, not be silently counted
            try:
                return fn._cache_size()
            except AttributeError:
                return fallback
        cached = self._prefix_cached_tokens.value
        computed = self._prefilled_tokens.value
        spec_events = self._spec_events.value
        try:
            health = self.health()
        except Exception as e:
            # stats() feeds the crash postmortem (debug_bundle) and /stats:
            # a signal plane wrecked by the very crash being postmortemed
            # must degrade to an "error" health entry, not take the whole
            # surface down (same contract as /healthz and the gauge)
            health = {"state": "error", "code": max(HEALTH_CODES.values()),
                      "reasons": [f"health evaluation failed: "
                                  f"{type(e).__name__}: {e}"],
                      "burn_rates": {}}
        # fused mode: _decode_fn IS the one fused program (decode-side count
        # 1); the standalone verify/chunk programs are never built (None)
        return {
            "decode_executables": execs(self._decode_fn,
                                        1 if self._decode_used else 0),
            "verify_executables": 0 if self._verify_fn is None else
                                  execs(self._verify_fn,
                                        1 if self._verify_steps.value else 0),
            "prefill_executables": execs(self._prefill_fn,
                                         len(self._seen_buckets)) +
                                   (0 if self._chunk_fn is None else
                                    execs(self._chunk_fn,
                                          1 if self._chunk_used else 0)),
            "copy_executables": execs(self._copy_fn,
                                      1 if self._copy_used else 0),
            "swap_executables": execs(self._swap_out_fn,
                                      1 if self._swap_out_used else 0) +
                                execs(self._swap_in_fn,
                                      1 if self._swap_in_used else 0),
            "buckets": list(self.buckets),
            "prefill_chunk": self.prefill_chunk,
            "spec_len": self.spec_len,
            "mp": self.mp,
            "role": self.role,
            "engine_steps": self._step_idx,
            "decode_iterations": self._decode_iters.value,
            "decode_tokens": self._decode_tokens.value,
            "verify_steps": self._verify_steps.value,
            # per-slot verify events that carried a draft — the denominator
            # of accepted_per_step, reported so benches can recompute it
            "spec_events": spec_events,
            "spec_drafted_tokens": self._spec_drafted.value,
            "spec_accepted_tokens": self._spec_accepted.value,
            "spec_emitted_tokens": self._spec_emitted.value,
            "spec_backoffs": self._spec_backoffs.value,
            # mean tokens emitted per drafted verify event (>= 1.0; 1.0 means
            # drafts never helped, spec_len+1 means every draft fully accepted)
            "accepted_per_step": self._spec_emitted.value / spec_events
                                 if spec_events else 0.0,
            "prefill_chunks": self._prefill_chunks.value,
            "prefilled_tokens": computed,
            "prefix_cached_tokens": cached,
            "prefix_hit_requests": self._prefix_hit_requests.value,
            "prefix_hit_rate": cached / (cached + computed)
                               if cached + computed else 0.0,
            "cow_page_copies": self._cow_copies.value,
            "pages_in_use": self.cache.pages_in_use(),
            "pages_free": self.cache.num_free_pages,
            "pages_evictable": self.cache.num_evictable_pages,
            "prefix_evictions": self.cache.prefix_evictions,
            "kv_token_capacity": self.cache.token_capacity(),
            "dense_token_footprint": self.cache.num_slots * self.max_model_len,
            "queued": len(self._queue),
            "prefilling": len(self._prefilling),
            "running": len(self._running),
            "finished_requests": self._finished_requests.value,
            "aborted_requests": self._aborted_requests.value,
            # overload surface: admission/preempt modes + the counters the
            # oversubscription bench and dashboards consume
            "admission": self.admission,
            "preempt": self.preempt,
            "preemptions": self._preemptions.value,
            "preempt_swaps": self._preempt_swaps.value,
            "preempt_recomputes": self._preempt_recomputes.value,
            "swapped_pages": self._swapped_pages_c.value,
            "swap_ms": self._swap_ms_c.value,
            "recomputed_tokens": self._recomputed_tokens.value,
            "timeouts": self._timeouts.value,
            "rejected_requests": self._rejected_requests.value,
            "intake_swap_rejects": self._intake_swap_rejects.value,
            "swapped": self.cache.swapped_requests,
            "kv_pages_swapped": self.cache.swapped_page_count,
            "kv_pool_pressure": round(self.cache.pool_pressure(), 4),
            # KV-tier surface (ROADMAP item 3): spilled-prefix occupancy per
            # tier level + the spill/restore traffic and rolling-hash
            # partial-index hits the multi-turn bench keys on
            "kv_tier": {
                "enabled": self.kv_tier,
                "spill_dir": self.spill_dir,
                "pages_host": self.cache.tier_pages_host,
                "pages_disk": self.cache.tier_pages_disk,
                "spills": self._tier_spills.value,
                "restores": self._tier_restores.value,
                "restored_tokens": self._tier_restored_tokens.value,
                "partial_page_hits": self._partial_hits.value,
                "disk_spills": 0 if self.cache._tier is None
                               else self.cache._tier.disk_spills,
                "disk_restores": 0 if self.cache._tier is None
                                 else self.cache._tier.disk_restores,
                "tier_drops": 0 if self.cache._tier is None
                              else self.cache._tier.tier_drops,
                # disaggregated handoff surface (ROADMAP item 2)
                "store": self.cache._tier is not None and
                         self.cache._tier.store is not None,
                "handoff_exports": self._handoff_exports.value,
                "handoff_pages": self._handoff_pages.value,
                "handoff_tokens": self._handoff_tokens.value,
                "store_nodes_restored": self._store_restored_nodes,
            },
            # quantized serving surface: the knobs and the at-rest pool bytes
            # the capacity math is about (None = full-precision default)
            "weight_dtype": self.weight_dtype,
            "kv_dtype": self.kv_dtype,
            "kv_pool_bytes": self.kv_pool_bytes(),
            # SLO surface (PR-10 deadlines made end-to-end): attainment over
            # every retired deadline-bearing request (timeouts/aborts are
            # misses in the denominator, still excluded from the latency
            # histograms) + final-output tokens per priority class
            "slo": {
                "deadline_requests": self._deadline_requests.value,
                "deadline_met": self._deadline_met.value,
                "deadline_attainment":
                    self._deadline_met.value / self._deadline_requests.value
                    if self._deadline_requests.value else None,
                "goodput_tokens_by_priority":
                    {p: c.value
                     for p, c in sorted(self._goodput_prio.items())},
            },
            # windowed rates (health & signals PR): sliding-window views of
            # the counters above — tokens/s etc. over ~10s/1m/5m, the
            # router's freshness-weighted signal (also pull gauges, e.g.
            # `tokens_per_sec_10s`, in the exposition)
            "rates": {rw.name: rw.rates() for rw in self._rate_surface},
            # compact health pair (full per-signal report via health());
            # state folds SLO burn + pressure + admission saturation +
            # recompile anomalies against analysis.registry.SERVE_SLO
            "health": {
                "state": health["state"],
                "code": health["code"],
                "reasons": health["reasons"],
                "burn_rates": health["burn_rates"],
            },
            # live roofline: the PR-8 static prediction next to the
            # steady-state EWMA it is now compared against every step
            "roofline": {
                "predicted_step_ms": self._predicted_ms,    # None until armed
                "measured_step_ms": self._measured_ewma_ms,
                "drift": self._roofline_drift() or None,
                "drift_alerts": self._roofline_alerts.value,
                "steady_state_recompiles": self._ss_recompiles.value,
            },
            # latency distributions (engine-side histograms; seconds) — the
            # serving SLO surface: benches report p50/p99 straight from here
            "latency": {
                "queue_s": self._h_queue.summary(),
                "ttft_s": self._h_ttft.summary(),
                "tpot_s": self._h_tpot.summary(),
                "e2e_s": self._h_e2e.summary(),
                "step_s": self._h_step.summary(),
            },
        }

    # ---- postmortem debug bundle ------------------------------------------
    def _request_states(self, finished_limit: int = 64) \
            -> Dict[str, Dict[str, object]]:
        """Per-request state map for the debug bundle: every live request
        (queued — including preempted/swapped resumes waiting at the head —
        prefilling, running) plus the last `finished_limit` retired ones,
        each with its scheduler coordinates and its trace timeline (empty
        with tracing off).  Keys are request-id strings (JSON object keys)."""
        def base(req, state, **extra):
            tr = self._trace_for(req.request_id)
            d = {"state": state, "prompt_len": int(req.prompt.size),
                 "max_new_tokens": int(req.max_new_tokens),
                 "priority": int(req.priority),
                 "deadline": req.deadline,
                 "events": list(tr.events) if tr is not None else []}
            d.update(extra)
            return d

        out: Dict[str, Dict[str, object]] = {}
        # snapshot the live containers: an obs-server handler thread walks
        # them concurrently with step()'s mutations, and iterating the deque/
        # dicts directly would raise mid-scrape ("mutated during iteration")
        for req in list(self._queue):
            rec = self._preempted.get(req.request_id)
            out[str(req.request_id)] = base(
                req, "queued",
                preempted_kind=None if rec is None else rec["kind"],
                banked_tokens=0 if rec is None else len(rec["generated"]))
        for slot, st in list(self._prefilling.items()):
            out[str(st.request.request_id)] = base(
                st.request, "prefilling", slot=slot, filled=int(st.filled),
                effective_prompt_len=int(st.prompt.size))
        for slot, seq in list(self._running.items()):
            out[str(seq.request.request_id)] = base(
                seq.request, "running", slot=slot,
                n_generated=len(seq.generated),
                kv_len=int(self.cache.lengths[slot]),
                spec_off=seq.spec_off)
        # last-N retired requests WITHOUT materializing the all-time output
        # ledger (unbounded on a long-running server): walk the insertion
        # order backwards, then flip to oldest-first
        recent = list(itertools.islice(reversed(self._outputs), finished_limit))
        for rid in reversed(recent):
            o = self._outputs[rid]
            out[str(rid)] = {
                "state": "finished", "finish_reason": o.finish_reason,
                "prompt_len": int(np.asarray(o.prompt).size),
                "n_generated": len(o.token_ids),
                "cached_tokens": int(o.cached_tokens),
                "events": list(o.trace.events) if o.trace is not None else [],
            }
        return out

    def debug_bundle(self, finished_limit: int = 64) -> Dict[str, object]:
        """The postmortem snapshot the obs server serves as ``GET /debug``
        and `dump_debug_bundle` writes to disk: engine/pool configuration,
        page-partition levels, per-request states with their trace
        timelines, the last-N step-trace ring, `stats()` and a full metrics
        snapshot — everything "what was the engine doing when it died" needs,
        all plain JSON (prompt/KV CONTENT deliberately excluded).  Safe to
        call mid-flight: it reads host scheduler state only, no device sync,
        no executable dispatch."""
        mgr = self.cache
        return {
            "version": 1,
            "t": self._now(),
            "engine": {
                "num_slots": mgr.num_slots, "page_size": mgr.page_size,
                "num_pages": mgr.num_pages,
                "max_model_len": self.max_model_len,
                "prefill_chunk": self.prefill_chunk,
                "spec_len": self.spec_len, "fused": self.fused,
                "double_buffer": self.double_buffer,
                "admission": self.admission, "preempt": self.preempt,
                "kv_tier": self.kv_tier, "spill_dir": self.spill_dir,
                "mp": self.mp, "weight_dtype": self.weight_dtype,
                "kv_dtype": self.kv_dtype,
                "request_tracing": self._req_tracing,
                "inflight": self._inflight is not None,
            },
            "pool": {
                "pages_in_use": mgr.pages_in_use(),
                "pages_free": mgr.num_free_pages,
                "pages_evictable": mgr.num_evictable_pages,
                "kv_pages_swapped": mgr.swapped_page_count,
                "swapped_requests": mgr.swapped_requests,
                "pool_pressure": round(mgr.pool_pressure(), 4),
                "kv_pool_bytes": self.kv_pool_bytes(),
                "swap_pool_pages": self.swap_pool_pages,
                "kv_tier_pages_host": mgr.tier_pages_host,
                "kv_tier_pages_disk": mgr.tier_pages_disk,
            },
            "requests": self._request_states(finished_limit),
            "step_trace": self.step_trace(),
            "stats": self.stats(),
            "metrics": self.metrics.snapshot(),
        }

    def dump_debug_bundle(self, dir_name: str,
                          finished_limit: int = 64) -> str:
        """Write `debug_bundle()` to ``<dir_name>/debug_bundle.json`` and
        return the path — `bench_serve.py` calls this automatically on a
        crash or a drain-invariant failure, and operators call it on demand
        (or hit the obs server's ``/debug``) for a live snapshot."""
        os.makedirs(dir_name, exist_ok=True)
        path = os.path.join(dir_name, "debug_bundle.json")
        with open(path, "w") as f:
            json.dump(self.debug_bundle(finished_limit), f)
        return path
