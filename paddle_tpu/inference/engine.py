"""Continuous-batching LLM serving engine.

Reference lineage: the reference repo serves via `fluid/inference`'s
AnalysisPredictor + PaddleNLP `generation` — a one-shot, whole-batch API.  For
"heavy traffic from millions of users" (ROADMAP north star) that shape is
wrong: every (batch, prompt_len, max_new) combination compiles a fresh
program, cache memory is dense `B x max_seq_len`, and a long request blocks
the batch.  This engine follows vLLM's paged KV cache (Kwon et al., SOSP 2023)
and Orca's iteration-level scheduling (Yu et al., OSDI 2022), under the same
"one jitted step, static shapes" discipline as the pretraining hot loop:

- **Paged KV cache** — one static pool of `[num_pages, page_size, KVH, hd]`
  pages per layer (`models.gpt.init_paged_cache`) + per-slot page tables
  (`inference.cache.PagedKVCache`): memory scales with live tokens, pages
  recycle as requests retire.
- **Slot-indexed decode** — ONE compiled decode program of fixed batch
  `num_slots` (`models.gpt.decode_step_paged`) serves a churning request set;
  retired slots are refilled without recompiling.
- **Prefix cache** (vLLM copy-on-write page sharing) — prompt pages are
  content-hashed at page granularity as their KV lands; admission maps the
  longest cached page-aligned prefix read-only into the new slot's table
  (refcount++), COW-copies a matched partial page (one jitted page-copy
  executable), and only prefills the uncached tail.  Retired prefixes stay
  matchable until LRU-evicted under pool pressure.
- **Chunked prefill** (Sarathi-Serve, Agrawal et al. OSDI 2024) — prompts
  prefill in fixed-size chunks through ONE compiled chunk executable
  (`models.gpt.prefill_chunk_paged`, any q_offset), and `step()` interleaves
  at most one chunk with each decode iteration: a 4k-token prompt no longer
  stalls every decode slot for a whole bucket-padded pass, and the prefill
  program count collapses from #buckets to <= 2.  The legacy bucketed
  one-shot path (`prefill_paged`, power-of-2 buckets) remains the default for
  uncached prompts when `prefill_chunk=None`.
- **Scheduler** — each `step()` admits queued requests into free slots
  (reservation-based page admission with prefix matching), advances at most
  one prefill chunk, runs one decode iteration over all fully-prefilled
  slots, and retires finished sequences (EOS or max_new_tokens), returning
  their pages to the refcounted pool.

`bench_serve.py` replays a Poisson request stream through this engine and
reports decode tokens/s/chip, TTFT percentiles, prefix-cache hit rate and
compiled-program counts.
"""
from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import gpt as gpt_mod
from .cache import PagedKVCache


@dataclasses.dataclass
class Request:
    """One generation request: prompt token ids + a decode budget."""
    prompt: np.ndarray
    max_new_tokens: int = 16
    request_id: int = -1
    t_enqueue: float = 0.0


@dataclasses.dataclass
class RequestOutput:
    request_id: int
    prompt: np.ndarray
    token_ids: List[int]            # generated tokens (prompt excluded)
    finish_reason: str              # "stop" (EOS) | "length" (budget) | "abort"
    cached_tokens: int = 0          # prompt tokens served from the prefix cache
    ttft_s: Optional[float] = None  # enqueue -> first generated token

    @property
    def tokens(self) -> np.ndarray:
        """prompt + generated, the `generate()`-compatible view."""
        return np.concatenate(
            [np.asarray(self.prompt, np.int64), np.asarray(self.token_ids,
                                                           np.int64)])


@dataclasses.dataclass
class _Running:
    request: Request
    slot: int
    generated: List[int]
    cached_tokens: int = 0
    ttft_s: Optional[float] = None


@dataclasses.dataclass
class _Prefilling:
    """A slot whose prompt KV is still landing: `filled` prompt tokens are in
    pages (prefix-cache hits + completed chunks); the slot joins the decode
    set only once filled == len(prompt)."""
    request: Request
    slot: int
    filled: int
    cached_tokens: int


def _pow2_buckets(lo: int, hi: int) -> List[int]:
    out = []
    b = lo
    while b <= hi:
        out.append(b)
        b *= 2
    return out


class LLMEngine:
    """Continuous-batching serving engine over the functional GPT core.

    params/config: the `models.gpt` pytree + GPTConfig.  `num_slots` is the
    fixed decode batch; `num_pages`/`page_size` size the KV pool (default pool
    is half of the dense `num_slots * max_model_len` footprint — the paged
    cache's whole point is that this still serves full-length traffic as long
    as *live* tokens fit).  Greedy by default; temperature/top_k compile the
    sampling variant of the same executables.

    `prefix_cache=True` shares prompt pages across requests copy-on-write;
    `prefill_chunk=N` switches prompt processing from the bucketed one-shot
    ladder to N-token chunks interleaved one-per-step with decode.  Both are
    scheduler-level: the decode executable, page pool and table shapes are
    identical in every mode.
    """

    def __init__(self, params, config: gpt_mod.GPTConfig, *,
                 num_slots: int = 4, page_size: int = 16,
                 num_pages: Optional[int] = None,
                 max_model_len: Optional[int] = None,
                 prefill_buckets: Optional[List[int]] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache: bool = True,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 seed: int = 0):
        self.params = params
        self.config = config
        self.eos_token_id = eos_token_id
        max_model_len = max_model_len or config.max_seq_len
        if max_model_len % page_size:
            raise ValueError("max_model_len must be a multiple of page_size")
        if not config.use_rope and max_model_len > config.max_seq_len:
            # learned positions: jnp.take clamps past wpe's last row, which
            # would be silently wrong — generate() raises here too
            raise ValueError(
                f"max_model_len {max_model_len} exceeds max_seq_len "
                f"{config.max_seq_len} (learned positions)")
        self.max_model_len = max_model_len
        max_pages_per_slot = max_model_len // page_size
        if num_pages is None:
            # default: half the dense footprint (+ the null page)
            num_pages = max(2, num_slots * max_pages_per_slot // 2 + 1)
        if prefill_buckets is None:
            prefill_buckets = _pow2_buckets(page_size, max_model_len)
            if not prefill_buckets or prefill_buckets[-1] != max_model_len:
                # non-power-of-2 max_model_len: cover the top tokens too
                prefill_buckets.append(max_model_len)
        self.buckets = sorted(prefill_buckets)
        for b in self.buckets:
            if b % page_size or b > max_model_len:
                raise ValueError(f"bucket {b} incompatible with page_size "
                                 f"{page_size} / max_model_len {max_model_len}")
        if prefill_chunk is not None and not 1 <= prefill_chunk <= max_model_len:
            raise ValueError(f"prefill_chunk {prefill_chunk} outside "
                             f"[1, {max_model_len}]")
        self.prefill_chunk = prefill_chunk
        self.chunked = prefill_chunk is not None
        # chunk width also serves prefix-hit tails in bucketed mode, where the
        # largest bucket bounds any tail in one call
        self._chunk = prefill_chunk if self.chunked else self.buckets[-1]
        self.prefix_cache = prefix_cache
        self.cache = PagedKVCache(num_pages, page_size, num_slots,
                                  max_pages_per_slot)
        self._pool = gpt_mod.init_paged_cache(config, num_pages, page_size)
        self._queue: deque = deque()
        self._running: Dict[int, _Running] = {}
        self._prefilling: Dict[int, _Prefilling] = {}   # slot -> state, FIFO
        self._free_slots = list(range(num_slots - 1, -1, -1))
        self._ids = itertools.count()
        self._key = jax.random.key(seed)
        self._outputs: Dict[int, RequestOutput] = {}

        sample = bool(temperature and temperature > 0.0)

        def pick(logits, key):
            # gpt.sample_token is shared with generate() — parity by construction
            return gpt_mod.sample_token(logits, key, sample=sample,
                                        temperature=temperature, top_k=top_k)

        cfg = config

        def decode_impl(params, tokens, pool, table, lengths, key):
            logits, pool = gpt_mod.decode_step_paged(params, tokens, pool,
                                                     table, lengths, cfg)
            nxt, key = pick(logits, key)
            return nxt, pool, key

        def prefill_impl(params, ids, pool, pages, length, key):
            logits, pool = gpt_mod.prefill_paged(params, ids, cfg, pool,
                                                 pages, length)
            first, key = pick(logits, key)
            return first, pool, key

        def chunk_impl(params, ids, pool, table, q_offset, valid, key):
            logits, pool = gpt_mod.prefill_chunk_paged(params, ids, cfg, pool,
                                                       table, q_offset, valid)
            tok, key = pick(logits, key)
            return tok, pool, key

        def copy_impl(pool, src, dst):
            # COW page copy: one [page, KVH, hd] slab per layer, src -> dst
            return {n: a.at[:, dst].set(a[:, src]) for n, a in pool.items()}

        # pool donated: each step updates it in place instead of copying the
        # whole page pool every iteration
        self._decode_fn = jax.jit(decode_impl, donate_argnums=(2,))
        self._prefill_fn = jax.jit(prefill_impl, donate_argnums=(2,))
        self._chunk_fn = jax.jit(chunk_impl, donate_argnums=(2,))
        self._copy_fn = jax.jit(copy_impl, donate_argnums=(0,))
        self._seen_buckets = set()
        self._chunk_used = False
        self._copy_used = False
        self.reset_counters()

    def reset_counters(self) -> None:
        """Zero the throughput/prefix counters (stats(), not executables) —
        benches call this after warmup so compile-time traffic is excluded."""
        self._decode_iters = 0
        self._decode_tokens = 0         # per-iteration ACTIVE slots summed
        self._prefill_chunks = 0
        self._prefilled_tokens = 0      # prompt tokens actually computed
        self._prefix_cached_tokens = 0  # prompt tokens served from the cache
        self._prefix_hit_requests = 0
        self._cow_copies = 0
        self.cache.prefix_evictions = 0

    # ---- request intake ---------------------------------------------------
    def add_request(self, prompt, max_new_tokens: int = 16) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        if not self.chunked and prompt.size > self.buckets[-1]:
            raise ValueError(f"prompt length {prompt.size} exceeds largest "
                             f"prefill bucket {self.buckets[-1]}")
        total = prompt.size + max_new_tokens
        if total > self.max_model_len:
            raise ValueError(f"prompt + max_new_tokens = {total} exceeds "
                             f"max_model_len {self.max_model_len}")
        rid = next(self._ids)
        self._queue.append(Request(prompt, max_new_tokens, rid,
                                   time.perf_counter()))
        return rid

    def abort(self, request_id: int) -> bool:
        """Cancel a queued or in-flight request and free/deref its pages
        immediately (a stuck client no longer leaks its reservation until
        max_new_tokens runs out).  Shared prefix pages are only
        deref-counted; the request lands in the outputs map with
        finish_reason="abort" and whatever tokens it had produced.  Returns
        False when the id is unknown or already finished."""
        for req in self._queue:
            if req.request_id == request_id:
                self._queue.remove(req)
                self._finish_output(req, [], "abort", 0, None)
                return True
        for slot, st in list(self._prefilling.items()):
            if st.request.request_id == request_id:
                del self._prefilling[slot]
                self.cache.release(slot)
                self._free_slots.append(slot)
                self._finish_output(st.request, [], "abort",
                                    st.cached_tokens, None)
                return True
        for slot, seq in list(self._running.items()):
            if seq.request.request_id == request_id:
                del self._running[slot]
                self.cache.release(slot)
                self._free_slots.append(slot)
                self._finish_output(seq.request, seq.generated, "abort",
                                    seq.cached_tokens, seq.ttft_s)
                return True
        return False

    def _finish_output(self, req: Request, token_ids: List[int], reason: str,
                       cached: int, ttft: Optional[float]) -> RequestOutput:
        out = RequestOutput(req.request_id, req.prompt, token_ids, reason,
                            cached, ttft)
        self._outputs[out.request_id] = out
        return out

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"no bucket for prompt length {n}")

    # ---- scheduler --------------------------------------------------------
    def step(self) -> List[RequestOutput]:
        """One engine iteration: admit queued requests into free slots
        (prefix-cache matching + page reservation), advance at most ONE
        prefill chunk, then one decode step over every fully-prefilled slot.
        Returns the requests that finished this iteration."""
        finished: List[RequestOutput] = []
        self._admit(finished)
        self._prefill_tick(finished)
        if self._running:
            self._decode_iter(finished)
        return finished

    def _admit(self, finished: List[RequestOutput]) -> None:
        mgr = self.cache
        while self._queue and self._free_slots:
            req = self._queue[0]
            total = req.prompt.size + req.max_new_tokens
            tokens = req.prompt if self.prefix_cache else None
            slot = self._free_slots[-1]
            try:
                # one shot: the prefix match and the reservation happen in the
                # same call (a failed attempt rolls its sharing back), instead
                # of re-hashing the prompt in a can_allocate probe every step
                row, matched, cow = mgr.allocate_prefixed(slot, total, tokens)
            except RuntimeError:            # out of KV pages
                if not self._running and not self._prefilling and \
                        mgr.pages_in_use() == 0:
                    # nothing will ever free: even with every cached prefix
                    # evicted the footprint exceeds the pool
                    raise ValueError(
                        f"request {req.request_id} needs "
                        f"{mgr.pages_needed(total)} pages but the pool only "
                        f"has {mgr.num_pages - 1}; raise num_pages")
                break                       # wait for pages to free up
            self._queue.popleft()
            self._free_slots.pop()
            if cow is not None:
                # the matched partial page is shared: copy it into the slot's
                # own page before anything is appended into it
                src, dst = cow
                self._pool = self._copy_fn(self._pool,
                                           jnp.asarray(src, jnp.int32),
                                           jnp.asarray(dst, jnp.int32))
                self._cow_copies += 1
                self._copy_used = True
            if matched:
                self._prefix_cached_tokens += matched
                self._prefix_hit_requests += 1
            lp = req.prompt.size
            if not self.chunked and matched == 0:
                # legacy one-shot bucketed prefill, synchronous at admission
                bucket = self._bucket_for(lp)
                ids = np.zeros((1, bucket), np.int32)
                ids[0, :lp] = req.prompt
                pages = row[:bucket // mgr.page_size][None, :]
                first, self._pool, self._key = self._prefill_fn(
                    self.params, jnp.asarray(ids), self._pool,
                    jnp.asarray(pages), jnp.asarray([lp], jnp.int32),
                    self._key)
                self._seen_buckets.add(bucket)
                self._prefilled_tokens += lp
                if self.prefix_cache:
                    mgr.register_prefix(slot, req.prompt, lp)
                self._start_decoding(req, slot, int(np.asarray(first)[0]), 0,
                                     finished)
            else:
                self._prefilling[slot] = _Prefilling(req, slot, matched,
                                                     matched)

    def _prefill_tick(self, finished: List[RequestOutput]) -> None:
        """Advance the oldest admitted prompt by ONE chunk (the Sarathi
        interleave cap: long prompts share each iteration with decode instead
        of stalling it)."""
        if not self._prefilling:
            return
        slot, st = next(iter(self._prefilling.items()))
        mgr = self.cache
        lp = st.request.prompt.size
        C = self._chunk
        n = min(C, lp - st.filled)
        ids = np.zeros((1, C), np.int32)
        ids[0, :n] = st.request.prompt[st.filled:st.filled + n]
        tok, self._pool, self._key = self._chunk_fn(
            self.params, jnp.asarray(ids), self._pool,
            jnp.asarray(mgr.page_table[slot][None, :]),
            jnp.asarray([st.filled], jnp.int32), jnp.asarray([n], jnp.int32),
            self._key)
        self._chunk_used = True
        self._prefill_chunks += 1
        self._prefilled_tokens += n
        st.filled += n
        if self.prefix_cache:
            mgr.register_prefix(slot, st.request.prompt, st.filled)
        if st.filled == lp:
            del self._prefilling[slot]
            self._start_decoding(st.request, slot, int(np.asarray(tok)[0]),
                                 st.cached_tokens, finished)

    def _start_decoding(self, req: Request, slot: int, first: int,
                        cached: int, finished: List[RequestOutput]) -> None:
        """Prompt fully in pages + first token picked: join the decode set."""
        self.cache.lengths[slot] = req.prompt.size
        ttft = time.perf_counter() - req.t_enqueue
        seq = _Running(req, slot, [first], cached, ttft)
        if not self._maybe_finish(seq, finished):
            self._running[slot] = seq

    def _decode_iter(self, finished: List[RequestOutput]) -> None:
        mgr = self.cache
        tokens = np.zeros((mgr.num_slots,), np.int32)
        for slot, seq in self._running.items():
            tokens[slot] = seq.generated[-1]
        table = mgr.page_table
        if self._prefilling:
            # mid-prefill slots must look inactive to the decode executable:
            # a null table row routes its (garbage) KV write to the null page
            # instead of position lengths[slot]=0 of the slot's REAL first page
            table = table.copy()
            for slot in self._prefilling:
                table[slot, :] = 0
        nxt, self._pool, self._key = self._decode_fn(
            self.params, jnp.asarray(tokens), self._pool,
            jnp.asarray(table), jnp.asarray(mgr.lengths), self._key)
        self._decode_iters += 1
        self._decode_tokens += len(self._running)
        nxt = np.asarray(nxt)
        for slot, seq in list(self._running.items()):
            mgr.lengths[slot] += 1          # the token we just fed is cached
            seq.generated.append(int(nxt[slot]))
            if self._maybe_finish(seq, finished):
                del self._running[slot]

    def _maybe_finish(self, seq: _Running,
                      finished: List[RequestOutput]) -> bool:
        reason = None
        if self.eos_token_id is not None and \
                seq.generated[-1] == self.eos_token_id:
            reason = "stop"
        elif len(seq.generated) >= seq.request.max_new_tokens:
            reason = "length"
        if reason is None:
            return False
        self.cache.release(seq.slot)
        self._free_slots.append(seq.slot)
        out = self._finish_output(seq.request, seq.generated, reason,
                                  seq.cached_tokens, seq.ttft_s)
        finished.append(out)
        return True

    def run(self) -> Dict[int, RequestOutput]:
        """Drain the queue: step until every request completes.  Returns
        {request_id: RequestOutput} for everything finished so far."""
        while self.has_work:
            self.step()
        return dict(self._outputs)

    @property
    def has_work(self) -> bool:
        return bool(self._queue or self._running or self._prefilling)

    # ---- observability ----------------------------------------------------
    def stats(self) -> Dict[str, object]:
        def execs(fn, fallback):
            try:
                return fn._cache_size()
            except Exception:
                return fallback
        cached, computed = self._prefix_cached_tokens, self._prefilled_tokens
        return {
            "decode_executables": execs(self._decode_fn,
                                        1 if self._decode_iters else 0),
            "prefill_executables": execs(self._prefill_fn,
                                         len(self._seen_buckets)) +
                                   execs(self._chunk_fn,
                                         1 if self._chunk_used else 0),
            "copy_executables": execs(self._copy_fn,
                                      1 if self._copy_used else 0),
            "buckets": list(self.buckets),
            "prefill_chunk": self.prefill_chunk,
            "decode_iterations": self._decode_iters,
            "decode_tokens": self._decode_tokens,
            "prefill_chunks": self._prefill_chunks,
            "prefilled_tokens": computed,
            "prefix_cached_tokens": cached,
            "prefix_hit_requests": self._prefix_hit_requests,
            "prefix_hit_rate": cached / (cached + computed)
                               if cached + computed else 0.0,
            "cow_page_copies": self._cow_copies,
            "pages_in_use": self.cache.pages_in_use(),
            "pages_free": self.cache.num_free_pages,
            "pages_evictable": self.cache.num_evictable_pages,
            "prefix_evictions": self.cache.prefix_evictions,
            "kv_token_capacity": self.cache.token_capacity(),
            "dense_token_footprint": self.cache.num_slots * self.max_model_len,
            "queued": len(self._queue),
            "prefilling": len(self._prefilling),
            "running": len(self._running),
        }
