"""Continuous-batching LLM serving engine.

Reference lineage: the reference repo serves via `fluid/inference`'s
AnalysisPredictor + PaddleNLP `generation` — a one-shot, whole-batch API.  For
"heavy traffic from millions of users" (ROADMAP north star) that shape is
wrong: every (batch, prompt_len, max_new) combination compiles a fresh
program, cache memory is dense `B x max_seq_len`, and a long request blocks
the batch.  This engine follows vLLM's paged KV cache (Kwon et al., SOSP 2023)
and Orca's iteration-level scheduling (Yu et al., OSDI 2022), under the same
"one jitted step, static shapes" discipline as the pretraining hot loop:

- **Paged KV cache** — one static pool of `[num_pages, page_size, KVH, hd]`
  pages per layer (`models.gpt.init_paged_cache`) + per-slot page tables
  (`inference.cache.PagedKVCache`): memory scales with live tokens, pages
  recycle as requests retire.
- **Slot-indexed decode** — ONE compiled decode program of fixed batch
  `num_slots` (`models.gpt.decode_step_paged`) serves a churning request set;
  retired slots are refilled without recompiling.
- **Bucketed prefill** — prompts pad to power-of-2 length buckets, bounding
  the prefill executable count to the bucket count; prefill writes straight
  into the slot's reserved pages.
- **Scheduler** — each `step()` admits queued requests into free slots
  (reservation-based page admission), runs one decode iteration over all
  active slots, and retires finished sequences (EOS or max_new_tokens),
  returning their pages to the free list.

`bench_serve.py` replays a Poisson request stream through this engine and
reports decode tokens/s/chip + compiled-program counts.
"""
from __future__ import annotations

import dataclasses
import itertools
from collections import deque
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import gpt as gpt_mod
from .cache import PagedKVCache


@dataclasses.dataclass
class Request:
    """One generation request: prompt token ids + a decode budget."""
    prompt: np.ndarray
    max_new_tokens: int = 16
    request_id: int = -1


@dataclasses.dataclass
class RequestOutput:
    request_id: int
    prompt: np.ndarray
    token_ids: List[int]            # generated tokens (prompt excluded)
    finish_reason: str              # "stop" (EOS) | "length" (budget)

    @property
    def tokens(self) -> np.ndarray:
        """prompt + generated, the `generate()`-compatible view."""
        return np.concatenate(
            [np.asarray(self.prompt, np.int64), np.asarray(self.token_ids,
                                                           np.int64)])


@dataclasses.dataclass
class _Running:
    request: Request
    slot: int
    generated: List[int]


def _pow2_buckets(lo: int, hi: int) -> List[int]:
    out = []
    b = lo
    while b <= hi:
        out.append(b)
        b *= 2
    return out


class LLMEngine:
    """Continuous-batching serving engine over the functional GPT core.

    params/config: the `models.gpt` pytree + GPTConfig.  `num_slots` is the
    fixed decode batch; `num_pages`/`page_size` size the KV pool (default pool
    is half of the dense `num_slots * max_model_len` footprint — the paged
    cache's whole point is that this still serves full-length traffic as long
    as *live* tokens fit).  Greedy by default; temperature/top_k compile the
    sampling variant of the same two executables.
    """

    def __init__(self, params, config: gpt_mod.GPTConfig, *,
                 num_slots: int = 4, page_size: int = 16,
                 num_pages: Optional[int] = None,
                 max_model_len: Optional[int] = None,
                 prefill_buckets: Optional[List[int]] = None,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 seed: int = 0):
        self.params = params
        self.config = config
        self.eos_token_id = eos_token_id
        max_model_len = max_model_len or config.max_seq_len
        if max_model_len % page_size:
            raise ValueError("max_model_len must be a multiple of page_size")
        if not config.use_rope and max_model_len > config.max_seq_len:
            # learned positions: jnp.take clamps past wpe's last row, which
            # would be silently wrong — generate() raises here too
            raise ValueError(
                f"max_model_len {max_model_len} exceeds max_seq_len "
                f"{config.max_seq_len} (learned positions)")
        self.max_model_len = max_model_len
        max_pages_per_slot = max_model_len // page_size
        if num_pages is None:
            # default: half the dense footprint (+ the null page)
            num_pages = max(2, num_slots * max_pages_per_slot // 2 + 1)
        if prefill_buckets is None:
            prefill_buckets = _pow2_buckets(page_size, max_model_len)
            if not prefill_buckets or prefill_buckets[-1] != max_model_len:
                # non-power-of-2 max_model_len: cover the top tokens too
                prefill_buckets.append(max_model_len)
        self.buckets = sorted(prefill_buckets)
        for b in self.buckets:
            if b % page_size or b > max_model_len:
                raise ValueError(f"bucket {b} incompatible with page_size "
                                 f"{page_size} / max_model_len {max_model_len}")
        self.cache = PagedKVCache(num_pages, page_size, num_slots,
                                  max_pages_per_slot)
        self._pool = gpt_mod.init_paged_cache(config, num_pages, page_size)
        self._queue: deque = deque()
        self._running: Dict[int, _Running] = {}
        self._free_slots = list(range(num_slots - 1, -1, -1))
        self._ids = itertools.count()
        self._key = jax.random.key(seed)
        self._outputs: Dict[int, RequestOutput] = {}

        sample = bool(temperature and temperature > 0.0)

        def pick(logits, key):
            # gpt.sample_token is shared with generate() — parity by construction
            return gpt_mod.sample_token(logits, key, sample=sample,
                                        temperature=temperature, top_k=top_k)

        cfg = config

        def decode_impl(params, tokens, pool, table, lengths, key):
            logits, pool = gpt_mod.decode_step_paged(params, tokens, pool,
                                                     table, lengths, cfg)
            nxt, key = pick(logits, key)
            return nxt, pool, key

        def prefill_impl(params, ids, pool, pages, length, key):
            logits, pool = gpt_mod.prefill_paged(params, ids, cfg, pool,
                                                 pages, length)
            first, key = pick(logits, key)
            return first, pool, key

        # pool donated: the step updates it in place instead of copying the
        # whole page pool every iteration
        self._decode_fn = jax.jit(decode_impl, donate_argnums=(2,))
        self._prefill_fn = jax.jit(prefill_impl, donate_argnums=(2,))
        self._seen_buckets = set()
        self._decode_iters = 0
        self._decode_tokens = 0         # per-iteration ACTIVE slots summed

    # ---- request intake ---------------------------------------------------
    def add_request(self, prompt, max_new_tokens: int = 16) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        if prompt.size > self.buckets[-1]:
            raise ValueError(f"prompt length {prompt.size} exceeds largest "
                             f"prefill bucket {self.buckets[-1]}")
        total = prompt.size + max_new_tokens
        if total > self.max_model_len:
            raise ValueError(f"prompt + max_new_tokens = {total} exceeds "
                             f"max_model_len {self.max_model_len}")
        rid = next(self._ids)
        self._queue.append(Request(prompt, max_new_tokens, rid))
        return rid

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"no bucket for prompt length {n}")

    # ---- scheduler --------------------------------------------------------
    def step(self) -> List[RequestOutput]:
        """One engine iteration: admit + prefill queued requests into free
        slots, then one decode step over every active slot.  Returns the
        requests that finished this iteration."""
        finished: List[RequestOutput] = []
        self._admit(finished)
        if self._running:
            self._decode_iter(finished)
        return finished

    def _admit(self, finished: List[RequestOutput]) -> None:
        mgr = self.cache
        while self._queue and self._free_slots:
            req = self._queue[0]
            total = req.prompt.size + req.max_new_tokens
            if not mgr.can_allocate(total):
                if not self._running and mgr.pages_in_use() == 0:
                    # nothing will ever free: the footprint exceeds the pool
                    raise ValueError(
                        f"request {req.request_id} needs "
                        f"{mgr.pages_needed(total)} pages but the pool only "
                        f"has {mgr.num_pages - 1}; raise num_pages")
                break                       # wait for pages to free up
            self._queue.popleft()
            slot = self._free_slots.pop()
            row = mgr.allocate(slot, total)
            lp = req.prompt.size
            bucket = self._bucket_for(lp)
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :lp] = req.prompt
            pages = row[:bucket // mgr.page_size][None, :]
            first, self._pool, self._key = self._prefill_fn(
                self.params, jnp.asarray(ids), self._pool,
                jnp.asarray(pages), jnp.asarray([lp], jnp.int32), self._key)
            self._seen_buckets.add(bucket)
            mgr.lengths[slot] = lp
            seq = _Running(req, slot, [int(np.asarray(first)[0])])
            if not self._maybe_finish(seq, finished):
                self._running[slot] = seq

    def _decode_iter(self, finished: List[RequestOutput]) -> None:
        mgr = self.cache
        tokens = np.zeros((mgr.num_slots,), np.int32)
        for slot, seq in self._running.items():
            tokens[slot] = seq.generated[-1]
        nxt, self._pool, self._key = self._decode_fn(
            self.params, jnp.asarray(tokens), self._pool,
            jnp.asarray(mgr.page_table), jnp.asarray(mgr.lengths), self._key)
        self._decode_iters += 1
        self._decode_tokens += len(self._running)
        nxt = np.asarray(nxt)
        for slot, seq in list(self._running.items()):
            mgr.lengths[slot] += 1          # the token we just fed is cached
            seq.generated.append(int(nxt[slot]))
            if self._maybe_finish(seq, finished):
                del self._running[slot]

    def _maybe_finish(self, seq: _Running,
                      finished: List[RequestOutput]) -> bool:
        reason = None
        if self.eos_token_id is not None and \
                seq.generated[-1] == self.eos_token_id:
            reason = "stop"
        elif len(seq.generated) >= seq.request.max_new_tokens:
            reason = "length"
        if reason is None:
            return False
        self.cache.release(seq.slot)
        self._free_slots.append(seq.slot)
        out = RequestOutput(seq.request.request_id, seq.request.prompt,
                            seq.generated, reason)
        self._outputs[out.request_id] = out
        finished.append(out)
        return True

    def run(self) -> Dict[int, RequestOutput]:
        """Drain the queue: step until every request completes.  Returns
        {request_id: RequestOutput} for everything finished so far."""
        while self._queue or self._running:
            self.step()
        return dict(self._outputs)

    @property
    def has_work(self) -> bool:
        return bool(self._queue or self._running)

    # ---- observability ----------------------------------------------------
    def stats(self) -> Dict[str, object]:
        def execs(fn, fallback):
            try:
                return fn._cache_size()
            except Exception:
                return fallback
        return {
            "decode_executables": execs(self._decode_fn,
                                        1 if self._decode_iters else 0),
            "prefill_executables": execs(self._prefill_fn,
                                         len(self._seen_buckets)),
            "buckets": list(self.buckets),
            "decode_iterations": self._decode_iters,
            "decode_tokens": self._decode_tokens,
            "pages_in_use": self.cache.pages_in_use(),
            "pages_free": self.cache.num_free_pages,
            "kv_token_capacity": self.cache.token_capacity(),
            "dense_token_footprint": self.cache.num_slots * self.max_model_len,
            "queued": len(self._queue),
            "running": len(self._running),
        }
