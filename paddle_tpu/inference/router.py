"""dp-replicated engine fleet with affinity-aware request routing.

Reference lineage: the reference repo's serving story ends at ONE
`AnalysisPredictor` per process — scale-out is "run more processes behind a
load balancer" and the balancer knows nothing about what each process has
cached.  For an LLM serving fleet that is the wrong default: PRs 2 and 15
made each engine's KV state *valuable* (prefix trie + host/disk tier — a
returning session restores its conversation in one scatter instead of
re-prefilling it), and PRs 12–13 made each engine *self-describing*
(`stats()["rates"]`, `health()`, pool-pressure/preemption churn).  This
module closes the loop: `EngineFleet` holds N data-parallel `LLMEngine`
replicas, each driven by its own background `step()` loop (the engine's
serving-loop surface), and routes every request to the replica where it is
cheapest to serve:

- **prefix/tier affinity** (`router="affinity"`, default): probe every
  healthy replica's prefix index (`LLMEngine.probe_affinity` — a pure read
  of the trie + rolling-hash partial index, tier-resident pages included)
  for the longest cached prefix of the prompt.  Sessions are sticky by
  default (ties break toward the replica that served the session last),
  but a replica whose cache/tier holds strictly MORE of the conversation
  wins — after an eviction-and-respill shuffle the pages, not the history,
  decide.
- **load**: among equal-affinity candidates, lowest live request count
  (`queue_depth`) wins, then highest windowed `tokens_per_sec` (a replica
  that is draining faster absorbs the next request sooner).  Replicas whose
  `health()` reads `overloaded` (SLO burn / pressure, PR-13 semantics) or
  that fail to evaluate are excluded from routing entirely.
- **victim-awareness**: low-priority requests (`priority < 0`) are the
  first preemption victims under optimistic admission, so routing them onto
  a replica already running hot (pool pressure over `victim_pressure`, or
  visible preemption churn in the 1m window) just schedules them to be
  evicted.  When a calmer replica exists, they go there instead.
- **load shedding**: when EVERY replica is overloaded/unreachable the fleet
  refuses the request with `FleetOverloaded` (carrying `retry_after_s`) —
  the front door maps it to 503 + `Retry-After` so clients back off instead
  of deepening queues that already burn their SLO budget.

`router="round_robin"` and `router="least_loaded"` are the A/B baselines
(`bench_serve.py --replicas N --router ...`): round-robin is what a
cache-blind balancer does, and the fleet bench measures exactly what that
blindness costs in prefix-hit rate and returning-turn TTFT.

Replication must not multiply compiled programs: replicas 0..N-1 run the
SAME model at the SAME shapes on the SAME mesh, so replica 0 compiles and
every other replica ADOPTS its executables (`_adopt_executables` — the
engine's jitted step functions are per-instance attributes precisely so a
fleet can share them).  `tools/check_program_count.py` runs a 2-replica
pass asserting per-replica program counts stay inside the single-engine
budget and that the executable objects are literally shared.

**Disaggregated prefill/decode** (`roles="P:D"`, ROADMAP item 2,
DistServe/Splitwise-style): the fleet partitions its replicas into a
PREFILL pool and a DECODE pool sharing one durable tier store
(`spill_dir`).  A new prompt routes least-loaded onto a prefill replica,
which runs admission + chunked prefill, generates one throwaway token, and
`export_prefix`-publishes the prompt's KV pages + durable index to the
store; the decode replica (chosen by affinity, sticky per session)
`refresh_store_index`-merges the published index and its ordinary
admission tier-restores the whole prompt with ONE scatter — long prefills
never steal fused-step slots from decode batches.  A returning turn whose
prefix the decode replica already holds skips the prefill hop entirely;
a shed prefill pool or a failed export degrades to a direct decode-side
submit (local re-prefill) — parity-lossless by construction, since the
decode engine re-computes exactly what the store could not provide.
Role-aware health: prefill replicas burn on TTFT only, decode replicas on
TPOT only (`health.py`), so shedding matches each pool's actual SLO.
"""
from __future__ import annotations

import dataclasses
import re
import tempfile
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from .engine import LLMEngine, RequestOutput
from .metrics import FleetMetrics

ROUTER_POLICIES = ("affinity", "round_robin", "least_loaded")

# the jitted step executables an engine builds in __init__ — per-instance
# attributes so a dp fleet can share ONE compiled set across replicas
_EXEC_ATTRS = ("_decode_fn", "_verify_fn", "_chunk_fn", "_prefill_fn",
               "_copy_fn", "_swap_out_fn", "_swap_in_fn")

# health states a request must never be routed to
_UNROUTABLE = ("overloaded", "error")


def _parse_roles(roles: str) -> Tuple[int, int]:
    """Parse a ``"P:D"`` / ``"2P:3D"`` role spec into (prefill, decode)
    replica counts (an omitted count means 1)."""
    m = re.fullmatch(r"(\d*)\s*P\s*:\s*(\d*)\s*D", str(roles).strip(), re.I)
    if not m:
        raise ValueError(f"roles must look like 'P:D' or '2P:3D', "
                         f"got {roles!r}")
    n_p = int(m.group(1)) if m.group(1) else 1
    n_d = int(m.group(2)) if m.group(2) else 1
    if n_p < 1 or n_d < 1:
        raise ValueError(f"roles needs >= 1 replica per pool, got {roles!r}")
    return n_p, n_d


class FleetOverloaded(RuntimeError):
    """Every replica is overloaded/unreachable — shed instead of queueing.
    `retry_after_s` is the client back-off hint (HTTP `Retry-After`)."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


@dataclasses.dataclass(frozen=True)
class FleetHandle:
    """A routed request: which replica took it and its engine-local rid.
    `str(handle)` (``engine0/3``) is the wire id the front door exposes;
    `parse` round-trips it."""
    label: str
    rid: int
    session: Optional[str] = None

    def __str__(self) -> str:
        return f"{self.label}/{self.rid}"

    @classmethod
    def parse(cls, s: str) -> "FleetHandle":
        label, _, rid = str(s).rpartition("/")
        return cls(label=label, rid=int(rid))


@dataclasses.dataclass
class ReplicaView:
    """One replica's routing signals, snapshotted per decision — the pure
    input to `rank_replicas`, so scoring is unit-testable without engines."""
    label: str
    state: str = "ok"               # health(): ok | degraded | overloaded
    matched_tokens: int = 0         # longest cached prefix of the prompt
    tier_tokens: int = 0            # ... of which host/disk tier-resident
    depth: int = 0                  # live requests (queued+prefill+decode)
    tokens_per_sec: float = 0.0     # windowed decode throughput (10s)
    pool_pressure: float = 0.0      # fraction of KV pool in live use
    preemptions_per_sec: float = 0.0  # victim churn (1m window)
    sticky: bool = False            # served this session last


def rank_replicas(views: List[ReplicaView], *, policy: str = "affinity",
                  priority: int = 0, victim_pressure: float = 0.85,
                  victim_churn: float = 0.5) -> Optional[ReplicaView]:
    """Pick the replica a request should land on, or None when nothing is
    routable.  Pure function of the snapshots (see module docstring for the
    scoring story); `round_robin` is stateful and lives on the fleet."""
    if policy not in ROUTER_POLICIES:
        raise ValueError(f"unknown router policy {policy!r}; "
                         f"expected one of {ROUTER_POLICIES}")
    usable = [v for v in views if v.state not in _UNROUTABLE]
    if not usable:
        return None
    if policy == "least_loaded":
        return min(usable, key=lambda v: (v.depth, -v.tokens_per_sec,
                                          v.label))
    if policy == "round_robin":
        raise ValueError("round_robin needs fleet state; route via "
                         "EngineFleet.select")
    # affinity: victim-aware pre-filter, then cache-weight ordering
    if priority < 0:
        calm = [v for v in usable if v.pool_pressure < victim_pressure and
                v.preemptions_per_sec <= victim_churn]
        if calm:
            usable = calm
    return max(usable, key=lambda v: (v.matched_tokens, v.sticky,
                                      -v.depth, v.tokens_per_sec,
                                      # stable last resort: lowest label
                                      tuple(-ord(c) for c in v.label)))


def _adopt_executables(replica: LLMEngine, leader: LLMEngine) -> None:
    """Point `replica`'s jitted step functions at `leader`'s compiled set.
    Sound exactly when both engines were built with identical construction
    arguments on the SAME mesh (the closures capture only config/sampling
    constants and the shared-mesh shardings) — which `EngineFleet` enforces
    by constructing every replica from one kwargs dict."""
    if replica.mesh is not leader.mesh:
        raise ValueError("executable adoption requires replicas on the "
                         "same mesh object (distinct meshes hash as "
                         "distinct jit cache keys -> one recompile per "
                         "replica)")
    for name in _EXEC_ATTRS:
        setattr(replica, name, getattr(leader, name))


class EngineFleet:
    """N dp-replicated `LLMEngine`s behind one routed submit/stream/abort
    surface.  Construct from `(params, config)` plus `engine_kwargs`
    (forwarded verbatim to every replica), or adopt pre-built `engines`.

    Lifecycle: `start()` spins one step()-loop thread per replica,
    `drain()` waits for quiescence, `stop()` joins the loops; the fleet is
    also a context manager.  `fleet_metrics` carries every replica for the
    PR-12 exposition (`per-{engine=...}` series + `llm_fleet_*` merges) and
    plugs straight into `ObservabilityServer(fleet=...)`.
    """

    def __init__(self, params=None, config=None, *, replicas: int = 2,
                 engines: Optional[List[LLMEngine]] = None,
                 router: str = "affinity",
                 roles: Optional[str] = None,
                 shed_retry_after_s: float = 1.0,
                 victim_pressure: float = 0.85,
                 victim_churn: float = 0.5,
                 handoff_timeout_s: float = 120.0,
                 engine_kwargs: Optional[Dict[str, object]] = None):
        if router not in ROUTER_POLICIES:
            raise ValueError(f"unknown router policy {router!r}; "
                             f"expected one of {ROUTER_POLICIES}")
        self.router = router
        self.roles = roles
        self.shed_retry_after_s = float(shed_retry_after_s)
        self.victim_pressure = float(victim_pressure)
        self.victim_churn = float(victim_churn)
        self.handoff_timeout_s = float(handoff_timeout_s)
        role_list: Optional[List[Optional[str]]] = None
        if roles is not None:
            n_p, n_d = _parse_roles(roles)
            role_list = ["prefill"] * n_p + ["decode"] * n_d
        if engines is None:
            if params is None or config is None:
                raise ValueError("EngineFleet needs (params, config) or "
                                 "pre-built engines=[...]")
            kw = dict(engine_kwargs or {})
            if role_list is not None:
                replicas = len(role_list)
                # disaggregation moves KV through the durable tier store:
                # force the tier on and give every pool member the SAME
                # store root so any decode replica can restore any prompt
                kw.setdefault("kv_tier", True)
                kw.setdefault("spill_dir",
                              tempfile.mkdtemp(prefix="kvstore_"))
                kw["role"] = role_list[0]
            if replicas < 1:
                raise ValueError(f"replicas must be >= 1, got {replicas}")
            leader = LLMEngine(params, config, **kw)
            engines = [leader]
            if replicas > 1:
                # replicas share the leader's mesh (mp>1: a fresh mesh per
                # replica would hash as a fresh jit cache key) and adopt
                # its compiled executables — dp replication adds ZERO
                # programs per mesh config
                kw.setdefault("mesh", leader.mesh)
                for i in range(1, replicas):
                    if role_list is not None:
                        kw["role"] = role_list[i]
                    e = LLMEngine(params, config, **kw)
                    _adopt_executables(e, leader)
                    engines.append(e)
        self.engines: "OrderedDict[str, LLMEngine]" = OrderedDict(
            (f"engine{i}", e) for i, e in enumerate(engines))
        # role pools (pre-built engines partition by their declared role)
        self.prefill_pool = [l for l, e in self.engines.items()
                             if e.role == "prefill"]
        self.decode_pool = [l for l, e in self.engines.items()
                            if e.role == "decode"]
        if roles is not None and not (self.prefill_pool and self.decode_pool):
            raise ValueError(
                f"roles={roles!r} needs >= 1 prefill and >= 1 decode "
                f"replica; got pools {self.prefill_pool} / "
                f"{self.decode_pool}")
        self.fleet_metrics = FleetMetrics()
        for label, eng in self.engines.items():
            self.fleet_metrics.add(label, eng)
        self._sessions: Dict[str, str] = {}
        self._rr = 0
        self.shed_count = 0
        self._submitted: Dict[str, int] = {l: 0 for l in self.engines}
        # handoff telemetry (disaggregated mode): per-handoff wall latency
        # (prefill submit -> store published + decode index refreshed),
        # plus skip (warm continuation) / degrade (fell back to decode-side
        # re-prefill) counts
        self.handoff_ms: List[float] = []
        self.handoff_skips = 0
        self.handoff_degrades = 0

    # ---- lifecycle --------------------------------------------------------
    def start(self, idle_wait_s: float = 0.002) -> "EngineFleet":
        for eng in self.engines.values():
            eng.start_loop(idle_wait_s)
        return self

    def stop(self, timeout: float = 30.0) -> None:
        for eng in self.engines.values():
            eng.stop_loop(timeout)

    def drain(self, timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        for eng in self.engines.values():
            rem = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            if not eng.drain(rem):
                return False
        return True

    def warm(self) -> None:
        """Warm every replica's executables outside any timed section.
        With adopted executables the leader's compiles are shared, so
        replica warmups re-dispatch cached programs (cheap) rather than
        compiling N times."""
        for eng in self.engines.values():
            eng.warm_decode()
            eng.warm_spec()
            eng.warm_swap()

    def __enter__(self) -> "EngineFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- routing ----------------------------------------------------------
    def _view(self, label: str, eng: LLMEngine, prompt,
              sticky_label: Optional[str]) -> ReplicaView:
        try:
            state = str(eng.health().get("state", "error"))
        except Exception:
            state = "error"
        v = ReplicaView(label=label, state=state,
                        sticky=(label == sticky_label))
        if state in _UNROUTABLE:
            return v
        try:
            probe = eng.probe_affinity(prompt) if prompt is not None \
                else {"cached_tokens": 0, "tier_tokens": 0}
            v.matched_tokens = probe["cached_tokens"]
            v.tier_tokens = probe["tier_tokens"]
            v.depth = eng.queue_depth()
            v.pool_pressure = float(eng.cache.pool_pressure())
            rates = {rw.name: rw for rw in eng._rate_surface}
            v.tokens_per_sec = float(
                rates["tokens_per_sec"].rate(10.0))
            v.preemptions_per_sec = float(
                rates["preemptions_per_sec"].rate(60.0))
        except Exception:
            v.state = "error"
        return v

    def views(self, prompt=None, session: Optional[str] = None,
              labels: Optional[List[str]] = None) -> List[ReplicaView]:
        sticky = self._sessions.get(session) if session is not None else None
        return [self._view(label, eng, prompt, sticky)
                for label, eng in self.engines.items()
                if labels is None or label in labels]

    def select(self, prompt=None, *, session: Optional[str] = None,
               priority: int = 0, policy: Optional[str] = None,
               labels: Optional[List[str]] = None) -> str:
        """Route: the chosen replica's label, or raise `FleetOverloaded`.
        `labels` restricts the candidate set (disagg role pools)."""
        policy = policy or self.router
        views = self.views(
            prompt if policy == "affinity" else None, session, labels)
        if policy == "round_robin":
            usable = [v for v in views if v.state not in _UNROUTABLE]
            if usable:
                pick = usable[self._rr % len(usable)]
                self._rr += 1
                return pick.label
            chosen = None
        else:
            chosen = rank_replicas(views, policy=policy, priority=priority,
                                   victim_pressure=self.victim_pressure,
                                   victim_churn=self.victim_churn)
        if chosen is None:
            self.shed_count += 1
            raise FleetOverloaded(
                f"all {len(views)} replicas overloaded/unreachable "
                f"(states: {[v.state for v in views]})",
                retry_after_s=self.shed_retry_after_s)
        return chosen.label

    # ---- request surface --------------------------------------------------
    def submit(self, prompt, *, session: Optional[str] = None,
               policy: Optional[str] = None, max_new_tokens: int = 16,
               temperature: Optional[float] = None, priority: int = 0,
               deadline_s: Optional[float] = None) -> FleetHandle:
        """Route + enqueue.  Raises `FleetOverloaded` when shedding; the
        per-engine validation/rejection semantics are `add_request`'s.
        With `roles` set the request takes the disaggregated path instead
        (prefill-pool hop + store handoff + decode-pool submit)."""
        if self.roles is not None:
            return self._submit_disagg(
                prompt, session=session, max_new_tokens=max_new_tokens,
                temperature=temperature, priority=priority,
                deadline_s=deadline_s)
        label = self.select(prompt, session=session, priority=priority,
                            policy=policy)
        rid = self.engines[label].submit(
            prompt, max_new_tokens=max_new_tokens, temperature=temperature,
            priority=priority, deadline_s=deadline_s)
        if session is not None:
            self._sessions[session] = label
        self._submitted[label] += 1
        return FleetHandle(label=label, rid=rid, session=session)

    def _submit_disagg(self, prompt, *, session: Optional[str],
                       max_new_tokens: int, temperature: Optional[float],
                       priority: int,
                       deadline_s: Optional[float]) -> FleetHandle:
        """Disaggregated routing: decode replica by affinity (sticky per
        session), prefill hop only when the decode replica is cold on this
        prompt.  Every degrade point (prefill pool shed, prefill timeout,
        empty export) falls through to the plain decode-side submit — the
        decode engine re-prefills locally, so outputs never depend on the
        handoff succeeding."""
        dlabel = self.select(prompt, session=session, priority=priority,
                             policy="affinity", labels=self.decode_pool)
        deng = self.engines[dlabel]
        prompt = np.asarray(prompt, np.int32)
        probe = deng.probe_affinity(prompt)
        if probe["cached_tokens"] * 2 >= prompt.size:
            # warm continuation: the decode replica already holds most of
            # the conversation — a prefill hop would only add latency
            self.handoff_skips += 1
        else:
            try:
                plabel = self.select(None, priority=priority,
                                     policy="least_loaded",
                                     labels=self.prefill_pool)
            except FleetOverloaded:
                plabel = None           # prefill pool shed: degrade
            if plabel is None:
                self.handoff_degrades += 1
            else:
                peng = self.engines[plabel]
                t0 = time.monotonic()
                prid = peng.submit(prompt, max_new_tokens=1,
                                   temperature=temperature)
                self._submitted[plabel] += 1
                out = peng.result(prid, timeout=self.handoff_timeout_s)
                exp = {"pages": 0}
                if out is not None and out.finish_reason in ("stop",
                                                             "length"):
                    exp = peng.export_prefix(prompt, rid=prid)
                if exp["pages"] > 0:
                    deng.refresh_store_index()
                    self.handoff_ms.append((time.monotonic() - t0) * 1e3)
                else:
                    self.handoff_degrades += 1
        rid = deng.submit(prompt, max_new_tokens=max_new_tokens,
                          temperature=temperature, priority=priority,
                          deadline_s=deadline_s)
        if session is not None:
            self._sessions[session] = dlabel
        self._submitted[dlabel] += 1
        return FleetHandle(label=dlabel, rid=rid, session=session)

    def _engine_of(self, handle: FleetHandle) -> LLMEngine:
        try:
            return self.engines[handle.label]
        except KeyError:
            raise KeyError(f"unknown replica {handle.label!r}") from None

    def abort(self, handle: FleetHandle) -> bool:
        return self._engine_of(handle).cancel(handle.rid)

    def progress(self, handle: FleetHandle) -> Dict[str, object]:
        return self._engine_of(handle).progress(handle.rid)

    def result(self, handle: FleetHandle,
               timeout: Optional[float] = None) -> Optional[RequestOutput]:
        return self._engine_of(handle).result(handle.rid, timeout)

    # ---- fleet state ------------------------------------------------------
    def health(self) -> Dict[str, object]:
        """Worst-of fleet health (the `/healthz` aggregation the obs plane
        already applies per engine)."""
        worst = {"state": "ok", "code": 0}
        per = {}
        for label, eng in self.engines.items():
            try:
                h = eng.health()
            except Exception as exc:
                h = {"state": "error", "code": 99, "error": repr(exc)}
            per[label] = h
            if float(h.get("code", 99)) > float(worst.get("code", 0)):
                worst = dict(h)
        worst["per_engine"] = {l: {"state": h.get("state"),
                                   "code": h.get("code"),
                                   "role": self.engines[l].role}
                               for l, h in per.items()}
        return worst

    def stats(self) -> Dict[str, object]:
        """Routing-plane summary (the full per-engine firehose stays on
        `engines[label].stats()` / the obs exposition)."""
        per = {}
        for label, eng in self.engines.items():
            with eng._serve_lock:
                st = eng.stats()
            per[label] = {
                "role": st["role"],
                "queue_depth": (st["queued"] + st["prefilling"] +
                                st["running"]),
                "decode_tokens": st["decode_tokens"],
                "tokens_per_sec_10s": st["rates"]["tokens_per_sec"]["10s"],
                "kv_pool_pressure": st["kv_pool_pressure"],
                "health": st["health"],
                "submitted": self._submitted[label],
            }
        out = {"router": self.router,
               "replicas": len(self.engines),
               "sessions": len(self._sessions),
               "shed": self.shed_count,
               "per_engine": per}
        if self.roles is not None:
            ms = sorted(self.handoff_ms)

            def _pct(q: float) -> float:
                return ms[min(len(ms) - 1, int(q * len(ms)))] if ms else 0.0

            out["disagg"] = {
                "roles": self.roles,
                "prefill_pool": list(self.prefill_pool),
                "decode_pool": list(self.decode_pool),
                "handoffs": len(ms),
                "handoff_skips": self.handoff_skips,
                "handoff_degrades": self.handoff_degrades,
                "handoff_p50_ms": round(_pct(0.50), 3),
                "handoff_p99_ms": round(_pct(0.99), 3),
            }
        return out

    def check_invariants(self) -> None:
        for eng in self.engines.values():
            with eng._serve_lock:
                eng.cache.check_invariants()

    def shared_executables(self) -> bool:
        """True when every replica runs the leader's compiled set (what
        check_program_count's fleet pass asserts)."""
        engines = list(self.engines.values())
        return all(getattr(e, n) is getattr(engines[0], n)
                   for e in engines[1:] for n in _EXEC_ATTRS)
