"""Serving front door: asyncio HTTP server over an `EngineFleet`.

Reference lineage: the reference repo's deployment story is
`AnalysisPredictor` behind an RPC server — ONE process-wide entry point that
validates, rate-limits and dispatches every request.  This module is that
front door for the fleet, stdlib-only (asyncio + json; no web framework —
the container bakes in nothing else, and an inference door needs exactly
two verbs):

- ``POST /v1/completions`` / ``POST /v1/chat/completions`` — OpenAI-style
  request shapes over **token ids** (this repo serves models, not
  tokenizers: ``prompt`` is a list of ints; chat ``messages`` carry
  ``content`` token-id lists that are concatenated in order).  Responses
  mirror the OpenAI envelope (``choices``/``usage``; ids are the fleet
  handle ``cmpl-<engine>/<rid>`` so ``/requests/<rid>?engine=...`` resolves
  them).  ``"stream": true`` serves Server-Sent Events: one ``data:`` frame
  per new token batch, a final frame with ``finish_reason`` + ``usage``,
  then ``data: [DONE]``.
- **Validation** — malformed JSON, non-token-id prompts, bad budgets → 400
  with the engine's own error text; per-engine intake rejections
  (footprint can never fit) surface as ``finish_reason: "rejected"``.
- **Per-tenant token-bucket rate limits** — tenant = ``X-Tenant`` header or
  body ``user``, `rate_limit_rps`/`rate_limit_burst` per tenant; an empty
  bucket answers 429 + ``Retry-After`` without touching the fleet.
- **Priority classes** — ``priority_class`` maps onto the engine's
  `priority=`/`deadline_s=` lanes (default classes: ``realtime`` >
  ``interactive`` > ``batch``; explicit ``priority``/``deadline_s`` keys
  override).  Low classes route victim-aware (see `inference.router`).
- **Disconnect propagation** — a client that drops mid-request (stream or
  not) aborts its fleet request, so the KV pages free immediately instead
  of decoding to a closed socket (`EngineFleet.abort` → `engine.cancel`).
- **Load shedding** — `FleetOverloaded` (every replica burning its SLO
  budget) answers 503 + ``Retry-After``.
- **ONE door** — the non-inference routes (``/metrics``, ``/stats``,
  ``/healthz``, ``/debug``, ``/requests/<rid>``) are served from the SAME
  socket by delegating to `ObservabilityServer.dispatch` (the shared
  routing table) over the fleet's `FleetMetrics`, so the scrape surface,
  worst-of health and exemplar resolution never fork from PR-12's plane.

Usage::

    fleet = EngineFleet(params, cfg, replicas=2).start()
    door = ServingFrontend(fleet).start()
    print(door.url)     # http://127.0.0.1:<port>
    # curl recipes: README "Serving front door"
    door.close(); fleet.stop()
"""
from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Dict, Optional

from .obs_server import ObservabilityServer, ROUTES as OBS_ROUTES
from .router import EngineFleet, FleetHandle, FleetOverloaded

V1_ROUTES = ("POST /v1/completions", "POST /v1/chat/completions")

# priority classes -> the engine's scheduling lanes (PR-10).  `deadline_s`
# None = no deadline; explicit body keys override the class.
PRIORITY_CLASSES: Dict[str, Dict[str, object]] = {
    "realtime": {"priority": 1, "deadline_s": 30.0},
    "interactive": {"priority": 0, "deadline_s": None},
    "batch": {"priority": -1, "deadline_s": None},
}

_JSON = "application/json; charset=utf-8"


class _TokenBucket:
    """Classic token bucket: `rate` tokens/s up to `burst`.  `take()`
    returns 0.0 on admit, else the seconds until a token exists."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.t = time.monotonic()

    def take(self) -> float:
        now = time.monotonic()
        self.tokens = min(self.burst, self.tokens + (now - self.t) * self.rate)
        self.t = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / self.rate


class _BadRequest(ValueError):
    pass


def _token_ids(value, what: str):
    if not isinstance(value, list) or not value or \
            not all(isinstance(t, int) and not isinstance(t, bool)
                    for t in value):
        raise _BadRequest(
            f"{what} must be a non-empty list of token ids (ints) — this "
            f"server serves models, not tokenizers")
    return value


def _chat_prompt(messages):
    if not isinstance(messages, list) or not messages:
        raise _BadRequest("messages must be a non-empty list")
    prompt = []
    for i, m in enumerate(messages):
        if not isinstance(m, dict) or "content" not in m:
            raise _BadRequest(f"messages[{i}] must be an object with "
                              f"role/content")
        prompt.extend(_token_ids(m["content"], f"messages[{i}].content"))
    return prompt


class ServingFrontend:
    """The fleet's HTTP door.  Runs its own asyncio loop on a daemon thread
    (same embedding contract as `ObservabilityServer`): `start()` binds —
    `port=0` picks an ephemeral port, read `.port`/`.url` after — and
    `close()` tears down; also a context manager.  Wraps a bare `LLMEngine`
    into a 1-replica fleet so every caller gets the same surface."""

    def __init__(self, fleet, *, host: str = "127.0.0.1", port: int = 0,
                 rate_limit_rps: Optional[float] = None,
                 rate_limit_burst: Optional[float] = None,
                 priority_classes: Optional[Dict[str, Dict]] = None,
                 default_max_new_tokens: int = 16,
                 max_new_tokens_cap: Optional[int] = None,
                 stream_poll_s: float = 0.005,
                 model_name: str = "paddle-tpu"):
        if not isinstance(fleet, EngineFleet):
            fleet = EngineFleet(engines=[fleet])
        self.fleet = fleet
        # the shared obs routing table over the SAME fleet members — the
        # one-door contract (never a second, drifting implementation)
        self.obs = ObservabilityServer(fleet=fleet.fleet_metrics)
        self._host = host
        self._port = int(port)
        self.rate_limit_rps = rate_limit_rps
        self.rate_limit_burst = rate_limit_burst if rate_limit_burst \
            is not None else (rate_limit_rps or 0) * 2
        self.priority_classes = dict(priority_classes if priority_classes
                                     is not None else PRIORITY_CLASSES)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.max_new_tokens_cap = max_new_tokens_cap
        self.stream_poll_s = float(stream_poll_s)
        self.model_name = model_name
        self._buckets: Dict[str, _TokenBucket] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._bound_port: Optional[int] = None
        self._started = threading.Event()
        self._start_error: Optional[BaseException] = None

    # ---- lifecycle --------------------------------------------------------
    def start(self) -> "ServingFrontend":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run, name="front-door",
                                        daemon=True)
        self._thread.start()
        self._started.wait(10.0)
        if self._start_error is not None:
            raise RuntimeError("front door failed to bind") \
                from self._start_error
        return self

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        async def boot():
            self._server = await asyncio.start_server(
                self._handle, self._host, self._port)
            self._bound_port = self._server.sockets[0].getsockname()[1]

        try:
            loop.run_until_complete(boot())
        except BaseException as exc:    # bind failure -> surface in start()
            self._start_error = exc
            self._started.set()
            return
        self._started.set()
        try:
            loop.run_forever()
        finally:
            self._server.close()
            loop.run_until_complete(self._server.wait_closed())
            loop.close()

    @property
    def port(self) -> int:
        if self._bound_port is None:
            raise RuntimeError("front door not started")
        return self._bound_port

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def close(self) -> None:
        loop, self._loop = self._loop, None
        if loop is not None:
            loop.call_soon_threadsafe(loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "ServingFrontend":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- HTTP plumbing ----------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            try:
                head = await asyncio.wait_for(
                    reader.readuntil(b"\r\n\r\n"), timeout=30.0)
            except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                    asyncio.LimitOverrunError):
                return
            lines = head.decode("latin-1").split("\r\n")
            try:
                method, target, _ = lines[0].split(" ", 2)
            except ValueError:
                await self._reply(writer, 400, {"error": "bad request line"})
                return
            headers = {}
            for ln in lines[1:]:
                if ":" in ln:
                    k, _, v = ln.partition(":")
                    headers[k.strip().lower()] = v.strip()
            body = b""
            n = int(headers.get("content-length") or 0)
            if n:
                body = await reader.readexactly(n)
            path, _, query = target.partition("?")
            if method == "GET":
                code, ctype, payload = self.obs.dispatch(
                    path, query, headers.get("accept", ""),
                    extra_routes=V1_ROUTES)
                await self._raw_reply(writer, code, payload, ctype)
            elif method == "POST" and path.rstrip("/") in \
                    ("/v1/completions", "/v1/chat/completions"):
                await self._completion(
                    reader, writer, headers, body,
                    chat=path.rstrip("/").endswith("chat/completions"))
            else:
                await self._reply(writer, 405 if method not in ("GET", "POST")
                                  else 404,
                                  {"error": f"no route {method} {path}",
                                   "routes": list(V1_ROUTES) +
                                   [f"GET {r}" for r in OBS_ROUTES]})
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _raw_reply(self, writer, code: int, body: bytes, ctype: str,
                         extra_headers: Dict[str, str] = ()) -> None:
        reason = {200: "OK", 300: "Multiple Choices", 400: "Bad Request",
                  404: "Not Found", 405: "Method Not Allowed",
                  429: "Too Many Requests",
                  503: "Service Unavailable"}.get(code, "OK")
        head = [f"HTTP/1.1 {code} {reason}",
                f"Content-Type: {ctype}",
                f"Content-Length: {len(body)}",
                "Connection: close"]
        for k, v in dict(extra_headers or {}).items():
            head.append(f"{k}: {v}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode("latin-1"))
        writer.write(body)
        await writer.drain()

    async def _reply(self, writer, code: int, obj,
                     extra_headers: Dict[str, str] = ()) -> None:
        await self._raw_reply(writer, code,
                              json.dumps(obj).encode("utf-8"), _JSON,
                              extra_headers)

    # ---- the inference endpoints ------------------------------------------
    def _parse(self, headers: Dict[str, str], body: bytes, chat: bool):
        """Validate one completion request -> submit kwargs + envelope
        info.  Raises `_BadRequest` with a client-facing message."""
        try:
            req = json.loads(body.decode("utf-8") or "{}")
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise _BadRequest(f"body is not JSON: {e}") from None
        if not isinstance(req, dict):
            raise _BadRequest("body must be a JSON object")
        if chat:
            prompt = _chat_prompt(req.get("messages"))
        else:
            prompt = _token_ids(req.get("prompt"), "prompt")
        max_new = req.get("max_tokens", self.default_max_new_tokens)
        if not isinstance(max_new, int) or max_new < 1:
            raise _BadRequest("max_tokens must be a positive int")
        if self.max_new_tokens_cap is not None:
            max_new = min(max_new, self.max_new_tokens_cap)
        cls_name = req.get("priority_class", "interactive")
        try:
            lane = dict(self.priority_classes[cls_name])
        except KeyError:
            raise _BadRequest(
                f"unknown priority_class {cls_name!r}; expected one of "
                f"{sorted(self.priority_classes)}") from None
        if "priority" in req:
            lane["priority"] = req["priority"]
        if "deadline_s" in req:
            lane["deadline_s"] = req["deadline_s"]
        tenant = headers.get("x-tenant") or req.get("user") or "default"
        temperature = req.get("temperature")
        if temperature is not None:
            temperature = float(temperature)
        return {
            "prompt": prompt,
            "kwargs": {"max_new_tokens": max_new, "temperature": temperature,
                       "priority": int(lane.get("priority") or 0),
                       "deadline_s": lane.get("deadline_s"),
                       "session": req.get("session")},
            "tenant": str(tenant),
            "stream": bool(req.get("stream", False)),
            "echo": bool(req.get("echo", False)),
        }

    def _throttle(self, tenant: str) -> float:
        """0.0 = admitted; else seconds the tenant must back off."""
        if not self.rate_limit_rps:
            return 0.0
        bucket = self._buckets.get(tenant)
        if bucket is None:
            bucket = self._buckets[tenant] = _TokenBucket(
                self.rate_limit_rps, self.rate_limit_burst)
        return bucket.take()

    @staticmethod
    def _finish_payload(handle: FleetHandle, out, prompt, chat: bool,
                        model: str):
        ids = list(out.token_ids)
        choice = {"index": 0, "finish_reason": out.finish_reason}
        if chat:
            choice["message"] = {"role": "assistant", "token_ids": ids}
        else:
            choice["token_ids"] = ids
            choice["text"] = " ".join(map(str, ids))
        return {
            "id": f"cmpl-{handle}",
            "object": "chat.completion" if chat else "text_completion",
            "model": model,
            "engine": handle.label,
            "choices": [choice],
            "usage": {"prompt_tokens": len(prompt),
                      "completion_tokens": len(ids),
                      "total_tokens": len(prompt) + len(ids),
                      "cached_tokens": int(out.cached_tokens)},
        }

    async def _completion(self, reader, writer, headers, body,
                          chat: bool) -> None:
        try:
            req = self._parse(headers, body, chat)
        except _BadRequest as e:
            await self._reply(writer, 400, {"error": str(e)})
            return
        wait = self._throttle(req["tenant"])
        if wait > 0.0:
            await self._reply(
                writer, 429,
                {"error": f"tenant {req['tenant']!r} rate-limited; retry in "
                          f"{wait:.2f}s"},
                {"Retry-After": f"{max(1, int(wait + 0.999))}"})
            return
        kw = req["kwargs"]
        try:
            # fleet.submit probes/locks engines — off the event loop thread
            handle = await asyncio.get_running_loop().run_in_executor(
                None, lambda: self.fleet.submit(
                    req["prompt"], session=kw["session"],
                    max_new_tokens=kw["max_new_tokens"],
                    temperature=kw["temperature"],
                    priority=kw["priority"], deadline_s=kw["deadline_s"]))
        except FleetOverloaded as e:
            await self._reply(
                writer, 503,
                {"error": f"fleet overloaded: {e}"},
                {"Retry-After": f"{max(1, int(e.retry_after_s + 0.999))}"})
            return
        except ValueError as e:         # add_request validation
            await self._reply(writer, 400, {"error": str(e)})
            return
        # from here on the request owns KV pages somewhere — any client
        # disconnect must abort it (reader.read() returning b"" = peer gone;
        # pipelined bytes would also resolve this task, but the connection
        # is Connection: close, so nothing legitimate arrives)
        hangup = asyncio.ensure_future(reader.read(1))
        try:
            if req["stream"]:
                await self._stream(writer, hangup, handle, req, chat)
            else:
                await self._unary(writer, hangup, handle, req, chat)
        except (ConnectionResetError, BrokenPipeError):
            self.fleet.abort(handle)
        finally:
            hangup.cancel()

    async def _unary(self, writer, hangup, handle, req, chat: bool) -> None:
        while True:
            prog = self.fleet.progress(handle)
            if prog["finished"]:
                break
            if hangup.done():
                self.fleet.abort(handle)
                return
            await asyncio.sleep(self.stream_poll_s)
        await self._reply(writer, 200, self._finish_payload(
            handle, prog["output"], req["prompt"], chat, self.model_name))

    async def _stream(self, writer, hangup, handle, req, chat: bool) -> None:
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-cache\r\n"
                     b"Connection: close\r\n\r\n")
        await writer.drain()

        def frame(obj) -> bytes:
            return b"data: " + json.dumps(obj).encode("utf-8") + b"\n\n"

        sent = 0
        rid = f"cmpl-{handle}"
        while True:
            prog = self.fleet.progress(handle)
            ids = prog["token_ids"]
            if len(ids) > sent:
                delta = ids[sent:]
                sent = len(ids)
                if chat:
                    choice = {"index": 0,
                              "delta": {"role": "assistant",
                                        "token_ids": delta}}
                else:
                    choice = {"index": 0, "token_ids": delta,
                              "text": " ".join(map(str, delta))}
                writer.write(frame({"id": rid, "object": "chunk",
                                    "engine": handle.label,
                                    "choices": [choice]}))
                await writer.drain()
            if prog["finished"]:
                break
            if hangup.done():
                self.fleet.abort(handle)
                return
            await asyncio.sleep(self.stream_poll_s)
        writer.write(frame(self._finish_payload(
            handle, prog["output"], req["prompt"], chat, self.model_name)))
        writer.write(b"data: [DONE]\n\n")
        await writer.drain()
