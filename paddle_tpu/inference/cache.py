"""Paged KV cache manager (ref vLLM block manager, Kwon et al. SOSP 2023).

Host-side page accounting for the serving engine: a free list over a static
device pool (`models.gpt.init_paged_cache`), per-slot page-table rows, and
per-slot lengths.  All methods are O(pages) host operations — the device only
ever sees the fixed-shape `[num_slots, max_pages_per_slot]` table and
`[num_slots]` lengths, so the compiled decode step never changes shape.

Allocation is reservation-based: a request's full footprint
(prompt + max_new_tokens, rounded up to pages) is reserved at admission, so a
running sequence can never hit out-of-pages mid-decode (preemption/swapping is
an open item, see ROADMAP).  Page 0 is reserved as the null page: unreserved
table entries point at it, inactive slots write to it, and attention masking
by length guarantees it is never read.
"""
from __future__ import annotations

import numpy as np

NULL_PAGE = 0


class PagedKVCache:
    """Page-table + free-list bookkeeping for `num_slots` decode slots over a
    pool of `num_pages` pages of `page_size` tokens each."""

    def __init__(self, num_pages: int, page_size: int, num_slots: int,
                 max_pages_per_slot: int):
        if page_size & (page_size - 1):
            raise ValueError(f"page_size must be a power of 2, got {page_size}")
        if num_pages < 2:
            raise ValueError("need at least one real page beyond the null page")
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_slots = num_slots
        self.max_pages_per_slot = max_pages_per_slot
        # page 0 reserved as the null page; ascending allocation order
        self._free = list(range(num_pages - 1, 0, -1))
        self.page_table = np.full((num_slots, max_pages_per_slot), NULL_PAGE,
                                  np.int32)
        self.lengths = np.zeros((num_slots,), np.int32)
        self._used = {s: [] for s in range(num_slots)}

    # ---- capacity queries -------------------------------------------------
    @property
    def num_free_pages(self) -> int:
        return len(self._free)

    def pages_needed(self, total_tokens: int) -> int:
        return -(-total_tokens // self.page_size)

    def can_allocate(self, total_tokens: int) -> bool:
        n = self.pages_needed(total_tokens)
        return n <= len(self._free) and n <= self.max_pages_per_slot

    def token_capacity(self) -> int:
        """Pool capacity in tokens (excludes the null page) — the number the
        engine's memory claim is measured against (vs num_slots * max_len)."""
        return (self.num_pages - 1) * self.page_size

    # ---- slot lifecycle ---------------------------------------------------
    def allocate(self, slot: int, total_tokens: int) -> np.ndarray:
        """Reserve ceil(total_tokens / page_size) pages for `slot` and write
        them into its table row.  Returns the row (view)."""
        n = self.pages_needed(total_tokens)
        if n > len(self._free):
            raise RuntimeError(
                f"out of KV pages: need {n}, free {len(self._free)}")
        if n > self.max_pages_per_slot:
            raise ValueError(
                f"request footprint {total_tokens} tokens exceeds slot "
                f"capacity {self.max_pages_per_slot * self.page_size}")
        if self._used[slot]:
            raise RuntimeError(f"slot {slot} already has pages")
        pages = [self._free.pop() for _ in range(n)]
        self._used[slot] = pages
        self.page_table[slot, :] = NULL_PAGE
        self.page_table[slot, :n] = pages
        return self.page_table[slot]

    def release(self, slot: int) -> None:
        """Return a retired slot's pages to the free list."""
        self._free.extend(reversed(self._used[slot]))
        self._used[slot] = []
        self.page_table[slot, :] = NULL_PAGE
        self.lengths[slot] = 0

    def pages_in_use(self) -> int:
        return sum(len(p) for p in self._used.values())
