"""Paged KV cache manager (ref vLLM block manager, Kwon et al. SOSP 2023).

Host-side page accounting for the serving engine: a free list over a static
device pool (`models.gpt.init_paged_cache`), per-slot page-table rows,
per-slot lengths, per-page refcounts, and a content-hash prefix index.  All
methods are O(pages) host operations — the device only ever sees the
fixed-shape `[num_slots, max_pages_per_slot]` table and `[num_slots]`
lengths, so the compiled decode step never changes shape.

Two allocation disciplines (the engine's `admission=` knob):

- **reservation** (default): a request's full footprint
  (prompt + max_new_tokens, rounded up to pages) is reserved at admission, so
  a running sequence can never hit out-of-pages mid-decode.
- **optimistic** (vLLM-style, Kwon et al. §4.3): only the prompt footprint is
  reserved at admission and the slot's pages `grow()` token-granularly as
  decode proceeds — live tokens, not worst-case reservations, bound
  concurrency.  A failed `grow()` is the engine's preemption trigger: the
  victim's pages either swap to a host-side pool (its page count tracked
  here as the fourth `swapped` partition, `note_swap_out`/`note_swap_in`) or
  are simply released and the sequence recomputed later as a longer prompt.

Page 0 is reserved as the null page: unreserved table entries point at it,
inactive slots write to it, and attention masking by length guarantees it is
never read.

Prefix cache (vLLM copy-on-write page sharing): prompt pages whose KV has
been fully written are registered in a trie-shaped index keyed by
(parent node, token bytes) — i.e. by the token-id *content* of the whole
prefix up to that page.  A later request whose prompt shares a page-aligned
prefix maps the cached pages read-only into its table row (refcount++) and
only prefills the tail; a matched *partial* final page is shared
copy-on-write: the caller copies the page on device into a fresh page the
new slot owns before appending into it.  Pages are freed only when their
refcount returns to 0; registered pages at refcount 0 park in an LRU of
evictable prefixes and are reclaimed on demand, so cached prefixes can never
deadlock the pool.
"""
from __future__ import annotations

import dataclasses
import itertools
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

NULL_PAGE = 0


@dataclasses.dataclass
class _PrefixNode:
    """One cached page of prompt KV: `page` holds the KV of `n_tokens` tokens
    whose identity (and that of the whole preceding prefix) is pinned by
    `key = (parent node id, token bytes)`.  n_tokens == page_size for full
    pages; a smaller n marks a partial page, shareable only via COW."""
    node_id: int
    key: Tuple[int, bytes]
    page: int
    n_tokens: int


_ROOT = 0   # parent id of first-page nodes


class PagedKVCache:
    """Page-table + free-list + prefix-index bookkeeping for `num_slots`
    decode slots over a pool of `num_pages` pages of `page_size` tokens."""

    def __init__(self, num_pages: int, page_size: int, num_slots: int,
                 max_pages_per_slot: int):
        if page_size & (page_size - 1):
            raise ValueError(f"page_size must be a power of 2, got {page_size}")
        if num_pages < 2:
            raise ValueError("need at least one real page beyond the null page")
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_slots = num_slots
        self.max_pages_per_slot = max_pages_per_slot
        # page 0 reserved as the null page; ascending allocation order
        self._free = list(range(num_pages - 1, 0, -1))
        self.page_table = np.full((num_slots, max_pages_per_slot), NULL_PAGE,
                                  np.int32)
        self.lengths = np.zeros((num_slots,), np.int32)
        self._used: Dict[int, List[int]] = {s: [] for s in range(num_slots)}
        self._ref = np.zeros((num_pages,), np.int64)
        # prefix index: key -> node; page -> node; LRU of refcount-0 nodes
        self._index: Dict[Tuple[int, bytes], _PrefixNode] = {}
        self._page_node: Dict[int, _PrefixNode] = {}
        self._lru: "OrderedDict[int, _PrefixNode]" = OrderedDict()
        self._node_ids = itertools.count(1)
        self.prefix_evictions = 0
        self._evictions_counter = None      # metrics mirror, see attach_metrics
        # fourth partition: pages whose KV content lives in the HOST swap
        # pool, keyed by request id (the device pages themselves were
        # released — this tracks the off-device obligation so drain checks
        # can prove nothing leaked there either)
        self._swapped: Dict[int, int] = {}

    # ---- capacity queries -------------------------------------------------
    @property
    def num_free_pages(self) -> int:
        """Pages immediately allocatable without evicting cached prefixes."""
        return len(self._free)

    @property
    def num_evictable_pages(self) -> int:
        """Registered prefix pages at refcount 0 — reclaimable on demand."""
        return len(self._lru)

    def pages_needed(self, total_tokens: int) -> int:
        return -(-total_tokens // self.page_size)

    def can_allocate(self, total_tokens: int,
                     tokens: Optional[np.ndarray] = None) -> bool:
        """Whether a `total_tokens` footprint fits, counting evictable cached
        pages and (when the prompt `tokens` are given) pages the prefix cache
        would share instead of allocating fresh."""
        n = self.pages_needed(total_tokens)
        if n > self.max_pages_per_slot:
            return False
        fresh = n
        in_lru = 0
        if tokens is not None:
            full, partial = self._match(np.asarray(tokens, np.int32))
            fresh = n - len(full)
            for node in full:
                if self._ref[node.page] == 0:
                    in_lru += 1         # shared, so not evictable for us
            if partial is not None and self._ref[partial.page] == 0:
                in_lru += 1             # COW source must survive the copy
        return fresh <= len(self._free) + len(self._lru) - in_lru

    def token_capacity(self) -> int:
        """Pool capacity in tokens (excludes the null page) — the number the
        engine's memory claim is measured against (vs num_slots * max_len)."""
        return (self.num_pages - 1) * self.page_size

    def pages_held(self, slot: int) -> int:
        """Pages currently mapped into `slot`'s table row (shared + private)
        — one of the three victim-selection signals."""
        return len(self._used[slot])

    def slot_pages(self, slot: int) -> List[int]:
        """The slot's page ids in table-row order (a copy)."""
        return list(self._used[slot])

    @property
    def swapped_page_count(self) -> int:
        """Pages whose KV currently lives in the host swap pool."""
        return sum(self._swapped.values())

    @property
    def swapped_requests(self) -> int:
        """Requests currently parked in the host swap pool."""
        return len(self._swapped)

    def host_pool_room(self, budget_pages: int) -> int:
        """Pages of host swap-pool room left under `budget_pages`: the
        budget minus the parked KV already counted against it.  The
        PREEMPTION decision reads this number (can the victim park *now*,
        given what is already parked) so the parked-KV account cannot be
        double-spent.  Intake admission deliberately does NOT — it compares
        the request's worst case against the raw budget (could it EVER
        park, even in an empty pool), because a transiently full pool must
        queue-and-drain, not reject (see `LLMEngine.add_request`).  Page
        counts are
        dtype-oblivious: an int8 pool parks the same page count in ~2-4x
        fewer host bytes (`LLMEngine.swap_pool_bytes`)."""
        return budget_pages - self.swapped_page_count

    def pool_pressure(self) -> float:
        """Fraction of the real pool in live use (0.0 idle .. 1.0 full) —
        the overload gauge victim selection and dashboards key on."""
        return self.pages_in_use() / max(1, self.num_pages - 1)

    def attach_metrics(self, registry) -> None:
        """Register page-accounting observability on a
        `inference.metrics.MetricsRegistry`: pull gauges over the free/in-use/
        evictable partition (evaluated only at scrape/snapshot time — the
        allocator hot path pushes nothing) and a monotonic counter mirroring
        `prefix_evictions` (the int attribute stays authoritative for
        `stats()`; the counter is the Prometheus face of the same events)."""
        self._evictions_counter = registry.counter(
            "prefix_evictions", "cached prefix pages reclaimed under pressure")
        registry.gauge("kv_pages_in_use", self.pages_in_use,
                       "pages with refcount > 0")
        registry.gauge("kv_pages_free", lambda: self.num_free_pages,
                       "pages immediately allocatable")
        registry.gauge("kv_pages_evictable", lambda: self.num_evictable_pages,
                       "refcount-0 cached prefix pages, reclaimable on demand")
        registry.gauge("prefix_cached_pages", lambda: len(self._index),
                       "pages registered in the prefix index")
        registry.gauge("kv_pages_swapped", lambda: self.swapped_page_count,
                       "pages whose KV lives in the host swap pool")
        # ratio gauge: a fleet merge folds it by MAX (a sum of per-replica
        # fractions would read >100% on a healthy fleet; the router's signal
        # is the worst member)
        registry.gauge("kv_pool_pressure", self.pool_pressure,
                       "fraction of the page pool in live use", agg="max")

    # ---- prefix index -----------------------------------------------------
    def _match(self, tokens: np.ndarray
               ) -> Tuple[List[_PrefixNode], Optional[_PrefixNode]]:
        """Longest cached prefix of `tokens`, capped at len(tokens) - 1 so at
        least one position is always recomputed (its logits seed generation).
        Returns (full-page nodes, optional partial-page node extending them)."""
        page = self.page_size
        lp = tokens.size
        full: List[_PrefixNode] = []
        parent = _ROOT
        for i in range((lp - 1) // page):
            node = self._index.get((parent, tokens[i * page:(i + 1) * page]
                                    .tobytes()))
            if node is None:
                break
            full.append(node)
            parent = node.node_id
        base = len(full) * page
        partial = None
        for j in range(min(lp - base - 1, page - 1), 0, -1):
            node = self._index.get((parent, tokens[base:base + j].tobytes()))
            if node is not None:
                partial = node
                break
        return full, partial

    def register_prefix(self, slot: int, tokens: np.ndarray,
                        filled: int) -> None:
        """Publish `slot`'s prompt pages whose KV is complete (the first
        `filled` of `tokens`) into the prefix index.  Idempotent — call after
        every prefill chunk; already-indexed keys (including pages this slot
        itself shares) are left untouched, so duplicate concurrent prompts
        simply keep their private pages unregistered.  The final partial page
        is registered only once the whole prompt is in (filled == len) — its
        content hash must cover exactly the prompt tail, and the slot keeps
        appending decode tokens past it (harmless: the node only ever claims
        the first n_tokens of the page; COW borrowers overwrite the rest)."""
        tokens = np.asarray(tokens, np.int32)
        page = self.page_size
        pages = self._used[slot]
        parent = _ROOT
        for i in range(min(filled, tokens.size) // page):
            key = (parent, tokens[i * page:(i + 1) * page].tobytes())
            node = self._index.get(key)
            if node is None and pages[i] not in self._page_node:
                node = _PrefixNode(next(self._node_ids), key, pages[i], page)
                self._index[key] = node
                self._page_node[pages[i]] = node
            if node is None:        # page already published under another key
                return
            parent = node.node_id
        rem = tokens.size % page
        if rem and filled == tokens.size:
            i = tokens.size // page
            key = (parent, tokens[i * page:].tobytes())
            if key not in self._index and pages[i] not in self._page_node:
                node = _PrefixNode(next(self._node_ids), key, pages[i], rem)
                self._index[key] = node
                self._page_node[pages[i]] = node

    def _evict(self, fresh_needed: int) -> None:
        """Reclaim LRU unreferenced cached prefixes until `fresh_needed` pages
        are on the free list (or the LRU runs dry)."""
        while len(self._free) < fresh_needed and self._lru:
            _, node = self._lru.popitem(last=False)
            del self._index[node.key]
            del self._page_node[node.page]
            self._free.append(node.page)
            self.prefix_evictions += 1
            if self._evictions_counter is not None:
                self._evictions_counter.inc()

    # ---- slot lifecycle ---------------------------------------------------
    def allocate(self, slot: int, total_tokens: int) -> np.ndarray:
        """Reserve ceil(total_tokens / page_size) pages for `slot` and write
        them into its table row.  Returns the row (view)."""
        row, _, _ = self.allocate_prefixed(slot, total_tokens, None)
        return row

    def allocate_prefixed(self, slot: int, total_tokens: int,
                          tokens: Optional[np.ndarray] = None
                          ) -> Tuple[np.ndarray, int, Optional[Tuple[int, int]]]:
        """Reserve `slot`'s footprint, sharing the longest cached prefix of
        the prompt `tokens` (when given) instead of allocating fresh pages.

        Returns (table row view, matched_tokens, cow):
        - matched_tokens: prompt tokens whose KV the slot starts with —
          full shared pages (mapped read-only, refcount++) plus, when `cow`
          is set, the tokens of a matched partial page;
        - cow: (src_page, dst_page) the CALLER must copy on device before the
          slot writes anything — dst is the slot's own fresh page at the
          partial boundary, src a cached page it must not mutate.
        """
        n = self.pages_needed(total_tokens)
        if n > self.max_pages_per_slot:
            raise ValueError(
                f"request footprint {total_tokens} tokens exceeds slot "
                f"capacity {self.max_pages_per_slot * self.page_size}")
        if self._used[slot]:
            raise RuntimeError(f"slot {slot} already has pages")
        full: List[_PrefixNode] = []
        partial = None
        if tokens is not None:
            full, partial = self._match(np.asarray(tokens, np.int32))
        shared = []
        for node in full:
            if self._ref[node.page] == 0:
                self._lru.pop(node.node_id, None)   # revive from evictable
            self._ref[node.page] += 1
            shared.append(node.page)
        # pin the COW source for the duration of this allocation: it must not
        # be evicted to satisfy our own fresh-page demand
        if partial is not None and partial.node_id in self._lru:
            self._lru.move_to_end(partial.node_id)
            pinned = self._lru.pop(partial.node_id)
        else:
            pinned = None
        fresh_needed = n - len(shared)
        self._evict(fresh_needed)
        if pinned is not None:
            self._lru[pinned.node_id] = pinned
        if fresh_needed > len(self._free):
            for p in reversed(shared):              # roll back the sharing
                self._ref[p] -= 1
                if self._ref[p] == 0:
                    self._lru[self._page_node[p].node_id] = self._page_node[p]
            raise RuntimeError(
                f"out of KV pages: need {fresh_needed}, "
                f"free {len(self._free)}")
        fresh = [self._free.pop() for _ in range(fresh_needed)]
        for p in fresh:
            self._ref[p] = 1
        pages = shared + fresh
        self._used[slot] = pages
        self.page_table[slot, :] = NULL_PAGE
        self.page_table[slot, :n] = pages
        matched = len(shared) * self.page_size
        cow = None
        if partial is not None:
            cow = (partial.page, fresh[0])
            matched += partial.n_tokens
        return self.page_table[slot], matched, cow

    def grow(self, slot: int, total_tokens: int) -> None:
        """Optimistic admission's token-granular growth: extend `slot`'s
        mapping so it covers `total_tokens` positions, allocating fresh pages
        (evicting LRU-parked prefixes on demand) past what it already holds.
        No-op when the slot already covers the footprint — the engine calls
        this before every decode/verify dispatch, so the common case must be
        one integer compare.  Raises RuntimeError when the pool cannot supply
        the pages — the engine's preemption trigger."""
        n = self.pages_needed(total_tokens)
        have = len(self._used[slot])
        if n <= have:
            return
        if n > self.max_pages_per_slot:
            raise ValueError(
                f"slot {slot} growth to {total_tokens} tokens exceeds slot "
                f"capacity {self.max_pages_per_slot * self.page_size}")
        need = n - have
        self._evict(need)
        if need > len(self._free):
            raise RuntimeError(
                f"out of KV pages growing slot {slot}: need {need}, "
                f"free {len(self._free)}")
        fresh = [self._free.pop() for _ in range(need)]
        for p in fresh:
            self._ref[p] = 1
        self.page_table[slot, have:n] = fresh
        self._used[slot].extend(fresh)

    # ---- host swap pool accounting (fourth partition) ---------------------
    def note_swap_out(self, request_id: int, n_pages: int) -> None:
        """Record that `n_pages` of KV for `request_id` now live in the host
        swap pool (the device pages are released separately — this partition
        tracks the off-device obligation)."""
        if n_pages < 1:
            raise ValueError(f"swap-out of {n_pages} pages")
        if request_id in self._swapped:
            raise RuntimeError(f"request {request_id} already swapped out")
        self._swapped[request_id] = n_pages

    def note_swap_in(self, request_id: int) -> int:
        """Clear `request_id`'s swap-pool obligation (swap-in completed, the
        request was aborted/timed out, or the swap degraded to recompute).
        Returns the page count released from the host pool (0 if unknown)."""
        return self._swapped.pop(request_id, 0)

    def release(self, slot: int) -> None:
        """Retire a slot: decrement its pages' refcounts; pages reaching 0 go
        back to the free list, unless they are registered cached prefixes —
        those park in the LRU and stay matchable until evicted."""
        for p in reversed(self._used[slot]):
            self._ref[p] -= 1
            if self._ref[p] == 0:
                node = self._page_node.get(p)
                if node is not None:
                    self._lru[node.node_id] = node
                    self._lru.move_to_end(node.node_id)
                else:
                    self._free.append(p)
        self._used[slot] = []
        self.page_table[slot, :] = NULL_PAGE
        self.lengths[slot] = 0

    def pages_in_use(self) -> int:
        """Distinct pages with refcount > 0 (cached-but-unreferenced prefixes
        do not count — they are reclaimable).  O(1) via the free/LRU/in-use
        partition over the real pages (asserted by check_invariants) — this
        runs on the scheduler hot path every step for the trace ring, so it
        must not scan refcounts on a production-sized pool."""
        return self.num_pages - 1 - len(self._free) - len(self._lru)

    def check_invariants(self) -> None:
        """Assert the refcount/free-list/LRU partition is consistent — every
        real page is exactly one of {free, refcounted-in-use, parked in the
        evictable LRU}, and refcounts equal the number of slot rows mapping
        the page.  Tests call this around speculative rollback and abort to
        prove neither path can leak or double-free a page."""
        assert (self._ref >= 0).all(), "negative refcount"
        assert self._ref[NULL_PAGE] == 0, "null page must never be refcounted"
        counts = np.zeros((self.num_pages,), np.int64)
        for pages in self._used.values():
            for p in pages:
                counts[p] += 1
        assert (counts == self._ref).all(), \
            f"refcounts {self._ref.tolist()} != slot usage {counts.tolist()}"
        free = set(self._free)
        lru = {n.page for n in self._lru.values()}
        used = {p for p in range(1, self.num_pages) if self._ref[p] > 0}
        assert len(free) == len(self._free), "duplicate page on free list"
        assert not (free & lru) and not (free & used) and not (lru & used), \
            "page in more than one of free/LRU/in-use"
        assert free | lru | used == set(range(1, self.num_pages)), \
            "page leaked out of free/LRU/in-use partition"
        assert self.pages_in_use() == len(used), \
            "O(1) pages_in_use diverged from the refcount scan"
        for node in self._lru.values():
            assert self._index.get(node.key) is node, "LRU node unregistered"
        for page, node in self._page_node.items():
            assert node.page == page
        # fourth (host-side) partition: every swap-pool obligation is a
        # positive page count, and the total matches the O(1) mirror — a
        # swapped request that was aborted/resumed without clearing its entry
        # is a host-pool leak even though the device partition looks clean
        for rid, n in self._swapped.items():
            assert 0 < n <= self.max_pages_per_slot, \
                f"swapped request {rid} records {n} pages"

    def prefix_stats(self) -> Dict[str, int]:
        return {
            "cached_pages": len(self._index),
            "evictable_pages": len(self._lru),
            "prefix_evictions": self.prefix_evictions,
        }
