"""Paged KV cache manager (ref vLLM block manager, Kwon et al. SOSP 2023).

Host-side page accounting for the serving engine: a free list over a static
device pool (`models.gpt.init_paged_cache`), per-slot page-table rows,
per-slot lengths, per-page refcounts, and a content-hash prefix index.  All
methods are O(pages) host operations — the device only ever sees the
fixed-shape `[num_slots, max_pages_per_slot]` table and `[num_slots]`
lengths, so the compiled decode step never changes shape.

Two allocation disciplines (the engine's `admission=` knob):

- **reservation** (default): a request's full footprint
  (prompt + max_new_tokens, rounded up to pages) is reserved at admission, so
  a running sequence can never hit out-of-pages mid-decode.
- **optimistic** (vLLM-style, Kwon et al. §4.3): only the prompt footprint is
  reserved at admission and the slot's pages `grow()` token-granularly as
  decode proceeds — live tokens, not worst-case reservations, bound
  concurrency.  A failed `grow()` is the engine's preemption trigger: the
  victim's pages either swap to a host-side pool (its page count tracked
  here as the fourth `swapped` partition, `note_swap_out`/`note_swap_in`) or
  are simply released and the sequence recomputed later as a longer prompt.

Page 0 is reserved as the null page: unreserved table entries point at it,
inactive slots write to it, and attention masking by length guarantees it is
never read.

Prefix cache (vLLM copy-on-write page sharing): prompt pages whose KV has
been fully written are registered in a trie-shaped index keyed by
(parent node, token bytes) — i.e. by the token-id *content* of the whole
prefix up to that page.  A later request whose prompt shares a page-aligned
prefix maps the cached pages read-only into its table row (refcount++) and
only prefills the tail; a matched *partial* final page is shared
copy-on-write: the caller copies the page on device into a fresh page the
new slot owns before appending into it.  Pages are freed only when their
refcount returns to 0; registered pages at refcount 0 park in an LRU of
evictable prefixes and are reclaimed on demand, so cached prefixes can never
deadlock the pool.

Rolling-hash partial-page index: next to the page-granularity trie, every
registered page also indexes the PREFIXES of its token content under a
polynomial rolling hash, so a prompt sharing only a partial tail of a cached
page (any page, not just one that happened to be registered as a partial
node) COW-copies the matched fraction and prefills only the true remainder.
Hash hits are verified against the node's stored token bytes before use, so
a collision can never corrupt a match.

KV tiering (device -> host -> optional disk): when a `HostKVTier` is
attached (`attach_tier`), `_evict` no longer drops retired prefixes — their
page CONTENT spills to a bounded host tier through the engine's spill
callback (the PR-10 `swap_out_pages` gather, d2h overlapped with the next
dispatch) and the trie node stays matchable with `page = HOST_PAGE`.  A
later `allocate_prefixed` whose prefix lives off-device assigns fresh pages
to those nodes and returns a restore plan (`take_restore`): the engine
scatters the parked KV back with ONE `swap_in_pages` dispatch and
`commit_restore` re-registers the nodes on device — a returning session's
conversation KV restores with one h2d scatter instead of a full re-prefill.
The host tier shares the engine's unified host-pool page budget with
preemption swap parking (`host_pool_room`); over budget it cascades to a
disk tier (`spill_dir=`) or drops, oldest first.

Durable tier index + PageStore (disaggregated serving PR): the disk level
writes through an object-store-shaped `PageStore` (`LocalDirStore` under
`spill_dir` by default), and `save_tier_index` / `load_tier_index`
serialize the trie + rolling-hash index beside the page objects
(versioned, atomic-rename writes) — so a restarted, or DIFFERENT, process
re-attaches any published session and restores it through the same
one-scatter path.  That transport is exactly the prefill->decode handoff
seam: a prefill-role engine exports its finished prompt's pages + index
into the shared store, and any decode-role replica's admission finds and
restores them.  A corrupted, version-skewed, or partially-deleted store
can only cost a re-prefill, never a crash or a wrong match (token content
rides in the index and every hash hit is verified against it).
"""
from __future__ import annotations

import dataclasses
import itertools
import json
import os
from collections import OrderedDict
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

import numpy as np

NULL_PAGE = 0
HOST_PAGE = -1      # node.page sentinel: content lives in the host/disk tier


@dataclasses.dataclass
class _PrefixNode:
    """One cached page of prompt KV: `page` holds the KV of `n_tokens` tokens
    whose identity (and that of the whole preceding prefix) is pinned by
    `key = (parent node id, token bytes)`.  n_tokens == page_size for full
    pages; a smaller n marks a partial page, shareable only via COW.
    page == HOST_PAGE marks a node whose KV content lives in the attached
    `HostKVTier` (host numpy or disk) instead of a device page.
    `partial_keys` are the rolling-hash partial-index entries this node
    registered — removed with the node so the index cannot dangle."""
    node_id: int
    key: Tuple[int, bytes]
    page: int
    n_tokens: int
    partial_keys: List[Tuple[int, int, int]] = \
        dataclasses.field(default_factory=list)


_ROOT = 0   # parent id of first-page nodes

# polynomial rolling hash over int32 token ids (base/modulus pairing keeps
# collisions rare; every hit is verified against the node's token bytes, so
# hash quality affects only lookup cost, never correctness)
_HASH_BASE = 1000003
_HASH_MOD = (1 << 61) - 1

# shortest partial-page tail worth matching: a 1-token hit costs a COW page
# copy (and, in bucketed mode, the chunk-tail prefill path) to save one
# token of prefill — and at small vocabularies single-token prefixes of
# unrelated prompts coincide often enough (~#root-children/vocab per
# admission) to tax the dispatch account with worthless hits
_MIN_PARTIAL = 2

# serialized tier-index format version: `load_tier_index` only merges index
# blobs whose version AND page geometry match — anything else is ignored and
# the affected sessions degrade to re-prefill (never a crash)
TIER_INDEX_VERSION = 1

# distinguishes page objects written by different tiers sharing one store
# (a disagg fleet's prefill + decode engines, or successive processes over
# one spill_dir): node ids are only unique per process, store names must be
# unique per writer
_TIER_TAGS = itertools.count()


class PageStore:
    """Object-store-shaped durable level under the host KV tier.

    The tier addresses content by NAME — ``kvnode_<tag>_<id>`` for page
    slabs, ``kvindex_<tag>`` for serialized index blobs — and a store maps
    names to bytes.  `LocalDirStore` below is the default; an S3/GCS-shaped
    backend only has to implement these six methods, because the tier, the
    durable index, and the cross-engine handoff never touch the filesystem
    directly."""

    def put(self, name: str, data: Dict[str, np.ndarray]) -> None:
        """Store one page slab ({lane name: array}) under `name`."""
        raise NotImplementedError

    def get(self, name: str) -> Dict[str, np.ndarray]:
        """Load a page slab; KeyError-family exceptions degrade upstream."""
        raise NotImplementedError

    def delete(self, name: str) -> None:
        raise NotImplementedError

    def exists(self, name: str) -> bool:
        raise NotImplementedError

    def put_blob(self, name: str, payload: bytes) -> None:
        """Store an opaque blob (index files); must be atomic — a reader
        may never observe a torn write."""
        raise NotImplementedError

    def blobs(self, prefix: str) -> Iterable[Tuple[str, bytes]]:
        """Iterate (name, payload) over stored blobs under `prefix`."""
        raise NotImplementedError


class LocalDirStore(PageStore):
    """The default `PageStore`: one npz file per page slab plus
    atomically-renamed index blobs, all under one directory (the engine's
    `spill_dir`) — the PR-15 disk-tier layout, now behind the store
    interface so any replica (or a restarted process) can read it."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.root, name)

    def put(self, name: str, data: Dict[str, np.ndarray]) -> None:
        np.savez(self._path(name + ".npz"), **data)

    def get(self, name: str) -> Dict[str, np.ndarray]:
        with np.load(self._path(name + ".npz")) as z:
            return {k: z[k] for k in z.files}

    def delete(self, name: str) -> None:
        path = self._path(name + ".npz")
        if os.path.exists(path):
            os.remove(path)

    def exists(self, name: str) -> bool:
        return os.path.exists(self._path(name + ".npz"))

    def put_blob(self, name: str, payload: bytes) -> None:
        # tmp-write + atomic rename: a concurrent reader (another replica's
        # merge, a restarting process) sees the old blob or the new one,
        # never a torn one
        path, tmp = self._path(name), self._path(name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)

    def blobs(self, prefix: str) -> Iterable[Tuple[str, bytes]]:
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return
        for fn in names:
            if not fn.startswith(prefix) or fn.endswith(".tmp"):
                continue
            try:
                with open(self._path(fn), "rb") as f:
                    yield fn, f.read()
            except OSError:
                continue


class HostKVTier:
    """Bounded host-side storage for spilled prefix-page KV, with an optional
    disk level underneath (`spill_dir`).

    Pure storage + LRU ordering: entries are keyed by prefix-node id and hold
    either host numpy page slabs ({lane name: [L, page_size, ...]}), a
    PENDING marker (the engine gathered the page on device but the d2h fetch
    is still deferred past the next dispatch), or a disk path.  Budget policy
    lives in the owner: `PagedKVCache.tier_make_room` pushes LRU host entries
    down to disk (or drops them) and the ENGINE decides how many pages of the
    unified host pool the tier may hold (`LLMEngine.swap_pool_pages` shared
    with preemption swap parking)."""

    _PENDING = object()

    def __init__(self, spill_dir: Optional[str] = None,
                 disk_pages: Optional[int] = None,
                 store: Optional[PageStore] = None):
        self._host: "OrderedDict[int, object]" = OrderedDict()
        # durable level: node id -> store name.  _shared marks entries whose
        # store object is visible to OTHER readers — imported from another
        # writer's index, or published in ours via `mark_shared` — so local
        # pop/drop remove the entry without deleting the object (a replica
        # restoring a handoff must not destroy the store under its peers;
        # object garbage collection is a store-level concern).
        self._disk: "OrderedDict[int, str]" = OrderedDict()
        self._shared: Set[int] = set()
        self.spill_dir = spill_dir
        self.disk_pages = disk_pages
        if store is None and spill_dir is not None:
            store = LocalDirStore(spill_dir)
        self.store = store
        # per-writer namespace for store object names (node ids are only
        # unique per process; two tiers sharing a store must not collide)
        self.tag = f"{os.getpid()}x{next(_TIER_TAGS)}"
        # monotonic event counts (the engine mirrors the user-facing ones
        # into its MetricsRegistry; these back the invariant checks)
        self.disk_spills = 0
        self.disk_restores = 0
        self.tier_drops = 0

    # ---- occupancy --------------------------------------------------------
    @property
    def pages_host(self) -> int:
        """Host-resident pages, PENDING gathers included (they count against
        the unified host-pool budget: their bytes are committed)."""
        return len(self._host)

    @property
    def pages_disk(self) -> int:
        return len(self._disk)

    def has(self, node_id: int) -> bool:
        return node_id in self._host or node_id in self._disk

    def is_pending(self, node_id: int) -> bool:
        return self._host.get(node_id) is self._PENDING

    # ---- spill / fill -----------------------------------------------------
    def add_pending(self, node_id: int) -> None:
        """Reserve a host entry for a page whose device gather is in flight
        (the engine fills it at the next `_pending_d2h` drain)."""
        if self.has(node_id):
            raise RuntimeError(f"tier node {node_id} already present")
        self._host[node_id] = self._PENDING

    def fill(self, node_id: int, data: Dict[str, np.ndarray]) -> None:
        """Land a pending entry's fetched page content."""
        if self._host.get(node_id) is not self._PENDING:
            raise RuntimeError(f"tier node {node_id} is not pending")
        self._host[node_id] = data

    # ---- read / restore ---------------------------------------------------
    def data(self, node_id: int) -> Dict[str, np.ndarray]:
        """The node's page content (host copy; read through from disk when
        it cascaded there — the entry STAYS at its level, so a read can
        never push the host level over its budget).  Raises KeyError when
        the node is unknown and RuntimeError while its d2h fetch is still
        pending (the engine drains pending gathers before restoring)."""
        if node_id in self._host:
            e = self._host[node_id]
            if e is self._PENDING:
                raise RuntimeError(f"tier node {node_id} still pending d2h")
            self._host.move_to_end(node_id)
            return e
        name = self._disk[node_id]      # KeyError: unknown node, degrade
        try:
            data = self.store.get(name)
        except (OSError, ValueError) as e:
            # object vanished/corrupted under us (shared store, another
            # process GC'd it): same degrade contract as an unknown node
            raise KeyError(f"tier node {node_id} store object {name!r} "
                           f"unreadable: {e}") from e
        self.disk_restores += 1
        return data

    def pop(self, node_id: int) -> None:
        """Remove an entry whose page moved back to the device tier.
        Shared store objects survive the pop — another replica (or a
        restarted process) may still restore from them."""
        if self._host.pop(node_id, None) is None:
            name = self._disk.pop(node_id)
            if node_id in self._shared:
                self._shared.discard(node_id)
            else:
                self.store.delete(name)

    def drop(self, node_id: int) -> None:
        """Discard an entry (node dropped from the index): host bytes
        released, and the store object too unless it is shared."""
        self._host.pop(node_id, None)
        name = self._disk.pop(node_id, None)
        if name is not None and node_id not in self._shared:
            self.store.delete(name)
        self._shared.discard(node_id)
        self.tier_drops += 1

    # ---- shared store (durable index / cross-engine handoff) --------------
    def import_entry(self, node_id: int, name: str) -> None:
        """Attach a store-resident page object (another writer's export, or
        a previous process's spill) as a disk-level entry of THIS tier,
        marked shared — restorable through the ordinary read path, never
        deleted by local bookkeeping."""
        if self.has(node_id):
            raise RuntimeError(f"tier node {node_id} already present")
        self._disk[node_id] = name
        self._shared.add(node_id)

    def mark_shared(self, node_ids: Iterable[int]) -> None:
        """Entries just published in a serialized index: their store objects
        may now be read by other replicas/processes, so local pop/drop must
        stop deleting them."""
        self._shared.update(nid for nid in node_ids if nid in self._disk)

    # ---- host -> disk cascade ---------------------------------------------
    def demotable(self) -> List[int]:
        """Host node ids oldest-first, pending entries excluded (their bytes
        do not exist on host yet, so they can neither demote nor drop)."""
        return [nid for nid, e in self._host.items()
                if e is not self._PENDING]

    def to_disk(self, node_id: int) -> bool:
        """Demote one host entry to the durable store level; False when no
        store is configured (the caller drops the node instead)."""
        if self.store is None:
            return False
        data = self._host[node_id]
        if data is self._PENDING:
            raise RuntimeError(f"cannot demote pending tier node {node_id}")
        name = f"kvnode_{self.tag}_{node_id}"
        self.store.put(name, data)
        del self._host[node_id]
        self._disk[node_id] = name
        self.disk_spills += 1
        return True


class PagedKVCache:
    """Page-table + free-list + prefix-index bookkeeping for `num_slots`
    decode slots over a pool of `num_pages` pages of `page_size` tokens."""

    def __init__(self, num_pages: int, page_size: int, num_slots: int,
                 max_pages_per_slot: int):
        if page_size & (page_size - 1):
            raise ValueError(f"page_size must be a power of 2, got {page_size}")
        if num_pages < 2:
            raise ValueError("need at least one real page beyond the null page")
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_slots = num_slots
        self.max_pages_per_slot = max_pages_per_slot
        # page 0 reserved as the null page; ascending allocation order
        self._free = list(range(num_pages - 1, 0, -1))
        self.page_table = np.full((num_slots, max_pages_per_slot), NULL_PAGE,
                                  np.int32)
        self.lengths = np.zeros((num_slots,), np.int32)
        self._used: Dict[int, List[int]] = {s: [] for s in range(num_slots)}
        self._ref = np.zeros((num_pages,), np.int64)
        # prefix index: key -> node; page -> node (device nodes only); LRU of
        # refcount-0 device nodes; rolling-hash partial index
        # (parent, j, hash) -> node over every registered page's j-token
        # content prefixes
        self._index: Dict[Tuple[int, bytes], _PrefixNode] = {}
        self._page_node: Dict[int, _PrefixNode] = {}
        self._lru: "OrderedDict[int, _PrefixNode]" = OrderedDict()
        self._partial: Dict[Tuple[int, int, int], _PrefixNode] = {}
        self._node_ids = itertools.count(1)
        self.prefix_evictions = 0
        self._evictions_counter = None      # metrics mirror, see attach_metrics
        # KV tier (attach_tier): spilled-prefix storage + the engine's spill
        # callback; _restore_plan[slot] is the off-device part of the latest
        # allocate_prefixed match, consumed by the engine via take_restore
        self._tier: Optional[HostKVTier] = None
        self._spill_cb: Optional[
            Callable[[List[_PrefixNode]], Set[int]]] = None
        self._tier_nodes: Dict[int, _PrefixNode] = {}   # off-device nodes
        self._restore_plan: Dict[int, List[Tuple[int, _PrefixNode, int]]] = {}
        # fourth partition: pages whose KV content lives in the HOST swap
        # pool, keyed by request id (the device pages themselves were
        # released — this tracks the off-device obligation so drain checks
        # can prove nothing leaked there either)
        self._swapped: Dict[int, int] = {}

    # ---- capacity queries -------------------------------------------------
    @property
    def num_free_pages(self) -> int:
        """Pages immediately allocatable without evicting cached prefixes."""
        return len(self._free)

    @property
    def num_evictable_pages(self) -> int:
        """Registered prefix pages at refcount 0 — reclaimable on demand."""
        return len(self._lru)

    def pages_needed(self, total_tokens: int) -> int:
        return -(-total_tokens // self.page_size)

    def can_allocate(self, total_tokens: int,
                     tokens: Optional[np.ndarray] = None) -> bool:
        """Whether a `total_tokens` footprint fits, counting evictable cached
        pages and (when the prompt `tokens` are given) pages the prefix cache
        would share instead of allocating fresh."""
        n = self.pages_needed(total_tokens)
        if n > self.max_pages_per_slot:
            return False
        fresh = n
        in_lru = 0
        if tokens is not None:
            full, partial = self._match(np.asarray(tokens, np.int32))
            # only DEVICE nodes share their page; off-device (tier) nodes
            # restore into fresh pages, so they reduce nothing here
            device_full = [nd for nd in full if nd.page >= 0]
            fresh = n - len(device_full)
            for node in device_full:
                if self._ref[node.page] == 0:
                    in_lru += 1         # shared, so not evictable for us
            if partial is not None and partial[0].page >= 0 and \
                    self._ref[partial[0].page] == 0:
                in_lru += 1             # COW source must survive the copy
        return fresh <= len(self._free) + len(self._lru) - in_lru

    def token_capacity(self) -> int:
        """Pool capacity in tokens (excludes the null page) — the number the
        engine's memory claim is measured against (vs num_slots * max_len)."""
        return (self.num_pages - 1) * self.page_size

    def pages_held(self, slot: int) -> int:
        """Pages currently mapped into `slot`'s table row (shared + private)
        — one of the three victim-selection signals."""
        return len(self._used[slot])

    def slot_pages(self, slot: int) -> List[int]:
        """The slot's page ids in table-row order (a copy)."""
        return list(self._used[slot])

    @property
    def swapped_page_count(self) -> int:
        """Pages whose KV currently lives in the host swap pool."""
        return sum(self._swapped.values())

    @property
    def swapped_requests(self) -> int:
        """Requests currently parked in the host swap pool."""
        return len(self._swapped)

    @property
    def tier_pages_host(self) -> int:
        """Spilled prefix pages resident on host (pending gathers included);
        0 with no tier attached."""
        return 0 if self._tier is None else self._tier.pages_host

    @property
    def tier_pages_disk(self) -> int:
        return 0 if self._tier is None else self._tier.pages_disk

    def host_pool_room(self, budget_pages: int) -> int:
        """Pages of host-pool room left under `budget_pages`: the budget
        minus everything already counted against the UNIFIED host pool —
        preemption swap parking AND spilled-prefix tier pages (disk pages
        are off-budget).  The PREEMPTION decision reads this number (can
        the victim park *now*, given what is already parked) so the
        parked-KV account cannot be double-spent; it may first reclaim tier
        room (`tier_make_room` — live victims outrank cached prefixes).
        Intake admission deliberately does NOT — it compares the request's
        worst case against the raw budget (could it EVER park, even in an
        empty pool: parked victims drain and tier pages are droppable on
        demand), because a transiently full pool must queue-and-drain, not
        reject (see `LLMEngine.add_request`).  Page counts are
        dtype-oblivious: an int8 pool parks the same page count in ~2-4x
        fewer host bytes (`LLMEngine.host_pool_bytes`)."""
        return budget_pages - self.swapped_page_count - self.tier_pages_host

    def attach_tier(self, tier: HostKVTier,
                    spill_cb: Callable[[List[_PrefixNode]], Set[int]]
                    ) -> None:
        """Enable KV tiering: `_evict` offers every retired prefix node to
        `spill_cb` (the engine's batched device gather) instead of dropping
        it; nodes the callback accepts (returned id set) stay in the index
        with their content parked in `tier`."""
        self._tier = tier
        self._spill_cb = spill_cb

    def tier_make_room(self, n_pages: int) -> int:
        """Reclaim up to `n_pages` of HOST-tier room for the unified host
        pool: LRU host entries demote to the disk level (when `spill_dir`
        is configured) or are dropped from the index outright.  Pending
        gathers cannot move.  Returns the pages actually freed — the
        preemption path calls this before parking a victim, so live work
        always outranks cached prefixes."""
        if self._tier is None or n_pages <= 0:
            return 0
        freed = 0
        for nid in self._tier.demotable():
            if freed >= n_pages:
                break
            node = self._node_by_id(nid)
            if self._tier.to_disk(nid):
                self._enforce_disk_cap()
            else:
                self._drop_node(node)
            freed += 1
        return freed

    def _enforce_disk_cap(self) -> None:
        if self._tier is None or self._tier.disk_pages is None:
            return
        while self._tier.pages_disk > self._tier.disk_pages:
            nid = next(iter(self._tier._disk))
            self._drop_node(self._node_by_id(nid))

    def _node_by_id(self, node_id: int) -> _PrefixNode:
        return self._tier_nodes[node_id]

    def tier_data(self, node: _PrefixNode) -> Dict[str, np.ndarray]:
        """The parked page content of an off-device node (loads from disk
        when it cascaded there).  KeyError/RuntimeError propagate — the
        engine degrades the restore to re-prefill."""
        if self._tier is None:
            raise KeyError(f"no tier attached (node {node.node_id})")
        return self._tier.data(node.node_id)

    def drop_tier_nodes(self, nodes: List[_PrefixNode]) -> None:
        """Drop off-device nodes entirely (failed d2h/h2d copy, vanished
        data): index + partial entries + tier bytes all released — the
        degrade path re-prefills instead."""
        for node in nodes:
            if self._index.get(node.key) is node:
                self._drop_node(node)

    # ---- durable tier index (restart re-attach / cross-engine handoff) ----
    def save_tier_index(self, tag: str = "main") -> int:
        """Serialize the store-resident part of the prefix index — trie
        topology, token content, page-object names — as ``kvindex_<tag>``
        beside the page objects (versioned, atomic-rename-written).  Only
        nodes whose WHOLE ancestor chain is store-resident are published: a
        chain broken by a device/host-only ancestor is unreachable to a
        reader anyway (`_match` walks from the root).  Publishing marks the
        referenced page objects shared, so this tier stops deleting them on
        pop/drop — another replica may now restore from them.  Returns the
        node count published (0 with no store attached)."""
        tier = self._tier
        if tier is None or tier.store is None:
            return 0
        nodes = {n.node_id: n for n in self._index.values()
                 if n.page < 0 and n.node_id in tier._disk}
        ok: Dict[int, bool] = {_ROOT: True}

        def _chain_ok(nid: int) -> bool:
            got = ok.get(nid)
            if got is None:
                node = nodes.get(nid)
                got = ok[nid] = node is not None and _chain_ok(node.key[0])
            return got

        rows = []
        for nid in sorted(nodes):       # node ids are parent-first monotonic
            node = nodes[nid]
            if not _chain_ok(nid):
                continue
            rows.append({"id": nid, "parent": node.key[0],
                         "tokens": np.frombuffer(node.key[1],
                                                 np.int32).tolist(),
                         "n_tokens": node.n_tokens,
                         "name": tier._disk[nid]})
        doc = {"version": TIER_INDEX_VERSION, "page_size": self.page_size,
               "nodes": rows}
        tier.store.put_blob(f"kvindex_{tag}",
                            json.dumps(doc, sort_keys=True).encode("utf-8"))
        tier.mark_shared(r["id"] for r in rows)
        return len(rows)

    def load_tier_index(self) -> int:
        """Merge every readable ``kvindex_*`` blob in the attached store
        into the live prefix index: each published node whose parent chain
        resolves (locally known, or imported by an earlier row) and whose
        page object still exists becomes an off-device node of THIS cache,
        restorable through the ordinary one-scatter tier path.  Remote node
        ids are remapped to fresh local ids as the rows are walked
        parent-first.  Rows that are corrupt, version- or geometry-skewed,
        already cached here, or missing their page object are skipped — a
        damaged store can only cost a re-prefill, never a crash or a wrong
        match (token content rides in the index, so the rebuilt
        rolling-hash entries verify exactly like locally-registered ones).
        Idempotent: re-merging is how a decode replica refreshes its view
        of a shared store between handoffs.  Returns nodes imported."""
        tier = self._tier
        if tier is None or tier.store is None:
            return 0
        imported = 0
        for _, payload in tier.store.blobs("kvindex_"):
            try:
                doc = json.loads(payload.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                continue                # corrupt blob: ignore entirely
            if not isinstance(doc, dict) \
                    or doc.get("version") != TIER_INDEX_VERSION \
                    or doc.get("page_size") != self.page_size \
                    or not isinstance(doc.get("nodes"), list):
                continue                # version/geometry skew: ignore
            idmap = {_ROOT: _ROOT}
            for row in doc["nodes"]:
                try:
                    rid = int(row["id"])
                    parent = int(row["parent"])
                    toks = np.asarray(row["tokens"], np.int32)
                    ntok = int(row["n_tokens"])
                    name = str(row["name"])
                except (KeyError, TypeError, ValueError):
                    continue            # malformed row: skip
                if parent not in idmap or toks.ndim != 1 \
                        or toks.size != ntok \
                        or not 0 < ntok <= self.page_size:
                    continue
                key = (idmap[parent], toks.tobytes())
                known = self._index.get(key)
                if known is not None:   # already cached here (any level)
                    idmap[rid] = known.node_id
                    continue
                if not tier.store.exists(name):
                    continue            # page object gone: chain ends here
                nid = next(self._node_ids)
                node = _PrefixNode(nid, key, HOST_PAGE, ntok)
                self._index[key] = node
                self._register_partial(node)
                self._tier_nodes[nid] = node
                tier.import_entry(nid, name)
                idmap[rid] = nid
                imported += 1
        return imported

    def pool_pressure(self) -> float:
        """Fraction of the real pool in live use (0.0 idle .. 1.0 full) —
        the overload gauge victim selection and dashboards key on."""
        return self.pages_in_use() / max(1, self.num_pages - 1)

    def attach_metrics(self, registry) -> None:
        """Register page-accounting observability on a
        `inference.metrics.MetricsRegistry`: pull gauges over the free/in-use/
        evictable partition (evaluated only at scrape/snapshot time — the
        allocator hot path pushes nothing) and a monotonic counter mirroring
        `prefix_evictions` (the int attribute stays authoritative for
        `stats()`; the counter is the Prometheus face of the same events)."""
        self._evictions_counter = registry.counter(
            "prefix_evictions", "cached prefix pages reclaimed under pressure")
        registry.gauge("kv_pages_in_use", self.pages_in_use,
                       "pages with refcount > 0")
        registry.gauge("kv_pages_free", lambda: self.num_free_pages,
                       "pages immediately allocatable")
        registry.gauge("kv_pages_evictable", lambda: self.num_evictable_pages,
                       "refcount-0 cached prefix pages, reclaimable on demand")
        registry.gauge("prefix_cached_pages", lambda: len(self._index),
                       "pages registered in the prefix index")
        registry.gauge("kv_pages_swapped", lambda: self.swapped_page_count,
                       "pages whose KV lives in the host swap pool")
        registry.gauge("kv_tier_pages_host", lambda: self.tier_pages_host,
                       "spilled prefix pages resident in the host KV tier")
        registry.gauge("kv_tier_pages_disk", lambda: self.tier_pages_disk,
                       "spilled prefix pages serialized to the disk tier")
        # ratio gauge: a fleet merge folds it by MAX (a sum of per-replica
        # fractions would read >100% on a healthy fleet; the router's signal
        # is the worst member)
        registry.gauge("kv_pool_pressure", self.pool_pressure,
                       "fraction of the page pool in live use", agg="max")

    # ---- prefix index -----------------------------------------------------
    def _match(self, tokens: np.ndarray
               ) -> Tuple[List[_PrefixNode],
                          Optional[Tuple[_PrefixNode, int]]]:
        """Longest cached prefix of `tokens`, capped at len(tokens) - 1 so at
        least one position is always recomputed (its logits seed generation).
        Returns (full-page nodes, optional (partial node, matched tokens)
        extending them).  Full nodes may live off-device (page == HOST_PAGE)
        when a tier is attached — the caller restores them.  The partial
        match runs over the rolling-hash index: ANY registered page whose
        content starts with the prompt's tail yields a COW hit, not just a
        page registered under that exact partial content (the PR-2
        behavior this subsumes)."""
        page = self.page_size
        lp = tokens.size
        full: List[_PrefixNode] = []
        parent = _ROOT
        for i in range((lp - 1) // page):
            node = self._index.get((parent, tokens[i * page:(i + 1) * page]
                                    .tobytes()))
            if node is None:
                break
            full.append(node)
            parent = node.node_id
        base = len(full) * page
        partial = None
        h = 0
        for j in range(1, min(lp - base - 1, page - 1) + 1):
            h = (h * _HASH_BASE + int(tokens[base + j - 1]) + 1) % _HASH_MOD
            if j < _MIN_PARTIAL:
                continue
            node = self._partial.get((parent, j, h))
            if node is not None and \
                    node.key[1][:4 * j] == tokens[base:base + j].tobytes():
                partial = (node, j)     # longest verified hit wins
        return full, partial

    def _register_partial(self, node: _PrefixNode) -> None:
        """Index every proper prefix of `node`'s token content under the
        rolling hash (first registrant wins a colliding key — equal content
        hashes equally, so the match outcome is unaffected)."""
        toks = np.frombuffer(node.key[1], np.int32)
        cap = node.n_tokens if node.n_tokens < self.page_size \
            else self.page_size - 1
        h = 0
        parent = node.key[0]
        for j in range(1, cap + 1):
            h = (h * _HASH_BASE + int(toks[j - 1]) + 1) % _HASH_MOD
            if j < _MIN_PARTIAL:
                continue
            k = (parent, j, h)
            if k not in self._partial:
                self._partial[k] = node
                node.partial_keys.append(k)

    def _drop_node(self, node: _PrefixNode) -> None:
        """Remove a node from every index structure (its page, if any, is
        NOT touched — callers manage the free list)."""
        del self._index[node.key]
        for k in node.partial_keys:
            if self._partial.get(k) is node:
                del self._partial[k]
        node.partial_keys = []
        if node.page >= 0:
            self._page_node.pop(node.page, None)
        elif self._tier is not None:
            self._tier_nodes.pop(node.node_id, None)
            self._tier.drop(node.node_id)

    def register_prefix(self, slot: int, tokens: np.ndarray,
                        filled: int, upgrade: bool = False) -> None:
        """Publish `slot`'s prompt pages whose KV is complete (the first
        `filled` of `tokens`) into the prefix index.  Idempotent — call after
        every prefill chunk; already-indexed keys (including pages this slot
        itself shares) are left untouched, so duplicate concurrent prompts
        simply keep their private pages unregistered.  The final partial page
        is registered only once the whole prompt is in (filled == len) — its
        content hash must cover exactly the prompt tail, and the slot keeps
        appending decode tokens past it (harmless: the node only ever claims
        the first n_tokens of the page; COW borrowers overwrite the rest).

        `upgrade=True` (finish-time registration of GENERATED pages): a page
        this slot owns that is already claimed by a SHORTER partial node —
        the prompt-time claim over the prompt's tail, which the slot has
        since decoded past — is re-keyed in place to the longer content
        (`_upgrade_node`), instead of stopping the walk at it.  Both claims
        are true of the page's KV (the slot appended in place), so the
        upgrade only widens what future prompts can match."""
        tokens = np.asarray(tokens, np.int32)
        page = self.page_size
        pages = self._used[slot]
        parent = _ROOT
        for i in range(min(filled, tokens.size) // page):
            key = (parent, tokens[i * page:(i + 1) * page].tobytes())
            node = self._index.get(key)
            if node is None:
                holder = self._page_node.get(pages[i])
                if holder is None:
                    node = _PrefixNode(next(self._node_ids), key, pages[i],
                                       page)
                    self._index[key] = node
                    self._page_node[pages[i]] = node
                    self._register_partial(node)
                elif upgrade and holder.key[0] == parent and \
                        holder.n_tokens < page and \
                        key[1].startswith(holder.key[1]):
                    node = self._upgrade_node(holder, key, page)
            if node is None:        # page already published under another key
                return
            parent = node.node_id
        rem = tokens.size % page
        if rem and filled == tokens.size:
            i = tokens.size // page
            key = (parent, tokens[i * page:].tobytes())
            if key in self._index:
                return
            holder = self._page_node.get(pages[i])
            if holder is None:
                node = _PrefixNode(next(self._node_ids), key, pages[i], rem)
                self._index[key] = node
                self._page_node[pages[i]] = node
                self._register_partial(node)
            elif upgrade and holder.key[0] == parent and \
                    holder.n_tokens < rem and \
                    key[1].startswith(holder.key[1]):
                self._upgrade_node(holder, key, rem)

    def _upgrade_node(self, node: _PrefixNode, key: Tuple[int, bytes],
                      n_tokens: int) -> _PrefixNode:
        """Re-key `node` to a LONGER claim over the same page (finish-time
        registration: the owning slot decoded past the original claim, so
        the page now holds more verified content).  Identity — node_id,
        page, refcount/LRU state, trie children keyed by node_id — is
        preserved; only the content key and the rolling-hash partial
        entries move."""
        del self._index[node.key]
        for k in node.partial_keys:
            if self._partial.get(k) is node:
                del self._partial[k]
        node.partial_keys = []
        node.key = key
        node.n_tokens = n_tokens
        self._index[key] = node
        self._register_partial(node)
        return node

    def _evict(self, fresh_needed: int) -> None:
        """Reclaim LRU unreferenced cached prefixes until `fresh_needed`
        pages are on the free list (or the LRU runs dry).  With a tier
        attached, evicted nodes are offered to the engine's spill callback
        in ONE batch (one fixed-shape `swap_out_pages` gather per
        `max_pages_per_slot` pages, d2h deferred): accepted nodes keep their
        index entry with `page = HOST_PAGE`; the rest drop as before.  The
        page returns to the free list either way — the gather dispatch is
        ordered before any dispatch that could overwrite the page, so its
        content is safe to fetch later."""
        evicted: List[_PrefixNode] = []
        while len(self._free) < fresh_needed and self._lru:
            _, node = self._lru.popitem(last=False)
            evicted.append(node)
            self._free.append(node.page)
            self.prefix_evictions += 1
            if self._evictions_counter is not None:
                self._evictions_counter.inc()
        if not evicted:
            return
        accepted: Set[int] = set()
        if self._spill_cb is not None:
            accepted = self._spill_cb(evicted)
        for node in evicted:
            if node.node_id in accepted:
                del self._page_node[node.page]
                node.page = HOST_PAGE
                self._tier_nodes[node.node_id] = node
                self._tier.add_pending(node.node_id)
            else:
                self._drop_node(node)

    # ---- slot lifecycle ---------------------------------------------------
    def allocate(self, slot: int, total_tokens: int) -> np.ndarray:
        """Reserve ceil(total_tokens / page_size) pages for `slot` and write
        them into its table row.  Returns the row (view)."""
        row, _, _ = self.allocate_prefixed(slot, total_tokens, None)
        return row

    def allocate_prefixed(self, slot: int, total_tokens: int,
                          tokens: Optional[np.ndarray] = None
                          ) -> Tuple[np.ndarray, int, Optional[Tuple[int, int]]]:
        """Reserve `slot`'s footprint, sharing the longest cached prefix of
        the prompt `tokens` (when given) instead of allocating fresh pages.

        Returns (table row view, matched_tokens, cow):
        - matched_tokens: prompt tokens whose KV the slot starts with —
          full shared pages (mapped read-only, refcount++), full pages
          restored from the KV tier (fresh pages the engine scatters the
          parked content into — see `take_restore`) plus, when `cow` is set
          or a tier partial matched, the matched tokens of a partial page;
        - cow: (src_page, dst_page) the CALLER must copy on device before the
          slot writes anything — dst is the slot's own fresh page at the
          partial boundary, src a cached DEVICE page it must not mutate (an
          off-device partial source rides the restore plan instead: the
          scatter IS the copy).

        When the match includes off-device nodes the engine MUST consume the
        restore plan (`take_restore(slot)`) and either scatter +
        `commit_restore` or roll the slot back (`release`) — `matched`
        already counts the planned tokens.
        """
        n = self.pages_needed(total_tokens)
        if n > self.max_pages_per_slot:
            raise ValueError(
                f"request footprint {total_tokens} tokens exceeds slot "
                f"capacity {self.max_pages_per_slot * self.page_size}")
        if self._used[slot]:
            raise RuntimeError(f"slot {slot} already has pages")
        full: List[_PrefixNode] = []
        partial = None
        if tokens is not None:
            full, partial = self._match(np.asarray(tokens, np.int32))
        shared = []                     # device pages shared (for rollback)
        for node in full:
            if node.page < 0:
                continue                # off-device: restored, not shared
            if self._ref[node.page] == 0:
                self._lru.pop(node.node_id, None)   # revive from evictable
            self._ref[node.page] += 1
            shared.append(node.page)
        pnode, pmatch = partial if partial is not None else (None, 0)
        # pin the COW source for the duration of this allocation: it must not
        # be evicted to satisfy our own fresh-page demand
        if pnode is not None and pnode.node_id in self._lru:
            self._lru.move_to_end(pnode.node_id)
            pinned = self._lru.pop(pnode.node_id)
        else:
            pinned = None
        fresh_needed = n - len(shared)
        self._evict(fresh_needed)
        if pinned is not None:
            self._lru[pinned.node_id] = pinned
        if fresh_needed > len(self._free) and pnode is not None:
            # the partial hit is a luxury the pool cannot afford: its pinned
            # COW source may be the very page this allocation needs (a
            # full-footprint request would otherwise wait forever on an
            # idle engine).  Drop the partial match — the source returns to
            # the LRU, evictable like any other parked page — and retry.
            pnode, pmatch = None, 0
            self._evict(fresh_needed)
        if fresh_needed > len(self._free):
            for p in reversed(shared):              # roll back the sharing
                self._ref[p] -= 1
                if self._ref[p] == 0:
                    self._lru[self._page_node[p].node_id] = self._page_node[p]
            raise RuntimeError(
                f"out of KV pages: need {fresh_needed}, "
                f"free {len(self._free)}")
        fresh = [self._free.pop() for _ in range(fresh_needed)]
        for p in fresh:
            self._ref[p] = 1
        # lay the row out chain-position-accurately: device nodes keep their
        # shared page at their prefix position, off-device nodes take the
        # next fresh page (the engine scatters their parked KV into it), and
        # the remaining fresh pages fill the tail
        pages: List[int] = []
        plan: List[Tuple[int, _PrefixNode, int]] = []
        fi = 0
        for node in full:
            if node.page >= 0:
                pages.append(node.page)
            else:
                pages.append(fresh[fi])
                plan.append((fresh[fi], node, self.page_size))
                fi += 1
        boundary = len(pages)
        pages.extend(fresh[fi:])
        self._used[slot] = pages
        self.page_table[slot, :] = NULL_PAGE
        self.page_table[slot, :n] = pages
        matched = boundary * self.page_size
        cow = None
        if pnode is not None:
            if pnode.page >= 0:
                cow = (pnode.page, pages[boundary])
            else:
                # off-device partial: the restore scatter into the slot's own
                # boundary page IS the copy; the node stays in the tier (the
                # slot appends past the matched fraction, so the page cannot
                # re-register under the node)
                plan.append((pages[boundary], pnode, pmatch))
            matched += pmatch
        if plan:
            self._restore_plan[slot] = plan
        return self.page_table[slot], matched, cow

    def take_restore(self, slot: int
                     ) -> List[Tuple[int, _PrefixNode, int]]:
        """Pop the off-device part of `slot`'s latest `allocate_prefixed`
        match: [(dst_page, node, n_tokens)] the engine must scatter from the
        tier into the slot's fresh pages (ONE `swap_in_pages` dispatch)
        before the slot computes anything.  Empty when the match was
        all-device."""
        return self._restore_plan.pop(slot, [])

    def commit_restore(self, slot: int,
                       plan: List[Tuple[int, _PrefixNode, int]]) -> None:
        """The restore scatter landed: full-page nodes move back to the
        device tier (their fresh page now holds their exact content, so
        they are matchable/shareable/re-spillable like any registered
        page); a partial node stays in the tier — the slot appends past the
        matched fraction, so its page diverges from the node content."""
        for dst, node, ntok in plan:
            if ntok == self.page_size and node.n_tokens == self.page_size:
                node.page = dst
                self._page_node[dst] = node
                self._tier_nodes.pop(node.node_id, None)
                self._tier.pop(node.node_id)

    def grow(self, slot: int, total_tokens: int) -> None:
        """Optimistic admission's token-granular growth: extend `slot`'s
        mapping so it covers `total_tokens` positions, allocating fresh pages
        (evicting LRU-parked prefixes on demand) past what it already holds.
        No-op when the slot already covers the footprint — the engine calls
        this before every decode/verify dispatch, so the common case must be
        one integer compare.  Raises RuntimeError when the pool cannot supply
        the pages — the engine's preemption trigger."""
        n = self.pages_needed(total_tokens)
        have = len(self._used[slot])
        if n <= have:
            return
        if n > self.max_pages_per_slot:
            raise ValueError(
                f"slot {slot} growth to {total_tokens} tokens exceeds slot "
                f"capacity {self.max_pages_per_slot * self.page_size}")
        need = n - have
        self._evict(need)
        if need > len(self._free):
            raise RuntimeError(
                f"out of KV pages growing slot {slot}: need {need}, "
                f"free {len(self._free)}")
        fresh = [self._free.pop() for _ in range(need)]
        for p in fresh:
            self._ref[p] = 1
        self.page_table[slot, have:n] = fresh
        self._used[slot].extend(fresh)

    # ---- host swap pool accounting (fourth partition) ---------------------
    def note_swap_out(self, request_id: int, n_pages: int) -> None:
        """Record that `n_pages` of KV for `request_id` now live in the host
        swap pool (the device pages are released separately — this partition
        tracks the off-device obligation)."""
        if n_pages < 1:
            raise ValueError(f"swap-out of {n_pages} pages")
        if request_id in self._swapped:
            raise RuntimeError(f"request {request_id} already swapped out")
        self._swapped[request_id] = n_pages

    def note_swap_in(self, request_id: int) -> int:
        """Clear `request_id`'s swap-pool obligation (swap-in completed, the
        request was aborted/timed out, or the swap degraded to recompute).
        Returns the page count released from the host pool (0 if unknown)."""
        return self._swapped.pop(request_id, 0)

    def release(self, slot: int) -> None:
        """Retire a slot: decrement its pages' refcounts; pages reaching 0 go
        back to the free list, unless they are registered cached prefixes —
        those park in the LRU and stay matchable until evicted.  An abort
        landing between `allocate_prefixed` and `take_restore` (or after a
        failed restore) must not leak the un-consumed restore plan: the plan
        is discarded here — the planned nodes simply stay in the tier."""
        self._restore_plan.pop(slot, None)
        for p in reversed(self._used[slot]):
            self._ref[p] -= 1
            if self._ref[p] == 0:
                node = self._page_node.get(p)
                if node is not None:
                    self._lru[node.node_id] = node
                    self._lru.move_to_end(node.node_id)
                else:
                    self._free.append(p)
        self._used[slot] = []
        self.page_table[slot, :] = NULL_PAGE
        self.lengths[slot] = 0

    def pages_in_use(self) -> int:
        """Distinct pages with refcount > 0 (cached-but-unreferenced prefixes
        do not count — they are reclaimable).  O(1) via the free/LRU/in-use
        partition over the real pages (asserted by check_invariants) — this
        runs on the scheduler hot path every step for the trace ring, so it
        must not scan refcounts on a production-sized pool."""
        return self.num_pages - 1 - len(self._free) - len(self._lru)

    def check_invariants(self) -> None:
        """Assert the refcount/free-list/LRU partition is consistent — every
        real page is exactly one of {free, refcounted-in-use, parked in the
        evictable LRU}, and refcounts equal the number of slot rows mapping
        the page.  Tests call this around speculative rollback and abort to
        prove neither path can leak or double-free a page."""
        assert (self._ref >= 0).all(), "negative refcount"
        assert self._ref[NULL_PAGE] == 0, "null page must never be refcounted"
        counts = np.zeros((self.num_pages,), np.int64)
        for pages in self._used.values():
            for p in pages:
                counts[p] += 1
        assert (counts == self._ref).all(), \
            f"refcounts {self._ref.tolist()} != slot usage {counts.tolist()}"
        free = set(self._free)
        lru = {n.page for n in self._lru.values()}
        used = {p for p in range(1, self.num_pages) if self._ref[p] > 0}
        assert len(free) == len(self._free), "duplicate page on free list"
        assert not (free & lru) and not (free & used) and not (lru & used), \
            "page in more than one of free/LRU/in-use"
        assert free | lru | used == set(range(1, self.num_pages)), \
            "page leaked out of free/LRU/in-use partition"
        assert self.pages_in_use() == len(used), \
            "O(1) pages_in_use diverged from the refcount scan"
        for node in self._lru.values():
            assert self._index.get(node.key) is node, "LRU node unregistered"
            assert node.page >= 0, "off-device node parked in the device LRU"
        for page, node in self._page_node.items():
            assert node.page == page
        # fourth (host-side) partition: every swap-pool obligation is a
        # positive page count, and the total matches the O(1) mirror — a
        # swapped request that was aborted/resumed without clearing its entry
        # is a host-pool leak even though the device partition looks clean
        for rid, n in self._swapped.items():
            assert 0 < n <= self.max_pages_per_slot, \
                f"swapped request {rid} records {n} pages"
        # fifth (tier) partition: every indexed node is EITHER a device node
        # (page mapped in _page_node) or an off-device node whose content the
        # tier tracks (host, pending, or disk) — and vice versa, the tier
        # holds no entry the index forgot (a dropped node whose tier bytes
        # survive is a host-memory leak)
        off_device = 0
        for node in self._index.values():
            if node.page >= 0:
                assert self._page_node.get(node.page) is node, \
                    f"device node {node.node_id} not in the page map"
            else:
                off_device += 1
                assert self._tier is not None and \
                    self._tier.has(node.node_id), \
                    f"off-device node {node.node_id} has no tier entry"
                assert self._tier_nodes.get(node.node_id) is node, \
                    f"off-device node {node.node_id} missing from _tier_nodes"
        if self._tier is not None:
            assert off_device == self._tier.pages_host + \
                self._tier.pages_disk, \
                (f"tier holds {self._tier.pages_host}+"
                 f"{self._tier.pages_disk} pages but the index has "
                 f"{off_device} off-device nodes")
            assert len(self._tier_nodes) == off_device
        else:
            assert off_device == 0, "off-device node with no tier attached"
        for k, node in self._partial.items():
            assert self._index.get(node.key) is node, \
                f"partial-index entry {k} points at an unregistered node"
            assert k in node.partial_keys
        # sixth (restore-plan) partition: a pending plan may only exist for a
        # slot that is still allocated (release() discards the plan, so an
        # aborted admission cannot strand one), and every planned placement
        # targets a page the slot actually holds, sourced from a registered
        # off-device node — the plan is a view over live state, never an
        # owner of pages or tier entries
        for slot, plan in self._restore_plan.items():
            assert self._used[slot], \
                f"restore plan pending for released slot {slot}"
            row = set(self._used[slot])
            for dst, node, n_tokens in plan:
                assert dst in row, \
                    f"slot {slot} restore plan targets foreign page {dst}"
                assert self._index.get(node.key) is node and node.page < 0, \
                    (f"slot {slot} restore plan sources node {node.node_id} "
                     f"that is no longer an off-device index node")

    def prefix_stats(self) -> Dict[str, int]:
        return {
            "cached_pages": len(self._index),
            "evictable_pages": len(self._lru),
            "prefix_evictions": self.prefix_evictions,
            "tier_pages_host": self.tier_pages_host,
            "tier_pages_disk": self.tier_pages_disk,
        }
