"""Per-request tracing: a structured event timeline for every `Request`.

Reference lineage: the reference repo's profiler subsystem records *spans*
(`RecordEvent` + the HostTracer/ChromeTracingLogger pair behind
`AnalysisPredictor`) keyed by host phase — good for "what was the process
doing", useless for "what happened to request 4711".  Serving stacks flip the
key: vLLM and production gateways treat the per-request timeline (enqueue ->
admit -> prefill chunks -> verify events -> preempt/swap -> finish) as the
primary debug surface for tail latency, because a p99 outlier is always ONE
request's story.  This module is that surface for `inference.engine.LLMEngine`:

- **`RequestTrace`** — an append-only list of plain-dict events stamped
  through the engine's injectable clock.  The hot-path cost of one event is a
  dict literal + a list append (no formatting, no locking, no device access);
  event volume is bounded by construction — admission-, chunk- and
  verify-granular, never per-decode-token.
- **Chrome export** (`RequestTrace.to_chrome()`) — the timeline rendered as a
  chrome-tracing span tree on the request's own track (`tid` = request id):
  a root `request/<rid>` span covering enqueue -> finish, child phase spans
  (`queued`, `prefill`, `decode`) derived from the lifecycle stamps, and one
  instant per raw event carrying its attributes.  Opens in the same
  ``chrome://tracing`` / Perfetto flow as the engine's `trace(dir)` host
  traces — and `LLMEngine.export_request_trace(rid)` / the obs server's
  ``GET /requests/<rid>`` serve exactly this dict.

Exemplars close the loop from the *aggregate* side: the engine attaches
``{request_id, trace}`` exemplar labels to its latency-histogram observations
(`inference.metrics.Histogram.observe(v, exemplar=...)`), so the request id
behind a p99 TTFT bucket is right on the scrape line — one
``GET /requests/<rid>`` away from this timeline.
"""
from __future__ import annotations

from typing import Dict, List, Optional


# Event names the engine stamps (one tuple so tests and dashboards don't
# chase string literals through the scheduler).  `finish` carries the retire
# reason (stop/length/abort/timeout/rejected) — there is deliberately no
# separate abort/timeout event.
REQUEST_EVENTS = (
    "enqueue",          # add_request: prompt_len/max_new_tokens/priority
    "admit",            # popped into a slot: slot, prefix hit, COW
    "prefill",          # bucketed one-shot prefill: n tokens in one pass
    "prefill_chunk",    # one staged chunk: q_offset + n tokens
    "first_token",      # joined the decode set
    "spec_verify",      # one drafted verify event: drafted/accepted/emitted
    "grow_fail",        # optimistic page growth failed (preemption trigger)
    "preempt",          # evicted: kind (swap intent vs recompute), pages
    "swap_out",         # victim KV materialized into the host pool
    "swap_degrade",     # a failed swap copy fell back to recompute
    "swap_in",          # parked KV restored by one h2d scatter
    "finish",           # retired: reason + generated-token count
)


class RequestTrace:
    """The structured event timeline of one request.

    `events` is a list of plain dicts ``{"t": <engine-clock>, "name": <str>,
    ...attrs}``, appended in stamp order (the engine clock is monotonic, so
    the list is time-sorted by construction).  JSON-serializable as-is —
    this IS the `RequestOutput.trace` payload and the obs server's
    ``/requests/<rid>`` source."""

    __slots__ = ("request_id", "events")

    def __init__(self, request_id: int):
        self.request_id = request_id
        self.events: List[Dict[str, object]] = []

    def event(self, t: float, name: str, **attrs) -> None:
        self.events.append({"t": t, "name": name, **attrs})

    def _first(self, name: str) -> Optional[float]:
        for e in self.events:
            if e["name"] == name:
                return e["t"]
        return None

    def to_chrome(self) -> Dict[str, object]:
        """Render the timeline as a chrome-tracing span tree.

        Layout (all on the request's own track, ``tid`` = request id):
        - root ``request/<rid>`` complete span, enqueue -> last event;
        - child phase spans derived from the lifecycle stamps: ``queued``
          (enqueue -> first admit), ``prefill`` (first admit -> first token)
          and ``decode`` (first token -> last event) — phases a request never
          reached are simply absent (an abort while queued has only the
          ``queued`` child);
        - one instant event per raw timeline entry, attributes under
          ``args`` — preemption cycles show as preempt/swap/admit instants
          inside the ``decode`` span rather than re-segmenting the phases.

        Timestamps are microseconds relative to enqueue (chrome-trace
        convention); durations are clamped >= 0 so a fake clock that never
        advances still produces a valid (zero-width) tree."""
        rid = self.request_id
        if not self.events:
            return {"traceEvents": [], "displayTimeUnit": "ms"}
        t0 = self.events[0]["t"]

        def us(t):
            return max(0.0, (t - t0) * 1e6)

        t_end = self.events[-1]["t"]
        t_admit = self._first("admit")
        t_first = self._first("first_token")
        out: List[Dict[str, object]] = [{
            "name": f"request/{rid}", "ph": "X", "ts": 0.0, "dur": us(t_end),
            "pid": 0, "tid": rid, "args": {"request_id": rid},
        }]

        def phase(name, a, b):
            out.append({"name": name, "ph": "X", "ts": us(a),
                        "dur": max(0.0, us(b) - us(a)), "pid": 0, "tid": rid})

        phase("queued", t0, t_admit if t_admit is not None else t_end)
        if t_admit is not None:
            phase("prefill", t_admit,
                  t_first if t_first is not None else t_end)
        if t_first is not None:
            phase("decode", t_first, t_end)
        for e in self.events:
            args = {k: v for k, v in e.items() if k not in ("t", "name")}
            out.append({"name": e["name"], "ph": "i", "ts": us(e["t"]),
                        "pid": 0, "tid": rid, "s": "t", "args": args})
        return {"traceEvents": out, "displayTimeUnit": "ms"}
