"""Fault injection for the serving engine's overload machinery.

Preemption, KV swapping and deadline expiry are exactly the code paths that
never fire under a healthy CPU-smoke load — and exactly the ones that corrupt
page accounting when they are wrong.  `FaultPlan` is the injectable chaos
plan tests hand to `LLMEngine(fault_plan=...)` to force those paths
deterministically:

- **pool pressure** (`pressure_steps`): at each listed engine step, the first
  optimistic-admission page-growth attempt is treated as out-of-pages, forcing
  a preemption even when the pool has room — the trigger for
  preempt-mid-verify / preempt-mid-chunk-prefill interleavings.
- **failing copies** (`fail_d2h` / `fail_h2d`): the next N swap-out
  device->host materializations / swap-in host->device restores raise
  `FaultInjected`; the engine must degrade the victim to recompute with zero
  leaked pages (and zero leaked host copies).
- **clock skew** (`skew_s`): added to the engine clock ONLY when deadlines
  are evaluated — a monotonic-clock jump (NTP step, VM migration) must at
  worst expire requests early with clean `finish_reason="timeout"`
  accounting, never wedge or leak.

The plan is mutable state (consumed injections are spent); build a fresh one
per engine.  Production engines run with the inert default plan — every hook
is a cheap attribute read returning falsy.

The health plane rides the same hooks: forced pool pressure drives the
preemption rate that flips `/healthz` to 503 (and back to 200 once the rate
window ages out), and clock skew drives deadline timeouts — the SLO
burn-rate and admission-saturation signals — so every
ok/degraded/overloaded transition is testable deterministically under the
fake clock (see tests/test_observability.py).
"""
from __future__ import annotations

import dataclasses
from typing import FrozenSet, Iterable


class FaultInjected(RuntimeError):
    """Raised by injected d2h/h2d copy failures — the ONLY exception the
    engine's swap fallback catches (a real transfer failure must propagate)."""


@dataclasses.dataclass
class FaultPlan:
    """A deterministic chaos plan for one engine instance.  All fields
    default to inert; see module docstring for semantics."""
    pressure_steps: Iterable[int] = ()
    fail_d2h: int = 0
    fail_h2d: int = 0
    skew_s: float = 0.0

    def __post_init__(self):
        self._pressure: FrozenSet[int] = frozenset(self.pressure_steps)
        self._fired_pressure: set = set()
        self._d2h_left = int(self.fail_d2h)
        self._h2d_left = int(self.fail_h2d)

    def pool_pressure(self, step: int) -> bool:
        """True at most ONCE per listed step: the engine treats the first
        growth attempt of that step as a failed allocation."""
        if step in self._pressure and step not in self._fired_pressure:
            self._fired_pressure.add(step)
            return True
        return False

    def d2h(self) -> None:
        """Called before each swap-out materialization; raises while the
        injected d2h failure budget lasts."""
        if self._d2h_left > 0:
            self._d2h_left -= 1
            raise FaultInjected("injected swap-out d2h copy failure")

    def h2d(self) -> None:
        """Called before each swap-in restore dispatch; raises while the
        injected h2d failure budget lasts."""
        if self._h2d_left > 0:
            self._h2d_left -= 1
            raise FaultInjected("injected swap-in h2d copy failure")

    def skew(self) -> float:
        """Clock skew applied to deadline evaluation only."""
        return self.skew_s
