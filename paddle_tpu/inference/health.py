"""Engine health: fold the live signal plane into one routable state.

Reference lineage: the reference repo's monitor layer couples live telemetry
to enforced thresholds (the `tools/` CI-check row of the survey) — serving
fleets do the same at runtime: a load balancer does not read 40 gauges, it
reads ONE health state per replica and the reasons behind it.  This module is
that fold for `inference.engine.LLMEngine`: `evaluate_engine_health()` turns
the windowed rates (`inference.metrics.RateWindow`), the SLO burn rates, the
pool-pressure gauge, admission-saturation rates and the steady-state
recompile anomaly counter into

    {"state": "ok" | "degraded" | "overloaded",
     "code": 0 | 1 | 2,
     "reasons": [<one line per non-ok signal>],
     "signals": {<per-signal state + value + threshold>},
     "burn_rates": {<window label>: <burn>}}

against the targets declared ONCE in `analysis.registry.SERVE_SLO`.  The obs
server's ``GET /healthz`` serves this report with 200/503 semantics
(overloaded — or an evaluation that cannot run at all — is 503, so a probe
takes the replica out of rotation; degraded still serves traffic and stays
200 with the state in the body), the ``engine_health`` gauge exposes the
numeric code (fleet merge folds it worst-of via ``agg="max"``), and
``stats()["health"]`` carries the compact state+reasons pair.

Signal semantics (every threshold from SERVE_SLO; each signal is evaluated
independently and the overall state is the WORST signal):

- **slo_burn** — multi-window deadline-attainment burn: the in-window miss
  fraction over the error budget ``1 - deadline_attainment_target``.  Either
  window at or above `burn_degraded` degrades; the fast window at or above
  `burn_overloaded` WITH the slow window confirming (>= `burn_degraded`)
  overloads — the classic two-window rule that ignores blips.  Windows with
  no deadline-bearing retirements burn 0.0 (no data is not an outage).
- **ttft_p99 / tpot_p99** — the lifecycle histograms' p99 against the
  declared bounds; degraded only (slow is not down).
- **pool_pressure** — the live pages-in-use fraction at or above
  `pressure_ceiling`; degraded only (pressure with consequences shows up in
  the preemption/timeout signals below).
- **preemption** — preemptions/s over the fast ~10s window: degraded at
  `preempt_rate_degraded`, overloaded at `preempt_rate_overloaded` (the
  FaultPlan pressure-injection tests drive exactly this path).
- **admission** — saturation at the front door: any timeout or intake
  rejection inside the fast window degrades; timeouts/s at or above
  `timeout_rate_overloaded` overloads (the engine sheds load as fast as it
  serves — clock-skew injection drives this deterministically).
- **recompiles** — `steady_state_recompiles` > 0 degrades: a fixed-shape
  engine that recompiles after warm is silently paying seconds per step.

All inputs are host-side reads (counters, rate rings, page accounting) — no
device sync, no dispatch, no compiled-program change.
"""
from __future__ import annotations

from typing import Dict, List

from ..analysis.registry import SERVE_SLO

# ordered severities; the numeric code is what the engine_health gauge
# exposes and FleetMetrics max-folds (worst-of, never sum)
HEALTH_STATES = ("ok", "degraded", "overloaded")
HEALTH_CODES: Dict[str, int] = {s: i for i, s in enumerate(HEALTH_STATES)}


def burn_rate(req_window, met_window, window_s: float,
              target: float) -> float:
    """Deadline-attainment burn over one window: in-window miss fraction
    over the error budget.  `req_window`/`met_window` are the RateWindows
    over the `deadline_requests` / `deadline_met` counters — sampled at the
    same instants, so their references share timestamps and the elapsed
    time cancels exactly (the ratio of deltas IS the miss fraction)."""
    req = req_window.delta(window_s)
    if req <= 0.0:
        return 0.0                      # no deadline traffic: nothing burns
    miss = max(0.0, req - met_window.delta(window_s)) / req
    budget = 1.0 - float(target)
    if budget <= 0.0:                   # target 1.0: any miss is infinite burn
        return 0.0 if miss == 0.0 else float("inf")
    return miss / budget


def evaluate_engine_health(engine, slo: Dict[str, object] = None
                           ) -> Dict[str, object]:
    """The health report (module docstring) for one engine, read entirely
    from host state.  `slo` overrides `SERVE_SLO` (tests tighten single
    thresholds without re-declaring the whole contract)."""
    cfg = dict(SERVE_SLO)
    if slo:
        cfg.update(slo)
    signals: Dict[str, Dict[str, object]] = {}
    reasons: List[str] = []

    def note(name: str, state: str, reason: str, **detail):
        signals[name] = {"state": state, **detail}
        if state != "ok":
            reasons.append(f"{name}: {reason}")

    # ---- SLO burn (multi-window deadline attainment) ----------------------
    windows = engine._rw_deadline_req.windows
    fast_lbl = str(cfg["burn_window_fast"])
    slow_lbl = str(cfg["burn_window_slow"])
    target = float(cfg["deadline_attainment_target"])
    burns = {lbl: burn_rate(engine._rw_deadline_req, engine._rw_deadline_met,
                            w, target) for lbl, w in windows}
    bf, bs = burns[fast_lbl], burns[slow_lbl]
    deg, over = float(cfg["burn_degraded"]), float(cfg["burn_overloaded"])
    if bf >= over and bs >= deg:
        state = "overloaded"
    elif bf >= deg or bs >= deg:
        state = "degraded"
    else:
        state = "ok"
    note("slo_burn", state,
         f"deadline-attainment burn {bf:.2f}x budget over {fast_lbl} "
         f"({bs:.2f}x over {slow_lbl}; target {target})",
         fast=bf, slow=bs, window_fast=fast_lbl, window_slow=slow_lbl,
         target=target)

    # ---- latency bounds (p99 vs the declared SLO) -------------------------
    # role-aware (disaggregated fleets): a prefill replica's only latency
    # product is TTFT and a decode replica's is TPOT — holding a pool to the
    # OTHER pool's bound would shed on a signal it cannot influence
    role = getattr(engine, "role", None)
    lat_signals = (("ttft_p99", engine._h_ttft, "ttft_p99_ms"),
                   ("tpot_p99", engine._h_tpot, "tpot_p99_ms"))
    if role == "prefill":
        lat_signals = lat_signals[:1]
    elif role == "decode":
        lat_signals = lat_signals[1:]
    for name, hist, key in lat_signals:
        bound = float(cfg[key])
        p99_ms = hist.percentile(99.0) * 1e3 if hist.count else 0.0
        note(name, "degraded" if p99_ms > bound else "ok",
             f"{p99_ms:.1f} ms exceeds the {bound:.0f} ms SLO bound",
             value_ms=p99_ms, bound_ms=bound)

    # ---- pool pressure ----------------------------------------------------
    ceiling = float(cfg["pressure_ceiling"])
    pressure = engine.cache.pool_pressure()
    note("pool_pressure", "degraded" if pressure >= ceiling else "ok",
         f"{pressure:.3f} at or above the {ceiling} ceiling",
         value=pressure, ceiling=ceiling)

    # ---- preemption churn (fast ~10s window) ------------------------------
    fast_s = engine._rw_preemptions.windows[0][1]
    fast_name = engine._rw_preemptions.windows[0][0]
    preempt_rate = engine._rw_preemptions.rate(fast_s)
    p_deg = float(cfg["preempt_rate_degraded"])
    p_over = float(cfg["preempt_rate_overloaded"])
    if preempt_rate >= p_over:
        state = "overloaded"
    elif preempt_rate >= p_deg:
        state = "degraded"
    else:
        state = "ok"
    note("preemption", state,
         f"{preempt_rate:.3f} preemptions/s over {fast_name} "
         f"(degraded >= {p_deg}, overloaded >= {p_over})",
         rate=preempt_rate, window=fast_name)

    # ---- admission saturation (timeouts + intake rejects) -----------------
    timeout_rate = engine._rw_timeouts.rate(fast_s)
    reject_rate = engine._rw_rejects.rate(fast_s)
    t_over = float(cfg["timeout_rate_overloaded"])
    if timeout_rate >= t_over:
        state = "overloaded"
    elif timeout_rate > 0.0 or reject_rate > 0.0:
        state = "degraded"
    else:
        state = "ok"
    note("admission", state,
         f"{timeout_rate:.3f} timeouts/s + {reject_rate:.3f} rejects/s over "
         f"{fast_name} (overloaded >= {t_over} timeouts/s)",
         timeouts_per_sec=timeout_rate, rejects_per_sec=reject_rate,
         window=fast_name)

    # ---- steady-state recompile anomaly -----------------------------------
    recompiles = engine._ss_recompiles.value
    note("recompiles", "degraded" if recompiles else "ok",
         f"{recompiles} decode-side recompiles after warm (fixed-shape "
         f"engines must never recompile in steady state)",
         count=recompiles)

    worst = max(signals.values(), key=lambda s: HEALTH_CODES[s["state"]])
    state = worst["state"]
    return {"state": state, "code": HEALTH_CODES[state], "reasons": reasons,
            "role": role, "signals": signals, "burn_rates": burns,
            "t": engine._now()}
