"""Serving metrics: Counter / Gauge / Histogram + a registry with JSON and
Prometheus export.

Reference lineage: the reference repo's profiler subsystem
(`python/paddle/profiler` + `fluid/platform/profiler/`) covers *traces* —
span trees and chrome-tracing export — but serving fleets are scraped, not
traced: Orca (Yu et al., OSDI 2022) and vLLM (Kwon et al., SOSP 2023) treat
request-lifecycle latency distributions and engine counters as first-class
monitoring state.  This module is that layer for `inference.engine.LLMEngine`:

- **Counter** — monotonic event count (tokens emitted, verify dispatches,
  evictions).  `inc()` only; scrapers derive rates from successive scrapes.
- **Gauge** — an instantaneous level, either `set()` explicitly or backed by
  a zero-argument callback evaluated at snapshot time (pages in use, queue
  depth) so the hot path never pushes gauge updates.
- **Histogram** — fixed log-spaced buckets (latencies span decades: a queue
  wait is 10 us under no load and 10 s under overload; linear buckets waste
  resolution at one end).  The hot path is one `bisect` + three adds, pure
  Python, no numpy allocation.  Percentiles interpolate linearly inside the
  covering bucket (the Prometheus `histogram_quantile` convention); values
  past the last edge report the observed maximum instead of an edge clamp.

The registry owns the **clock** (`now()`), injectable so lifecycle tests can
drive deterministic timestamps through the engine; the default is
`time.perf_counter`, the same monotonic base the engine already stamps
`Request.t_enqueue` with.

Export surfaces:
- `snapshot()` — plain-JSON dict `{counters, gauges, histograms}` (histograms
  as `{count, sum, mean, min, max, p50, p90, p99}` summaries), embedded in
  bench JSON and `engine.trace()` dumps;
- `to_prometheus()` — text exposition format (`# HELP` / `# TYPE` + samples,
  cumulative `_bucket{le=...}` rows ending at `+Inf`, `_sum`/`_count`), ready
  for a scrape endpoint.  `tools/check_metrics.py` parses this output in CI.
"""
from __future__ import annotations

import math
import re
import time
from bisect import bisect_left
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence


def log_buckets(lo: float, hi: float, per_decade: int = 4) -> List[float]:
    """Geometric bucket edges covering [lo, hi]: `per_decade` edges per 10x,
    computed as lo * r**i (no compounding float drift), last edge >= hi."""
    if not (lo > 0.0 and hi > lo):
        raise ValueError(f"need 0 < lo < hi, got [{lo}, {hi}]")
    if per_decade < 1:
        raise ValueError(f"per_decade must be >= 1, got {per_decade}")
    ratio = 10.0 ** (1.0 / per_decade)
    n = math.ceil(per_decade * math.log10(hi / lo))
    edges = [lo * ratio ** i for i in range(n + 1)]
    if edges[-1] < hi:          # guard log10 rounding just under hi
        edges.append(edges[-1] * ratio)
    return edges


# 100 us .. 100 s, 4 edges per decade (25 buckets + overflow): spans a CPU
# smoke TTFT (~ms) and an overloaded queue wait (~10 s) at ~78% edge ratio
DEFAULT_LATENCY_BUCKETS = log_buckets(1e-4, 100.0, 4)


class Counter:
    """Monotonic counter.  `.value` for host reads; resets only via the
    registry (bench warmup exclusion), never decrements in between."""

    __slots__ = ("name", "help", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        self._value += n

    @property
    def value(self) -> int:
        return self._value

    def __bool__(self) -> bool:
        return self._value != 0

    def reset(self) -> None:
        self._value = 0


class Gauge:
    """Instantaneous level: `set()` pushed, or `fn` pulled at read time (the
    engine registers pull gauges over cache/queue state so the scheduler hot
    path never updates them)."""

    __slots__ = ("name", "help", "_value", "_fn")

    def __init__(self, name: str, fn: Optional[Callable[[], float]] = None,
                 help: str = ""):
        self.name = name
        self.help = help
        self._fn = fn
        self._value = 0.0

    def set(self, v: float) -> None:
        if self._fn is not None:
            raise ValueError(f"gauge {self.name} is callback-backed")
        self._value = v

    @property
    def value(self) -> float:
        return float(self._fn() if self._fn is not None else self._value)

    def reset(self) -> None:
        if self._fn is None:
            self._value = 0.0


class Histogram:
    """Fixed-bucket histogram with le-semantics edges (`counts[i]` holds
    observations in `(edges[i-1], edges[i]]`; larger values land in the
    overflow bucket).  Tracks count/sum/min/max exactly; percentiles are
    bucket-interpolated estimates."""

    __slots__ = ("name", "help", "edges", "counts", "overflow",
                 "count", "sum", "_min", "_max")

    def __init__(self, name: str, buckets: Optional[Sequence[float]] = None,
                 help: str = ""):
        self.name = name
        self.help = help
        edges = [float(e) for e in (buckets if buckets is not None
                                    else DEFAULT_LATENCY_BUCKETS)]
        if not edges or any(b <= a for a, b in zip(edges, edges[1:])):
            raise ValueError(f"bucket edges must be strictly increasing "
                             f"and non-empty, got {edges}")
        self.edges = edges
        self.reset()

    def reset(self) -> None:
        self.counts = [0] * len(self.edges)
        self.overflow = 0
        self.count = 0
        self.sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect_left(self.edges, v)      # first edge >= v: the le bucket
        if i < len(self.edges):
            self.counts[i] += 1
        else:
            self.overflow += 1
        self.count += 1
        self.sum += v
        if v < self._min:
            self._min = v
        if v > self._max:
            self._max = v

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    @property
    def min(self) -> float:
        return self._min if self.count else 0.0

    @property
    def max(self) -> float:
        return self._max if self.count else 0.0

    def percentile(self, p: float) -> float:
        """Estimate the p-th percentile (0..100): linear interpolation inside
        the bucket where the cumulative count crosses rank p/100 * count
        (lower edge of the first bucket taken as 0), clamped to the observed
        [min, max] envelope so a sparse bucket cannot report a quantile
        outside the data.  Ranks landing in the overflow bucket return the
        exact observed maximum."""
        if not 0.0 <= p <= 100.0:
            raise ValueError(f"percentile must be in [0, 100], got {p}")
        if self.count == 0:
            return 0.0
        rank = p / 100.0 * self.count
        if rank <= 0.0:
            return self.min
        cum = 0
        prev = 0.0
        for edge, c in zip(self.edges, self.counts):
            cum += c
            if c and cum >= rank:
                v = prev + (edge - prev) * (rank - (cum - c)) / c
                return min(max(v, self._min), self._max)
            prev = edge
        return self.max                     # rank falls in the overflow bucket

    def summary(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "sum": self.sum,
            "mean": self.mean,
            "min": self.min,
            "max": self.max,
            "p50": self.percentile(50.0),
            "p90": self.percentile(90.0),
            "p99": self.percentile(99.0),
        }


def _sanitize(name: str) -> str:
    """Prometheus metric-name charset: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return "_" + name if name and name[0].isdigit() else name


def _fmt(v: float) -> str:
    if isinstance(v, int):
        return str(v)
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    return f"{v:.10g}"


class MetricsRegistry:
    """Namespace of metrics sharing one injectable monotonic clock.

    Factory methods are idempotent per name (the same Counter comes back, so
    the engine and the cache manager can both ask for `prefix_evictions`);
    asking for an existing name as a different type raises."""

    def __init__(self, namespace: str = "",
                 clock: Callable[[], float] = time.perf_counter):
        self.namespace = namespace
        self._clock = clock
        self._metrics: "OrderedDict[str, object]" = OrderedDict()

    def now(self) -> float:
        """The registry clock — every lifecycle stamp the engine takes goes
        through here, so tests inject a fake and get exact latencies."""
        return self._clock()

    def _register(self, name: str, cls, factory):
        m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{type(m).__name__}, not {cls.__name__}")
            return m
        m = factory()
        self._metrics[name] = m
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._register(name, Counter, lambda: Counter(name, help))

    def gauge(self, name: str, fn: Optional[Callable[[], float]] = None,
              help: str = "") -> Gauge:
        return self._register(name, Gauge, lambda: Gauge(name, fn, help))

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None,
                  help: str = "") -> Histogram:
        return self._register(name, Histogram,
                              lambda: Histogram(name, buckets, help))

    def get(self, name: str):
        return self._metrics.get(name)

    def reset(self) -> None:
        """Zero counters and histograms (set-gauges too; callback gauges read
        live state and have nothing to reset) — the engine's
        `reset_counters()` warmup-exclusion hook."""
        for m in self._metrics.values():
            m.reset()

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Plain-JSON view: counters/gauges as scalars, histograms as
        summary dicts.  Callback gauges are evaluated here, once."""
        out: Dict[str, Dict[str, object]] = {"counters": {}, "gauges": {},
                                             "histograms": {}}
        for name, m in self._metrics.items():
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.value
            else:
                out["histograms"][name] = m.summary()
        return out

    def to_prometheus(self) -> str:
        """Text exposition format, one block per metric: HELP/TYPE comments,
        `_total` suffix on counters, cumulative `_bucket` rows ending at
        `+Inf` plus `_sum`/`_count` on histograms."""
        ns = _sanitize(self.namespace + "_") if self.namespace else ""
        lines: List[str] = []
        for name, m in self._metrics.items():
            full = ns + _sanitize(name)
            if isinstance(m, Counter):
                tname = full if full.endswith("_total") else full + "_total"
                if m.help:
                    lines.append(f"# HELP {tname} {m.help}")
                lines.append(f"# TYPE {tname} counter")
                lines.append(f"{tname} {m.value}")
            elif isinstance(m, Gauge):
                if m.help:
                    lines.append(f"# HELP {full} {m.help}")
                lines.append(f"# TYPE {full} gauge")
                lines.append(f"{full} {_fmt(m.value)}")
            else:
                if m.help:
                    lines.append(f"# HELP {full} {m.help}")
                lines.append(f"# TYPE {full} histogram")
                cum = 0
                for edge, c in zip(m.edges, m.counts):
                    cum += c
                    lines.append(f'{full}_bucket{{le="{_fmt(edge)}"}} {cum}')
                lines.append(f'{full}_bucket{{le="+Inf"}} {m.count}')
                lines.append(f"{full}_sum {_fmt(m.sum)}")
                lines.append(f"{full}_count {m.count}")
        return "\n".join(lines) + "\n"
