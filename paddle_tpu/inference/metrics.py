"""Serving metrics: Counter / Gauge / Histogram + a registry with JSON and
Prometheus export.

Reference lineage: the reference repo's profiler subsystem
(`python/paddle/profiler` + `fluid/platform/profiler/`) covers *traces* —
span trees and chrome-tracing export — but serving fleets are scraped, not
traced: Orca (Yu et al., OSDI 2022) and vLLM (Kwon et al., SOSP 2023) treat
request-lifecycle latency distributions and engine counters as first-class
monitoring state.  This module is that layer for `inference.engine.LLMEngine`:

- **Counter** — monotonic event count (tokens emitted, verify dispatches,
  evictions).  `inc()` only; scrapers derive rates from successive scrapes.
- **Gauge** — an instantaneous level, either `set()` explicitly or backed by
  a zero-argument callback evaluated at snapshot time (pages in use, queue
  depth) so the hot path never pushes gauge updates.
- **Histogram** — fixed log-spaced buckets (latencies span decades: a queue
  wait is 10 us under no load and 10 s under overload; linear buckets waste
  resolution at one end).  The hot path is one `bisect` + three adds, pure
  Python, no numpy allocation.  Percentiles interpolate linearly inside the
  covering bucket (the Prometheus `histogram_quantile` convention); values
  past the last edge report the observed maximum instead of an edge clamp.

The registry owns the **clock** (`now()`), injectable so lifecycle tests can
drive deterministic timestamps through the engine; the default is
`time.perf_counter`, the same monotonic base the engine already stamps
`Request.t_enqueue` with.

Export surfaces:
- `snapshot()` — plain-JSON dict `{counters, gauges, histograms}` (histograms
  as `{count, sum, mean, min, max, p50, p90, p99}` summaries), embedded in
  bench JSON and `engine.trace()` dumps;
- `to_prometheus()` — text exposition format (`# HELP` / `# TYPE` + samples,
  cumulative `_bucket{le=...}` rows ending at `+Inf`, `_sum`/`_count`), ready
  for a scrape endpoint (`inference.obs_server` serves it on ``GET
  /metrics``).  `tools/check_metrics.py` parses this output in CI.

One signal-plane extension (the health plane's freshness-weighted input):
- **`RateWindow`** — a ring of ``(t, counter_value)`` samples on the
  registry clock that derives *sliding-window rates* from the monotonic
  counters above (tokens/s, admits/s, preemptions/s over ~10s/1m/5m).
  Counters alone answer "how much since reset"; a router or health probe
  needs "how much *lately*" — `registry.rate_window()` registers one and
  exposes each window as a pull gauge, `sample_rates()` is the engine's
  once-per-step recording hook, and the math is exact under the injectable
  clock (golden-value testable): the live counter value is the window's
  right edge, the newest ring sample at or before ``now - window`` its
  left.  `reset()` clears the rings with the counters (the warmup-exclusion
  contract), and a counter observed DECREASING (reset underneath the ring)
  restarts the window instead of reporting a negative rate.

Two fleet-facing extensions (the dp-group router's input):
- **Exemplars** — `Histogram.observe(v, exemplar={...labels...})` remembers,
  per bucket, the labels of the latest observation that landed there
  (the engine attaches ``{request_id, trace}``), and `to_prometheus()` emits
  them in OpenMetrics ``# {label="v"} value`` exemplar syntax on the
  ``_bucket`` line — so the request behind a p99 latency bucket is one
  ``GET /requests/<rid>`` away from the scrape text itself.
- **`merge()` / `FleetMetrics`** — fold N engines' registries into one
  aggregate with per-type semantics (counters SUM; gauges fold by their
  declared `agg` — sum for levels, max for ratio gauges — queue
  depths and page levels add across replicas; histograms add bucket-wise
  with min/max/count/sum folded and the last-merged exemplar kept per
  bucket), while `FleetMetrics.to_prometheus()` re-exposes every member's
  samples under an ``{engine="<label>"}`` label, grouped per metric family
  so the exposition stays well-formed.
"""
from __future__ import annotations

import math
import re
import time
from bisect import bisect_left
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple


def log_buckets(lo: float, hi: float, per_decade: int = 4) -> List[float]:
    """Geometric bucket edges covering [lo, hi]: `per_decade` edges per 10x,
    computed as lo * r**i (no compounding float drift), last edge >= hi."""
    if not (lo > 0.0 and hi > lo):
        raise ValueError(f"need 0 < lo < hi, got [{lo}, {hi}]")
    if per_decade < 1:
        raise ValueError(f"per_decade must be >= 1, got {per_decade}")
    ratio = 10.0 ** (1.0 / per_decade)
    n = math.ceil(per_decade * math.log10(hi / lo))
    edges = [lo * ratio ** i for i in range(n + 1)]
    if edges[-1] < hi:          # guard log10 rounding just under hi
        edges.append(edges[-1] * ratio)
    return edges


# 100 us .. 100 s, 4 edges per decade (25 buckets + overflow): spans a CPU
# smoke TTFT (~ms) and an overloaded queue wait (~10 s) at ~78% edge ratio
DEFAULT_LATENCY_BUCKETS = log_buckets(1e-4, 100.0, 4)


class Counter:
    """Monotonic counter.  `.value` for host reads; resets only via the
    registry (bench warmup exclusion), never decrements in between."""

    __slots__ = ("name", "help", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        self._value += n

    @property
    def value(self) -> int:
        return self._value

    def __bool__(self) -> bool:
        return self._value != 0

    def reset(self) -> None:
        self._value = 0


class Gauge:
    """Instantaneous level: `set()` pushed, or `fn` pulled at read time (the
    engine registers pull gauges over cache/queue state so the scheduler hot
    path never updates them).

    `agg` declares how the gauge folds across a fleet merge: ``"sum"``
    (default — queue depths and page levels add across replicas) or
    ``"max"`` for ratio/fraction gauges like pool pressure, where a sum of
    per-replica fractions is meaningless and the fleet-wide signal is the
    worst member."""

    __slots__ = ("name", "help", "_value", "_fn", "agg")

    def __init__(self, name: str, fn: Optional[Callable[[], float]] = None,
                 help: str = "", agg: str = "sum"):
        if agg not in ("sum", "max"):
            raise ValueError(f"gauge {name} agg must be 'sum' or 'max', "
                             f"got {agg!r}")
        self.name = name
        self.help = help
        self._fn = fn
        self._value = 0.0
        self.agg = agg

    def set(self, v: float) -> None:
        if self._fn is not None:
            raise ValueError(f"gauge {self.name} is callback-backed")
        self._value = v

    @property
    def value(self) -> float:
        return float(self._fn() if self._fn is not None else self._value)

    def reset(self) -> None:
        if self._fn is None:
            self._value = 0.0


class Histogram:
    """Fixed-bucket histogram with le-semantics edges (`counts[i]` holds
    observations in `(edges[i-1], edges[i]]`; larger values land in the
    overflow bucket).  Tracks count/sum/min/max exactly; percentiles are
    bucket-interpolated estimates.

    `observe(v, exemplar={...})` additionally remembers `(labels, v)` for the
    bucket v landed in — the LATEST observation per bucket wins (OpenMetrics
    keeps one exemplar per bucket; the freshest is the debuggable one).
    `reset()` clears exemplars with the counts: a handle pointing at a
    request observed before the reset must not survive into an exposition
    whose bucket counts say nothing was observed."""

    __slots__ = ("name", "help", "edges", "counts", "overflow",
                 "count", "sum", "_min", "_max", "exemplars")

    def __init__(self, name: str, buckets: Optional[Sequence[float]] = None,
                 help: str = ""):
        self.name = name
        self.help = help
        edges = [float(e) for e in (buckets if buckets is not None
                                    else DEFAULT_LATENCY_BUCKETS)]
        if not edges or any(b <= a for a, b in zip(edges, edges[1:])):
            raise ValueError(f"bucket edges must be strictly increasing "
                             f"and non-empty, got {edges}")
        self.edges = edges
        self.reset()

    def reset(self) -> None:
        self.counts = [0] * len(self.edges)
        self.overflow = 0
        self.count = 0
        self.sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        # one slot per bucket + the overflow bucket: (labels dict, value)
        self.exemplars: List[Optional[tuple]] = [None] * (len(self.edges) + 1)

    def observe(self, v: float,
                exemplar: Optional[Dict[str, str]] = None) -> None:
        v = float(v)
        i = bisect_left(self.edges, v)      # first edge >= v: the le bucket
        if i < len(self.edges):
            self.counts[i] += 1
        else:
            self.overflow += 1
        if exemplar is not None:
            self.exemplars[min(i, len(self.edges))] = (exemplar, v)
        self.count += 1
        self.sum += v
        if v < self._min:
            self._min = v
        if v > self._max:
            self._max = v

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    @property
    def min(self) -> float:
        return self._min if self.count else 0.0

    @property
    def max(self) -> float:
        return self._max if self.count else 0.0

    def percentile(self, p: float) -> float:
        """Estimate the p-th percentile (0..100): linear interpolation inside
        the bucket where the cumulative count crosses rank p/100 * count
        (lower edge of the first bucket taken as 0), clamped to the observed
        [min, max] envelope so a sparse bucket cannot report a quantile
        outside the data.  Ranks landing in the overflow bucket return the
        exact observed maximum."""
        if not 0.0 <= p <= 100.0:
            raise ValueError(f"percentile must be in [0, 100], got {p}")
        if self.count == 0:
            return 0.0
        rank = p / 100.0 * self.count
        if rank <= 0.0:
            return self.min
        cum = 0
        prev = 0.0
        for edge, c in zip(self.edges, self.counts):
            cum += c
            if c and cum >= rank:
                v = prev + (edge - prev) * (rank - (cum - c)) / c
                return min(max(v, self._min), self._max)
            prev = edge
        return self.max                     # rank falls in the overflow bucket

    def summary(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "sum": self.sum,
            "mean": self.mean,
            "min": self.min,
            "max": self.max,
            "p50": self.percentile(50.0),
            "p90": self.percentile(90.0),
            "p99": self.percentile(99.0),
        }


# the serving signal plane's standard windows: fast enough for a health
# probe (~10s), the multi-window burn-rate pair (1m/5m) for SLO alerting
RATE_WINDOWS: Tuple[Tuple[str, float], ...] = \
    (("10s", 10.0), ("1m", 60.0), ("5m", 300.0))


class RateWindow:
    """Sliding-window rates over a monotonic counter: a ring of
    ``(t, value)`` samples on the shared registry clock.

    `sample()` records the counter's current value (throttled to
    `min_interval_s` so a kHz step loop cannot grow the ring past
    ``max_window / min_interval`` entries; samples older than the largest
    window are pruned, always keeping the newest one at or beyond the
    horizon as the reference).  `rate(window_s)` reads LIVE state — the
    counter's value now against the newest sample at or before
    ``now - window_s`` (or the oldest sample while the ring is younger than
    the window) — so an idle engine's rates decay to exactly 0.0 without
    further sampling, and the math is deterministic under a fake clock:

    - empty ring -> 0.0 (no reference, no rate);
    - single sample at ``now`` -> 0.0 (zero elapsed);
    - counter DECREASED vs the reference (reset underneath the ring) ->
      ring restarts, 0.0 — never a negative rate.

    `delta(window_s)` is the raw in-window count increment — what burn-rate
    ratios divide (two windows sampled at the same instants share reference
    timestamps, so the elapsed time cancels exactly)."""

    __slots__ = ("name", "fn", "windows", "min_interval_s", "_clock",
                 "_samples", "_max_window")

    def __init__(self, name: str, fn: Callable[[], float],
                 clock: Callable[[], float],
                 windows: Sequence[Tuple[str, float]] = RATE_WINDOWS,
                 min_interval_s: float = 0.25):
        self.name = name
        self.fn = fn
        self._clock = clock
        self.windows: Tuple[Tuple[str, float], ...] = \
            tuple((str(lbl), float(w)) for lbl, w in windows)
        if not self.windows or any(w <= 0.0 for _, w in self.windows):
            raise ValueError(f"rate window {name!r} needs positive window "
                             f"lengths, got {windows}")
        self.min_interval_s = float(min_interval_s)
        self._max_window = max(w for _, w in self.windows)
        self._samples: deque = deque()      # (t, value), time-ordered

    def sample(self, force: bool = False) -> None:
        """Record ``(now, fn())`` — the engine calls this once per step.
        `force=True` overrides the interval throttle: the engine forces a
        sample on EVENTFUL steps (finishes, preemptions, intake rejects) so
        a burst right before the engine goes idle is anchored at its true
        time — otherwise those unanchored events would decay hyperbolically
        against an old reference instead of dropping to exactly 0.0 once
        the window passes them.  A forced sample inside the throttle
        interval SLIDES the newest ring entry forward instead of appending
        (when that entry is itself within the interval of its predecessor),
        so sustained eventful load keeps the latest anchor exact while the
        ring stays bounded at ~max_window/min_interval entries."""
        now = self._clock()
        v = float(self.fn())
        if self._samples:
            t_last, v_last = self._samples[-1]
            if v < v_last:          # counter reset underneath the ring
                self._samples.clear()
            elif now - t_last < self.min_interval_s:
                if not force:
                    return
                if len(self._samples) >= 2 and \
                        t_last - self._samples[-2][0] < self.min_interval_s:
                    self._samples[-1] = (now, v)    # slide the anchor
                    return
        self._samples.append((now, v))
        horizon = now - self._max_window
        # keep the NEWEST sample at or beyond the horizon: it is the exact
        # reference for the largest window until a closer one ages past
        while len(self._samples) >= 2 and self._samples[1][0] <= horizon:
            self._samples.popleft()

    def _reference(self, now: float, window_s: float) -> Optional[tuple]:
        cut = now - window_s
        for t, v in reversed(self._samples):
            if t <= cut:
                return (t, v)
        return self._samples[0] if self._samples else None

    def _live(self) -> Optional[float]:
        """The counter's current value, with reset detection against the
        NEWEST ring sample (the ring maximum — the source is monotonic):
        a value below it means the counter was reset underneath the ring,
        so the window restarts instead of reporting a phantom rate."""
        v_now = float(self.fn())
        if self._samples and v_now < self._samples[-1][1]:
            self._samples.clear()
            return None
        return v_now

    def delta(self, window_s: float) -> float:
        """Counter increment inside the window (>= 0.0; 0.0 on an empty
        ring or across a counter reset)."""
        v_now = self._live()
        ref = self._reference(self._clock(), window_s)
        if v_now is None or ref is None:
            return 0.0
        return max(0.0, v_now - ref[1])

    def rate(self, window_s: float) -> float:
        """Events/second over the window — see the class docstring for the
        exact reference-sample semantics."""
        now = self._clock()
        v_now = self._live()
        ref = self._reference(now, window_s)
        if v_now is None or ref is None:
            return 0.0
        t_ref, v_ref = ref
        dt = now - t_ref
        return (v_now - v_ref) / dt if dt > 0.0 else 0.0

    def rates(self) -> Dict[str, float]:
        """{window label: rate} over every configured window."""
        return {lbl: self.rate(w) for lbl, w in self.windows}

    def reset(self) -> None:
        self._samples.clear()


def _sanitize(name: str) -> str:
    """Prometheus metric-name charset: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return "_" + name if name and name[0].isdigit() else name


def _fmt(v: float) -> str:
    if isinstance(v, int):
        return str(v)
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    return f"{v:.10g}"


def _escape(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _render_labels(labels: Optional[Dict[str, str]],
                   le: Optional[str] = None) -> str:
    """`{k="v",...}` label block (extra labels first, `le` last), or ""."""
    parts = [f'{_sanitize(k)}="{_escape(v)}"'
             for k, v in (labels or {}).items()]
    if le is not None:
        parts.append(f'le="{le}"')
    return "{" + ",".join(parts) + "}" if parts else ""


def _render_exemplar(ex: Optional[tuple], engine: Optional[str] = None) -> str:
    """OpenMetrics exemplar suffix ``# {labels} value`` (empty when None).

    `engine` is the fleet member label the sample is being re-exposed under:
    request ids are per-engine (every member has a request 0), so a bare
    ``/requests/<rid>`` trace handle is ambiguous fleet-wide — the handle
    gets the member scoped on as ``?engine=<label>``, which the obs server's
    fleet mode resolves to exactly that member's timeline."""
    if ex is None:
        return ""
    labels, value = ex
    if engine is not None and "trace" in labels:
        labels = {**labels, "trace": f'{labels["trace"]}?engine={engine}'}
    return f" # {_render_labels(labels) or '{}'} {_fmt(float(value))}"


class MetricsRegistry:
    """Namespace of metrics sharing one injectable monotonic clock.

    Factory methods are idempotent per name (the same Counter comes back, so
    the engine and the cache manager can both ask for `prefix_evictions`);
    asking for an existing name as a different type raises.

    Readers (snapshot/exposition/merge) copy the metric map before iterating:
    an obs-server thread scrapes concurrently with the engine thread lazily
    registering counters (per-priority goodput), and iterating the live dict
    would raise mid-scrape."""

    def __init__(self, namespace: str = "",
                 clock: Callable[[], float] = time.perf_counter):
        self.namespace = namespace
        self._clock = clock
        self._metrics: "OrderedDict[str, object]" = OrderedDict()
        self._rate_windows: "OrderedDict[str, RateWindow]" = OrderedDict()

    def now(self) -> float:
        """The registry clock — every lifecycle stamp the engine takes goes
        through here, so tests inject a fake and get exact latencies."""
        return self._clock()

    def _register(self, name: str, cls, factory):
        m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{type(m).__name__}, not {cls.__name__}")
            return m
        m = factory()
        self._metrics[name] = m
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._register(name, Counter, lambda: Counter(name, help))

    def gauge(self, name: str, fn: Optional[Callable[[], float]] = None,
              help: str = "", agg: str = "sum") -> Gauge:
        return self._register(name, Gauge,
                              lambda: Gauge(name, fn, help, agg))

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None,
                  help: str = "") -> Histogram:
        return self._register(name, Histogram,
                              lambda: Histogram(name, buckets, help))

    def rate_window(self, name: str, fn: Callable[[], float],
                    windows: Sequence[Tuple[str, float]] = RATE_WINDOWS,
                    help: str = "", min_interval_s: float = 0.25,
                    agg: str = "sum", expose: bool = True) -> RateWindow:
        """A `RateWindow` over `fn` (a live counter read) on the registry
        clock, idempotent per name.  With `expose=True` each window also
        registers a pull gauge ``<name>_<label>`` (e.g. ``tokens_per_sec_10s``)
        so the rates ride every existing surface — snapshot, exposition,
        fleet merge — for free; `agg` is those gauges' fleet fold (rates are
        levels: fleet tokens/s SUM across replicas).  `sample_rates()`
        records one sample on every window; `reset()` clears the rings."""
        rw = self._rate_windows.get(name)
        if rw is not None:
            return rw
        rw = RateWindow(name, fn, self.now, windows, min_interval_s)
        self._rate_windows[name] = rw
        if expose:
            for lbl, w in rw.windows:
                self.gauge(f"{name}_{lbl}", (lambda w=w: rw.rate(w)),
                           help=f"{help or name} over the trailing {lbl}",
                           agg=agg)
        return rw

    def sample_rates(self, force: bool = False) -> None:
        """Record one ``(now, value)`` sample on every rate window — the
        engine's once-per-step hook (each window throttles itself unless
        `force`, which eventful steps use to anchor their events exactly)."""
        for rw in self._rate_windows.values():
            rw.sample(force)

    def get(self, name: str):
        return self._metrics.get(name)

    def reset(self) -> None:
        """Zero counters and histograms (set-gauges too; callback gauges read
        live state and have nothing to reset) and clear every rate window's
        sample ring (the counters underneath restart at zero, so a surviving
        ring would read negative deltas) — the engine's `reset_counters()`
        warmup-exclusion hook."""
        for m in list(self._metrics.values()):
            m.reset()
        for rw in self._rate_windows.values():
            rw.reset()

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Plain-JSON view: counters/gauges as scalars, histograms as
        summary dicts.  Callback gauges are evaluated here, once."""
        out: Dict[str, Dict[str, object]] = {"counters": {}, "gauges": {},
                                             "histograms": {}}
        for name, m in list(self._metrics.items()):
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.value
            else:
                out["histograms"][name] = m.summary()
        return out

    def _families(self, labels: Optional[Dict[str, str]] = None,
                  exemplars: bool = True, openmetrics: bool = False):
        """Yield one exposition family per metric: `(family_name, type,
        help, [sample lines])`, with `labels` attached to every sample —
        the shared core of `to_prometheus()` and `FleetMetrics`, which must
        interleave several registries' samples per family to keep the
        exposition grouped.  Counter samples always carry the `_total`
        suffix; the FAMILY name (what HELP/TYPE lines cite) depends on the
        dialect — OpenMetrics reserves the suffix for the sample and
        forbids it on the MetricFamily (`# TYPE foo counter` + sample
        `foo_total`; a strict parser rejects a `_total` family outright),
        while legacy 0.0.4 text names the family as exposed."""
        ns = _sanitize(self.namespace + "_") if self.namespace else ""
        lbl = _render_labels(labels)
        eng = (labels or {}).get("engine")
        for name, m in list(self._metrics.items()):
            full = ns + _sanitize(name)
            if isinstance(m, Counter):
                tname = full if full.endswith("_total") else full + "_total"
                fam = tname[:-len("_total")] if openmetrics else tname
                yield fam, "counter", m.help, [f"{tname}{lbl} {m.value}"]
            elif isinstance(m, Gauge):
                yield full, "gauge", m.help, [f"{full}{lbl} {_fmt(m.value)}"]
            else:
                lines: List[str] = []
                cum = 0
                for i, (edge, c) in enumerate(zip(m.edges, m.counts)):
                    cum += c
                    ex = (_render_exemplar(m.exemplars[i], eng)
                          if exemplars else "")
                    lines.append(
                        f'{full}_bucket'
                        f'{_render_labels(labels, le=_fmt(edge))} {cum}{ex}')
                ex = (_render_exemplar(m.exemplars[-1], eng)
                      if exemplars else "")
                lines.append(f'{full}_bucket'
                             f'{_render_labels(labels, le="+Inf")} '
                             f'{m.count}{ex}')
                lines.append(f"{full}_sum{lbl} {_fmt(m.sum)}")
                lines.append(f"{full}_count{lbl} {m.count}")
                yield full, "histogram", m.help, lines

    def to_prometheus(self, labels: Optional[Dict[str, str]] = None,
                      exemplars: Optional[bool] = None,
                      openmetrics: bool = False) -> str:
        """Text exposition format, one block per metric: HELP/TYPE comments,
        `_total` suffix on counters, cumulative `_bucket` rows ending at
        `+Inf` plus `_sum`/`_count` on histograms.  Histogram buckets carry
        their latest exemplar in OpenMetrics ``# {labels} value`` syntax;
        `labels` attaches a constant label set to every sample (how
        `FleetMetrics` scopes a member engine).  `openmetrics=True` names
        counter FAMILIES without the reserved `_total` suffix (samples keep
        it) as the OpenMetrics spec requires — a strict parser rejects a
        `_total` MetricFamily outright.

        `exemplars` defaults to FOLLOW the dialect: the ``# {...}`` suffix is
        OpenMetrics-only syntax that a stock 0.0.4 text parser rejects, so a
        bare `to_prometheus()` stays pure legacy text a naive scraper can
        consume, and `openmetrics=True` carries the exemplars.  Pass it
        explicitly to override either way (the tests round-trip exemplars
        through the legacy-named dialect that way)."""
        if exemplars is None:
            exemplars = openmetrics
        lines: List[str] = []
        for full, mtype, help_, samples in self._families(labels, exemplars,
                                                          openmetrics):
            if help_:
                lines.append(f"# HELP {full} {help_}")
            lines.append(f"# TYPE {full} {mtype}")
            lines.extend(samples)
        return "\n".join(lines) + "\n"

    def merge(self, other: "MetricsRegistry") -> "MetricsRegistry":
        """Fold `other`'s CURRENT values into this registry, in place, with
        per-type semantics (the fleet-aggregation primitive — build a fresh
        aggregate registry and merge each member into it):

        - **counter**: sum;
        - **gauge**: folded by the gauge's declared `agg` over the values
          read NOW — ``"sum"`` for fleet queue depths and page levels,
          ``"max"`` for ratio gauges like pool pressure, where a sum of
          per-replica fractions reads >100% on a healthy fleet and the
          meaningful aggregate is the worst member (`other`'s callback
          gauges are evaluated here and land as plain set-gauges in the
          aggregate; a callback gauge on the AGGREGATE side cannot absorb
          a merge and raises);
        - **histogram**: bucket-wise count add (edges must match exactly),
          overflow/count/sum added, min/max folded, and per bucket the
          last-merged exemplar wins (matching `observe`'s latest-wins rule).

        Metrics absent on one side pass through (a disjoint merge is a
        union); a name registered as different types on the two sides
        raises TypeError.  Returns self so merges chain."""
        for name, m in list(other._metrics.items()):
            if isinstance(m, Counter):
                self.counter(name, m.help).inc(m.value)
            elif isinstance(m, Gauge):
                g = self.gauge(name, help=m.help, agg=m.agg)
                if g.agg != m.agg:      # like mismatched histogram edges:
                    raise ValueError(   # refuse loudly, don't fold garbage
                        f"gauge {name!r} agg differs: aggregate folds by "
                        f"{g.agg!r}, member declares {m.agg!r}")
                g.set(max(g.value, m.value) if g.agg == "max"
                      else g.value + m.value)
            else:
                h = self.histogram(name, m.edges, m.help)
                if h.edges != m.edges:
                    raise ValueError(
                        f"histogram {name!r} bucket edges differ: "
                        f"{h.edges} vs {m.edges}")
                for i, c in enumerate(m.counts):
                    h.counts[i] += c
                h.overflow += m.overflow
                h.count += m.count
                h.sum += m.sum
                h._min = min(h._min, m._min)
                h._max = max(h._max, m._max)
                for i, ex in enumerate(m.exemplars):
                    if ex is not None:
                        h.exemplars[i] = ex
        return self


class FleetMetrics:
    """Aggregates N engines' registries — the dp-group router's input.

    Members register under a label (`add("e0", engine_or_registry)`); the two
    views are:

    - `merged()` — a fresh `MetricsRegistry` (namespace ``llm_fleet``) built
      by `MetricsRegistry.merge()` over every member: counters summed,
      gauges folded by their declared `agg` (sum / max),
      histograms bucket-wise added.  `snapshot()` returns
      ``{"fleet": <merged snapshot>, "engines": {label: snapshot}}``.
    - `to_prometheus()` — every member's samples re-exposed under an
      ``{engine="<label>"}`` label, interleaved per metric family (all
      samples of one name stay grouped under one TYPE comment, as the
      exposition format requires), exemplars intact.  The merged totals ride
      along as ``llm_fleet_*`` families — a different namespace, so the
      per-engine series are never double-counted by an aggregating scraper.

    Registration accepts an engine (anything with a `.metrics` registry —
    `stats()`/`debug_bundle()` owners are kept for the obs server's fleet
    endpoints) or a bare `MetricsRegistry`."""

    def __init__(self):
        self.registries: "OrderedDict[str, MetricsRegistry]" = OrderedDict()
        self.engines: "OrderedDict[str, object]" = OrderedDict()

    def add(self, label: str, member) -> "FleetMetrics":
        reg = getattr(member, "metrics", member)
        if not isinstance(reg, MetricsRegistry):
            raise TypeError(f"member {label!r} is neither a MetricsRegistry "
                            f"nor an engine exposing one, got {type(member)}")
        self.registries[str(label)] = reg
        self.engines[str(label)] = member if reg is not member else None
        return self

    def merged(self) -> MetricsRegistry:
        agg = MetricsRegistry(namespace="llm_fleet")
        for reg in self.registries.values():
            agg.merge(reg)
        return agg

    def snapshot(self) -> Dict[str, object]:
        return {
            "fleet": self.merged().snapshot(),
            "engines": {label: reg.snapshot()
                        for label, reg in self.registries.items()},
        }

    def to_prometheus(self, exemplars: Optional[bool] = None,
                      openmetrics: bool = False) -> str:
        if exemplars is None:       # follow the dialect, as the registry does
            exemplars = openmetrics
        lines: List[str] = []
        # per-engine series, grouped per metric family across members
        families: "OrderedDict[str, tuple]" = OrderedDict()
        samples: Dict[str, List[str]] = {}
        for label, reg in self.registries.items():
            for full, mtype, help_, fam_lines in reg._families(
                    {"engine": label}, exemplars, openmetrics):
                if full not in families:
                    families[full] = (mtype, help_)
                    samples[full] = []
                elif families[full][0] != mtype:
                    raise TypeError(
                        f"metric {full!r} exposed as {families[full][0]} by "
                        f"one engine and {mtype} by another")
                samples[full].extend(fam_lines)
        for full, (mtype, help_) in families.items():
            if help_:
                lines.append(f"# HELP {full} {help_}")
            lines.append(f"# TYPE {full} {mtype}")
            lines.extend(samples[full])
        # fleet totals under their own namespace (no double counting)
        merged = self.to_prometheus_merged(exemplars, openmetrics)
        return "\n".join(lines) + ("\n" + merged if merged else "\n")

    def to_prometheus_merged(self, exemplars: Optional[bool] = None,
                             openmetrics: bool = False) -> str:
        return self.merged().to_prometheus(exemplars=exemplars,
                                           openmetrics=openmetrics)
