"""Draft proposers for speculative decoding (Leviathan et al. 2023).

The serving engine's verify step (`models.gpt.verify_step_paged`) scores
`spec_len + 1` candidate tokens per slot in one fixed-shape pass; anything
that can guess the next few tokens cheaply is a valid draft source.  This
module holds the host-side proposers:

- `DraftProposer` — the pluggable interface: per-slot, history in, up to
  `max_tokens` proposed continuation tokens out.  A small draft *model* slots
  in here later (ROADMAP follow-on) without touching the scheduler.
- `NgramProposer` — n-gram / prompt-lookup self-drafting (the vLLM
  "prompt lookup" / ANPD family): match the sequence's trailing n-gram
  against its own earlier prompt+generated history and propose the tokens
  that followed the most recent previous occurrence.  Zero model cost, pure
  numpy, and strong exactly where decode is most wasteful — repetitive
  continuations (code, structured text, self-looping generations).

Proposals are *guesses*: the engine's greedy longest-prefix acceptance only
ever emits tokens the verify logits argmax to, so a bad proposer can only
cost speed, never correctness — output is token-identical to vanilla decode
as long as the verify and decode executables agree at argmax (exact at
matching kernel numerics; see the engine docstring for the TPU bf16 caveat).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class DraftProposer:
    """Interface: propose up to `max_tokens` continuation tokens for one
    slot given its token history (prompt + generated so far)."""

    # History window consulted, in tokens from the END of the context.
    # Part of the interface contract: the engine materializes only this tail
    # of prompt+generated before calling propose() (proposing runs on the
    # host inside every decode iteration, so per-slot work must not grow
    # with sequence length).  0 = unbounded: the full history is built and
    # passed each iteration — O(context) per slot per step.
    max_lookback: int = 0

    def propose(self, context: np.ndarray,
                max_tokens: int) -> Optional[np.ndarray]:
        """context: 1-D int array, the last `max_lookback` tokens of
        prompt + generated (generated last; everything when max_lookback=0).
        Returns int32 [n] with 1 <= n <= max_tokens, or None for no draft
        (the slot falls back to vanilla decode this iteration)."""
        raise NotImplementedError

    # Observability: drafting runs on the host inside every decode iteration,
    # so the engine's step trace wants the proposer's own view of its traffic
    # (how often the scan even finds a match is a victim-selection signal the
    # slot-level acceptance counters cannot recover).  Both hooks are
    # optional — the engine probes with getattr and tolerates proposers that
    # track nothing.
    def stats(self) -> Dict[str, object]:
        """Host-side drafting telemetry; default: nothing tracked."""
        return {}

    def reset_stats(self) -> None:
        """Zero the telemetry (the engine's `reset_counters()` warmup hook);
        default: nothing to zero."""


class _NgramStats:
    """Plain-int telemetry for NgramProposer — kept off the DraftProposer
    hot-path contract so a stats-less custom proposer costs nothing."""

    __slots__ = ("calls", "hits", "tokens_proposed")

    def __init__(self):
        self.reset()

    def reset(self):
        self.calls = 0
        self.hits = 0
        self.tokens_proposed = 0


class NgramProposer(DraftProposer):
    """Prompt-lookup / n-gram self-drafting.

    Tries the trailing n-gram for n = max_ngram down to min_ngram; the first n
    with an earlier occurrence in the history wins (longer matches are more
    specific, so their continuations accept more often).  Among the hits, the
    MOST RECENT one with a full max_tokens continuation is proposed (recency
    tracks local structure); when every recent hit is truncated by the end of
    the history — the tight-loop case, where the latest occurrence sits right
    next to the tail — the EARLIEST hit wins instead, maximizing the drafted
    run length.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 max_lookback: int = 512):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(f"need 1 <= min_ngram <= max_ngram, got "
                             f"[{min_ngram}, {max_ngram}]")
        if max_lookback < min_ngram + 1:
            raise ValueError(f"max_lookback {max_lookback} too small for "
                             f"min_ngram {min_ngram}")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        # bounded scan (see DraftProposer.max_lookback): recent history is
        # also where loop/structure matches live
        self.max_lookback = max_lookback
        self._stats = _NgramStats()

    def stats(self) -> Dict[str, object]:
        s = self._stats
        return {
            "propose_calls": s.calls,
            "propose_hits": s.hits,
            "tokens_proposed": s.tokens_proposed,
            "hit_rate": s.hits / s.calls if s.calls else 0.0,
        }

    def reset_stats(self) -> None:
        self._stats.reset()

    def propose(self, context: np.ndarray,
                max_tokens: int) -> Optional[np.ndarray]:
        # the engine already hands over only the window; re-slice so direct
        # callers (tests, other schedulers) get the same bounded contract
        self._stats.calls += 1
        ctx = np.asarray(context).reshape(-1)[-self.max_lookback:]
        L = ctx.size
        if max_tokens < 1 or L < self.min_ngram + 1:
            return None
        # n capped at L-1: the pattern must leave room for an earlier
        # occurrence with at least one continuation token
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            pat = ctx[L - n:]
            # candidate starts 0..L-1-n: window ends before the final token,
            # so a hit always has a continuation inside the history
            win = np.lib.stride_tricks.sliding_window_view(ctx[:L - 1], n)
            hits = np.nonzero((win == pat).all(axis=1))[0]
            if hits.size:
                full = hits[hits + n + max_tokens <= L]
                # most recent full-length continuation, else the earliest hit
                # (its continuation is the longest available)
                j = int(full[-1]) if full.size else int(hits[0])
                prop = ctx[j + n:j + n + max_tokens]
                if prop.size:
                    self._stats.hits += 1
                    self._stats.tokens_proposed += prop.size
                    return prop.astype(np.int32, copy=True)
        return None
