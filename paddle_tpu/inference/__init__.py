"""paddle.inference — deployment predictor API.

Reference parity: `paddle/fluid/inference/api/analysis_predictor.cc` +
`python/paddle/inference/__init__.py` (Config, create_predictor, named
input/output handles).

TPU-native design: the "analysis + IR pass pipeline + engine subgraphs" of the
reference collapses into XLA — a saved model is a serialized StableHLO program
(`jit.save` / `static.save_inference_model` artifact), and the Predictor is a
thin handle layer over the deserialized executable.  TensorRT/ONNXRuntime/
mkldnn toggles are accepted for API compatibility and are inert: XLA:TPU is the
one engine.
"""
from __future__ import annotations

import os
import pickle
from typing import Dict, List, Optional

import numpy as np


class PrecisionType:
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


class PlaceType:
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3


class Config:
    """ref inference.Config: model paths + engine knobs (engine knobs are inert
    on TPU — XLA owns compilation)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self._prefix = prog_file
        self._params_file = params_file
        self._flags: Dict[str, object] = {}
        self._llm: Optional[Dict[str, object]] = None

    def enable_llm_engine(self, model_config, params, *, replicas: int = 1,
                          router: str = "affinity", **engine_kwargs):
        """Route this Config to the continuous-batching causal-LM engine
        instead of a saved StableHLO program: `create_predictor` then
        returns an `LLMEngine` (or, with `replicas > 1`, an `EngineFleet`
        routing across dp replicas — the serving front door's fleet).
        `engine_kwargs` forward to `LLMEngine` verbatim (num_slots,
        page_size, spec_len, kv_tier, ...)."""
        self._llm = {"model_config": model_config, "params": params,
                     "replicas": int(replicas), "router": router,
                     "engine_kwargs": engine_kwargs}
        return self

    def set_prog_file(self, path):
        self._prefix = path[:-len(".pdmodel")] if path.endswith(".pdmodel") \
            else path

    def set_params_file(self, path):
        self._params_file = path

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return self._params_file or (self._prefix or "") + ".pdiparams"

    # engine/placement knobs — accepted, inert (XLA owns them on TPU)
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._flags["gpu"] = device_id

    def enable_xpu(self, *a, **k):
        self._flags["xpu"] = True

    def disable_gpu(self):
        self._flags.pop("gpu", None)

    def enable_tensorrt_engine(self, *a, **k):
        self._flags["trt"] = True

    def enable_mkldnn(self):
        self._flags["mkldnn"] = True

    def switch_ir_optim(self, flag=True):
        self._flags["ir_optim"] = flag

    def enable_memory_optim(self, flag=True):
        self._flags["memory_optim"] = flag

    def set_cpu_math_library_num_threads(self, n):
        self._flags["threads"] = n

    def summary(self):
        return f"Config(prefix={self._prefix}, flags={self._flags})"


class Tensor_:
    """Named input/output handle (ref ZeroCopyTensor / PaddleTensor)."""

    def __init__(self, name):
        self.name = name
        self._data = None

    def copy_from_cpu(self, arr):
        self._data = np.asarray(arr)

    def copy_to_cpu(self):
        return self._data

    def reshape(self, shape):
        if self._data is not None:
            self._data = self._data.reshape(shape)

    def shape(self):
        return list(self._data.shape) if self._data is not None else []


class Predictor:
    """ref AnalysisPredictor: run a saved program with named handles."""

    def __init__(self, config: Config):
        from jax import export as jax_export
        self._config = config
        with open(config.prog_file(), "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        self._in_names: List[str] = []
        self._out_names: List[str] = []
        meta = {}
        params_path = config.params_file()
        if os.path.exists(params_path):
            with open(params_path, "rb") as f:
                try:
                    meta = pickle.load(f)
                except Exception:
                    meta = {}
        n_in = len(self._exported.in_avals)
        n_out = len(self._exported.out_avals)
        self._in_names = list(meta.get("feed_names") or
                              [f"input_{i}" for i in range(n_in)])[:n_in]
        if len(self._in_names) < n_in:
            self._in_names += [f"input_{i}"
                               for i in range(len(self._in_names), n_in)]
        self._out_names = [f"output_{i}" for i in range(n_out)]
        self._inputs = {n: Tensor_(n) for n in self._in_names}
        self._outputs = {n: Tensor_(n) for n in self._out_names}

    def get_input_names(self):
        return list(self._in_names)

    def get_output_names(self):
        return list(self._out_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_input_tensor(self, name):
        return self._inputs[name]

    def get_output_handle(self, name):
        return self._outputs[name]

    def get_output_tensor(self, name):
        return self._outputs[name]

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Batched inference: one XLA executable call (compiled once)."""
        import jax.numpy as jnp
        if inputs is not None:
            for n, a in zip(self._in_names, inputs):
                self._inputs[n].copy_from_cpu(a)
        args = [jnp.asarray(self._inputs[n]._data) for n in self._in_names]
        outs = self._exported.call(*args)
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        for n, o in zip(self._out_names, outs):
            self._outputs[n]._data = np.asarray(o)
        if inputs is not None:
            return [self._outputs[n]._data for n in self._out_names]


def _causal_lm_predictor(model_config, params, *, replicas: int = 1,
                         router: str = "affinity", **engine_kwargs):
    if params is None:
        raise ValueError("causal-LM predictor needs params (the model's "
                         "weight pytree)")
    from .engine import LLMEngine
    from .router import EngineFleet
    if replicas > 1:
        return EngineFleet(params, model_config, replicas=replicas,
                           router=router, engine_kwargs=engine_kwargs)
    return LLMEngine(params, model_config, **engine_kwargs)


def create_predictor(config, params=None, **engine_kwargs):
    """The ONE front door for inference construction (ref
    `paddle_inference_api.create_predictor`), now routing by config kind:

    - a `Config` naming a saved StableHLO program -> `Predictor` (the
      classic named-handle path);
    - a `Config` with `enable_llm_engine(...)` set, or a `models.gpt
      .GPTConfig` passed directly with `params=` -> the continuous-batching
      `LLMEngine`, or an `EngineFleet` of dp replicas when `replicas > 1`
      (affinity-routed by default; serve it over HTTP with
      `ServingFrontend`)."""
    if isinstance(config, Config):
        if config._llm is not None:
            spec = config._llm
            return _causal_lm_predictor(
                spec["model_config"], spec["params"],
                replicas=spec["replicas"], router=spec["router"],
                **{**spec["engine_kwargs"], **engine_kwargs})
        return Predictor(config)
    # duck-typed causal-LM model config (models.gpt.GPTConfig and friends)
    if hasattr(config, "num_layers") and hasattr(config, "vocab_size"):
        return _causal_lm_predictor(config, params, **engine_kwargs)
    raise TypeError(f"create_predictor: expected an inference.Config or a "
                    f"causal-LM model config, got {type(config).__name__}")


def get_version():
    from .. import __version__
    return __version__


def convert_to_mixed_precision(*a, **k):
    raise NotImplementedError(
        "convert_to_mixed_precision: on TPU use paddle.amp at train time or "
        "export the program in bfloat16 (GPU pass-pipeline concept)")


# Serving engine (continuous batching + paged KV cache) — lazy so importing
# paddle_tpu.inference does not pull the model zoo in.
_SERVING = {"LLMEngine": "engine", "Request": "engine",
            "RequestOutput": "engine", "RequestMetrics": "engine",
            "PagedKVCache": "cache",
            "DraftProposer": "spec", "NgramProposer": "spec",
            "MetricsRegistry": "metrics", "Counter": "metrics",
            "Gauge": "metrics", "Histogram": "metrics",
            "log_buckets": "metrics", "FleetMetrics": "metrics",
            "RateWindow": "metrics", "RATE_WINDOWS": "metrics",
            "RequestTrace": "tracing",
            "evaluate_engine_health": "health", "HEALTH_STATES": "health",
            "ObservabilityServer": "obs_server",
            "EngineFleet": "router", "FleetHandle": "router",
            "FleetOverloaded": "router", "ReplicaView": "router",
            "rank_replicas": "router", "ROUTER_POLICIES": "router",
            "ServingFrontend": "frontend", "PRIORITY_CLASSES": "frontend"}


def __getattr__(name):
    if name in _SERVING:
        import importlib
        mod = importlib.import_module("." + _SERVING[name], __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "PlaceType", "get_version", "convert_to_mixed_precision",
           "LLMEngine", "Request", "RequestOutput", "RequestMetrics",
           "PagedKVCache", "DraftProposer", "NgramProposer",
           "MetricsRegistry", "Counter", "Gauge", "Histogram",
           "log_buckets", "FleetMetrics", "RateWindow", "RATE_WINDOWS",
           "RequestTrace", "evaluate_engine_health", "HEALTH_STATES",
           "ObservabilityServer",
           "EngineFleet", "FleetHandle", "FleetOverloaded", "ReplicaView",
           "rank_replicas", "ROUTER_POLICIES", "ServingFrontend",
           "PRIORITY_CLASSES"]
