"""HTTP observability plane for the serving engine — stdlib-only, zero deps.

Reference lineage: the reference repo's monitor/stat machinery behind
`AnalysisPredictor` exposes pool/timer state to an external collector; every
modern serving stack (vLLM, TGI, Triton) does it over HTTP — Prometheus
scrapes ``/metrics``, dashboards poll a JSON stats endpoint, and tail-latency
debugging walks from a metric exemplar to the offending request's timeline.
This module is that front door for `inference.engine.LLMEngine` (and, via
`inference.metrics.FleetMetrics`, for a dp-replicated group of them):

- ``GET /metrics`` — text exposition (`MetricsRegistry.to_prometheus()`),
  content-negotiated: ``Accept: application/openmetrics-text`` gets
  OpenMetrics with ``# {...}`` bucket exemplars whose ``trace`` label is a
  path served two lines down (+ ``# EOF``); anything else gets plain
  0.0.4 text with the exemplar suffixes stripped (stock Prometheus
  text-format parsers reject them).  Fleet mode re-exposes every member
  under an ``{engine="<label>"}`` label plus ``llm_fleet_*`` merged totals.
- ``GET /stats`` — the engine's flat `stats()` dict as JSON (fleet:
  ``{label: stats}``).
- ``GET /requests/<rid>`` — the request's chrome-trace span tree
  (`LLMEngine.export_request_trace`); 404 for unknown ids.  This is where
  an exemplar's ``request_id`` resolves.  Request ids are per-engine
  counters, so fleet mode needs a member scope: fleet-exposed exemplar
  handles carry ``?engine=<label>``, and a bare rid matching multiple
  members returns 300 with the candidate handles instead of an arbitrary
  member's timeline.
- ``GET /debug`` — the postmortem bundle (`LLMEngine.debug_bundle()`:
  per-request states + timelines, step-trace ring, pool levels, stats,
  metrics snapshot) as JSON (fleet: ``{label: bundle}``).
- ``GET /healthz`` — the engine's REAL health evaluation
  (`LLMEngine.health()` against `analysis.registry.SERVE_SLO`: multi-window
  SLO burn rates, pool pressure, admission saturation, preemption churn,
  steady-state recompile anomalies), not a hardcoded liveness stub.
  ``ok``/``degraded`` answer 200 with the state and per-signal reasons in
  the body (degraded still serves traffic — a router should deprioritize,
  not eject); ``overloaded`` — or a health evaluation that cannot run at
  all, i.e. an engine wedged mid-crash — answers 503 so a probe takes the
  replica out of rotation.  Fleet mode reports per-engine detail plus a
  worst-of rollup (a fleet is as healthy as its sickest member).

Serving runs on a **daemon thread** (`ThreadingHTTPServer`) bound to an
ephemeral port by default (`port=0`; read `.port` after `start()`), so an
engine embeds it with two lines and a crashed engine process never blocks on
its observer.  Handlers read host scheduler state concurrently with `step()`
— Python's GIL keeps each read internally consistent, but a response is a
*best-effort snapshot*, not a barrier: a request can retire between two
lines of `/stats`.  Any handler exception returns 500 with the error text
instead of killing the server thread.

Usage::

    from paddle_tpu.inference.obs_server import ObservabilityServer
    srv = ObservabilityServer(engine).start()
    print(srv.url)                      # http://127.0.0.1:<port>
    ...
    srv.close()

    # fleet mode: one scrape surface over N dp replicas
    fleet = FleetMetrics().add("e0", eng0).add("e1", eng1)
    srv = ObservabilityServer(fleet=fleet).start()
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs

from .health import HEALTH_CODES
from .metrics import FleetMetrics

# exemplars are OpenMetrics-only syntax: a stock Prometheus text-format
# (0.0.4) parser rejects the `# {...} v` bucket suffix outright, so the
# server content-negotiates — plain scrapers get exemplar-free 0.0.4 text,
# and a client sending `Accept: application/openmetrics-text` (Prometheus
# does once exemplar storage is on) gets the full OpenMetrics exposition,
# `# EOF` terminator included
_METRICS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
_OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"

# the one route table: dispatch documentation AND the 404 body read it, so
# the advertised set cannot drift from what is actually served
ROUTES = ("/metrics", "/stats", "/requests/<rid>", "/debug", "/healthz")

# worst-of ordering for the fleet /healthz rollup, derived from the ONE
# declared state ordering (health.HEALTH_CODES) so a new health state cannot
# desynchronize the rollup; "error" (an evaluation that raised — the engine
# is wedged mid-crash) outranks every real state, and anything unrecognized
# ranks worst too — and therefore serves 503, never a blind 200
_ERROR_CODE = max(HEALTH_CODES.values()) + 1
_HEALTH_SEVERITY = {**HEALTH_CODES, "error": _ERROR_CODE}


def _health_status(state: str) -> int:
    """HTTP status for a health state: 200 up to degraded, 503 from
    overloaded up (error and unknown states included)."""
    return 503 if _HEALTH_SEVERITY.get(state, _ERROR_CODE) >= \
        HEALTH_CODES["overloaded"] else 200


class ObservabilityServer:
    """Daemon-thread HTTP server over one engine or a `FleetMetrics` group.

    Exactly one of `engine` / `fleet` must be given.  `port=0` (default)
    binds an ephemeral port; `host` defaults to loopback — this is an
    operator plane, not a public API, so exposing it wider is an explicit
    choice.  `start()` binds and returns self; `close()` shuts the listener
    down (also a context manager)."""

    def __init__(self, engine=None, *, fleet: Optional[FleetMetrics] = None,
                 host: str = "127.0.0.1", port: int = 0):
        if (engine is None) == (fleet is None):
            raise ValueError("pass exactly one of engine= or fleet=")
        self.engine = engine
        self.fleet = fleet
        self._host = host
        self._port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle --------------------------------------------------------
    def start(self) -> "ObservabilityServer":
        if self._httpd is not None:
            return self
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self._host, self._port), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-server", daemon=True)
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("server not started")
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._thread is not None:
                self._thread.join(timeout=5.0)
                self._thread = None

    def __enter__(self) -> "ObservabilityServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # ---- endpoint payloads (shared by the handler; best-effort snapshots) -
    def _engines(self):
        """(label, engine) pairs — fleet members with a stats() owner, or
        the single wrapped engine under the label "engine"."""
        if self.engine is not None:
            return [("engine", self.engine)]
        return [(label, e) for label, e in self.fleet.engines.items()
                if e is not None]

    def render_metrics(self, openmetrics: bool = True) -> str:
        """The scrape text: OpenMetrics (exemplars + `# EOF`) or plain
        0.0.4 text with the exemplar suffixes stripped."""
        if self.fleet is not None:
            text = self.fleet.to_prometheus(exemplars=openmetrics,
                                            openmetrics=openmetrics)
        else:
            text = self.engine.metrics.to_prometheus(exemplars=openmetrics,
                                                     openmetrics=openmetrics)
        return text + "# EOF\n" if openmetrics else text

    def render_stats(self):
        if self.fleet is not None:
            return {label: e.stats() for label, e in self._engines()}
        return self.engine.stats()

    def render_debug(self):
        if self.fleet is not None:
            return {label: e.debug_bundle() for label, e in self._engines()}
        return self.engine.debug_bundle()

    def render_health(self):
        """``(status_code, payload)`` for ``/healthz``: the engine's health
        evaluation, no longer a hardcoded ``{"ok": true}``.  ok/degraded are
        200 (degraded still serves; the state and reasons ride the body),
        overloaded is 503; an evaluation that RAISES — the exact
        wedged-mid-crash case the old stub answered 200 to — reports
        ``state="error"`` with the exception text, also 503.  Fleet mode:
        per-engine reports plus the worst-of rollup."""
        def one(e):
            try:
                h = e.health()
                return {"state": h["state"], "code": h["code"],
                        "reasons": h["reasons"], "signals": h["signals"],
                        "role": h.get("role")}
            except Exception as err:
                # same shape as a real report (probes read code/signals)
                return {"state": "error", "code": _ERROR_CODE,
                        "reasons": [f"health evaluation failed: "
                                    f"{type(err).__name__}: {err}"],
                        "signals": {}, "role": getattr(e, "role", None)}

        if self.fleet is not None:
            reports = {label: one(e) for label, e in self._engines()}
            worst = max((r["state"] for r in reports.values()),
                        key=lambda s: _HEALTH_SEVERITY.get(s, _ERROR_CODE),
                        default="ok")
            return _health_status(worst), {"state": worst, "engines": reports}
        rep = one(self.engine)
        return _health_status(rep["state"]), rep

    def dispatch(self, path: str, query: str = "", accept: str = "",
                 extra_routes: tuple = ()):
        """The ONE routing table, as data: ``(status, content_type,
        body_bytes)`` for any GET path.  Both HTTP doors serve exactly this
        — the stdlib handler below and the serving front door
        (`inference.frontend`, which mounts the obs routes next to
        ``/v1/*``) — so the two servers cannot drift.  Unknown paths 404
        with the advertised route list (plus the caller's `extra_routes`,
        e.g. the front door's inference endpoints)."""
        def json_reply(obj, code=200):
            return code, "application/json; charset=utf-8", \
                json.dumps(obj).encode("utf-8")

        path = path.rstrip("/") or "/"
        if path == "/metrics":
            om = "application/openmetrics-text" in (accept or "")
            return (200,
                    _OPENMETRICS_CONTENT_TYPE if om
                    else _METRICS_CONTENT_TYPE,
                    self.render_metrics(openmetrics=om).encode("utf-8"))
        if path == "/stats":
            return json_reply(self.render_stats())
        if path == "/debug":
            return json_reply(self.render_debug())
        if path == "/healthz":
            # routed through the real health evaluation (render_health
            # never raises: an evaluation failure IS a 503 payload, not a
            # generic 500 — and never a blind 200)
            code, payload = self.render_health()
            return json_reply(payload, code)
        if path.startswith("/requests/"):
            tail = path[len("/requests/"):]
            try:
                rid = int(tail)
            except ValueError:
                return json_reply({"error": f"bad request id {tail!r}"}, 400)
            engine = (parse_qs(query).get("engine") or [None])[0]
            status, payload = self.render_request(rid, engine)
            if status == "not_found":
                return json_reply(
                    {"error": f"unknown request {rid} (tracing off, "
                              f"never submitted, or not retained)"}, 404)
            if status == "ambiguous":
                return json_reply(
                    {"error": f"request id {rid} exists on "
                              f"{len(payload)} engines — request ids "
                              f"are per-engine; scope the lookup",
                     "engines": payload,
                     "handles": [f"/requests/{rid}?engine={lb}"
                                 for lb in payload]}, 300)
            return json_reply(payload)
        return json_reply({"error": f"no route {path!r}",
                           "routes": list(ROUTES) + list(extra_routes)}, 404)

    def render_request(self, rid: int, engine: Optional[str] = None):
        """``(status, payload)`` for ``/requests/<rid>``: ``("ok", tree)``,
        ``("not_found", None)``, or — fleet mode only — ``("ambiguous",
        [labels])``.  Request ids are per-engine counters, so in a fleet
        every member has a request 0: a bare rid that resolves in more than
        one member is reported as ambiguous (listing the members) instead of
        silently returning an arbitrary engine's timeline, and
        ``?engine=<label>`` (what fleet-exposed exemplar handles carry)
        scopes the lookup to exactly that member."""
        pairs = self._engines()
        if engine is not None:
            pairs = [(lb, e) for lb, e in pairs if lb == engine]
        hits = [(lb, e.export_request_trace(rid)) for lb, e in pairs]
        hits = [(lb, t) for lb, t in hits if t is not None]
        if not hits:
            return "not_found", None
        if len(hits) > 1:
            return "ambiguous", [lb for lb, _ in hits]
        return "ok", hits[0][1]


def _make_handler(srv: ObservabilityServer):
    class _Handler(BaseHTTPRequestHandler):
        # operator plane: no access-log spam on the engine's stderr
        def log_message(self, fmt, *args):
            pass

        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, obj, code: int = 200) -> None:
            self._send(code, json.dumps(obj).encode("utf-8"),
                       "application/json; charset=utf-8")

        def do_GET(self):  # noqa: N802 (http.server API)
            path, _, query = self.path.partition("?")
            try:
                # the shared routing table (srv.dispatch) is the whole
                # handler — the serving front door mounts the same calls
                code, ctype, body = srv.dispatch(
                    path, query, self.headers.get("Accept", ""))
                self._send(code, body, ctype)
            except (BrokenPipeError, ConnectionResetError):
                # client hung up mid-write (scrape timeout, curl Ctrl-C):
                # nothing to send a response TO — just drop the connection
                # quietly (a second write would raise again and socketserver
                # would traceback-spam the engine's stderr)
                return
            except Exception as e:  # snapshot raced the scheduler: report,
                try:                # don't kill the server thread
                    self._send_json({"error": f"{type(e).__name__}: {e}"},
                                    500)
                except OSError:
                    # the failure above may have left a half-written
                    # response or a dead socket; the 500 is best-effort
                    pass

    return _Handler
