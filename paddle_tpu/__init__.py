"""paddle_tpu — a TPU-native deep learning framework with PaddlePaddle's capability
surface, built on JAX/XLA/Pallas.

Public API mirrors `python/paddle/__init__.py` of the reference; implementations are
idiomatic TPU (XLA kernels, GSPMD parallelism, jaxpr program capture) rather than ports.
"""
from __future__ import annotations

import os as _os

import jax as _jax

# Sharding-invariant RNG (the modern JAX default).  On old JAX the default
# (False) lowers jitted `jax.random.*` with sharded out_shardings to
# per-shard streams, so the SAME seed yields DIFFERENT params on different
# meshes — which silently breaks every dp/mp-vs-single-device parity
# guarantee the parallel trainers advertise.  This is a process-global knob;
# an explicit JAX_THREEFRY_PARTITIONABLE env setting wins (see README).
if "JAX_THREEFRY_PARTITIONABLE" not in _os.environ:
    try:
        _jax.config.update("jax_threefry_partitionable", True)
    except Exception:  # flag removed once True became the only behavior
        pass

# ---- core ----
from .core import dtype as _dtype_mod
from .core.dtype import (bool_ as bool, uint8, int8, int16, int32, int64, float16,  # noqa
                         bfloat16, float32, float64, complex64, complex128,
                         set_default_dtype, get_default_dtype)
from .core.place import (CPUPlace, CUDAPinnedPlace, CUDAPlace, CustomPlace, Place,  # noqa
                         TPUPlace, XPUPlace, set_device, get_device, device_count,
                         is_compiled_with_cuda, is_compiled_with_rocm,
                         is_compiled_with_tpu, is_compiled_with_xpu)
from .core.tensor import Tensor, to_tensor  # noqa
from .core.autograd import no_grad, enable_grad, set_grad_enabled, is_grad_enabled  # noqa
from .core.generator import seed, get_rng_state_tracker  # noqa
from .core.flags import get_flags, set_flags  # noqa
from .core import generator as _generator

# ---- ops: flatten the functional namespace like paddle.* ----
from .ops.creation import (arange, assign, clone, complex, create_parameter, diag,  # noqa
                           diag_embed, diagflat, empty, empty_like, eye, full,
                           full_like, linspace, logspace, meshgrid, ones, ones_like,
                           polar, tril, tril_indices, triu, triu_indices, zeros,
                           zeros_like)
from .ops.math import (abs, acos, acosh, accuracy, add, addmm, all, amax, amin,  # noqa
                       angle, any, asin, asinh, atan, atan2, atanh, bmm,
                       broadcast_shape, ceil, clip, conj, copysign, cos, cosh,
                       count_nonzero, cross, cumprod, cummax, cummin, cumsum,
                       deg2rad, diagonal, diff, digamma, divide, dot,
                       erf, erfinv, exp, expm1, floor, floor_divide, floor_mod, fmax,
                       fmin, frac, gcd, heaviside, hypot, i0, i0e, i1, i1e, imag,
                       increment, inner, isfinite, isinf, isnan, isneginf, isposinf,
                       isreal, kron, lcm, ldexp, lerp, lgamma, log, log10, log1p,
                       log2, logaddexp, logcumsumexp, logsumexp, matmul, max, maximum,
                       mean, min, minimum, mm, mod, multiplex, multiply, mv, nan_to_num,
                       nanmean, nansum, neg, nextafter, outer, polygamma, pow, prod,
                       rad2deg, real, reciprocal, remainder, round, rsqrt, scale, sgn,
                       sign, sin, sinh, sqrt, square, stanh, subtract, sum, t, take,
                       tan, tanh, trace, trunc)
from .ops.manipulation import (as_complex, as_real, as_strided, atleast_1d,  # noqa
                               atleast_2d, atleast_3d, broadcast_tensors, broadcast_to,
                               cast, chunk, concat, crop, expand, expand_as, flatten,
                               flip, gather, gather_nd, index_add, index_put,
                               index_sample, index_select, is_complex, is_empty,
                               is_floating_point, is_integer, is_tensor, masked_fill,
                               masked_fill_, masked_select, moveaxis, nonzero, numel,
                               pad, put_along_axis, rank, repeat_interleave, reshape,
                               reshape_, roll, rot90, scatter, scatter_, scatter_nd,
                               scatter_nd_add, shape, shard_index, slice, split,
                               squeeze, squeeze_, stack, strided_slice, swapaxes,
                               take_along_axis, tensor_split, tile, transpose, unbind,
                               unique, unique_consecutive, unsqueeze, unsqueeze_,
                               unstack, view, view_as, where, where_)
from .ops.logic import (allclose, bitwise_and, bitwise_not, bitwise_or, bitwise_xor,  # noqa
                        equal, equal_all, greater_equal, greater_than, isclose,
                        less_equal, less_than, logical_and, logical_not, logical_or,
                        logical_xor, not_equal)
from .ops.random import (bernoulli, bernoulli_, binomial, cauchy_, exponential_,  # noqa
                         gaussian, geometric_, get_cuda_rng_state, get_rng_state,
                         log_normal_, multinomial, normal, normal_, poisson, rand,
                         rand_like, randint, randint_like, randn, randn_like, randperm,
                         set_cuda_rng_state, set_rng_state, standard_normal, uniform,
                         uniform_)
from .ops.search import (argmax, argmin, argsort, bucketize, kthvalue, mode,  # noqa
                         searchsorted, sort, topk)
from .ops.stat import median, nanmedian, nanquantile, quantile, std, var  # noqa
from .ops.linalg import (bincount, cdist, cholesky, cholesky_solve, cond, corrcoef,  # noqa
                         cov, det, dist, eig, eigh, eigvals, eigvalsh, histogram,
                         histogramdd, householder_product, inverse, lstsq, lu,
                         matrix_power, matrix_rank, multi_dot, norm, pdist, pinv, qr,
                         slogdet, solve, svd, triangular_solve)
from .ops.einsum import einsum  # noqa
from .ops.math import (add_n, cumulative_trapezoid, frexp, logit, renorm,  # noqa
                       sigmoid, trapezoid)
from .ops.manipulation import reverse, unflatten, unfold, vsplit  # noqa
from .ops.linalg import lu_unpack, pca_lowrank, tensordot  # noqa
from .ops.creation import create_tensor, vander  # noqa
from .ops.inplace import *  # noqa  (trailing-underscore in-place variants)

from .param_attr import ParamAttr  # noqa
from .framework.io import save, load  # noqa
from .autograd import grad, backward  # noqa
from .utils.dlpack import to_dlpack, from_dlpack  # noqa

# ---- subpackages (paddle.nn style access) ----
from . import amp  # noqa
from . import audio  # noqa
from . import autograd  # noqa
from . import distributed  # noqa
from . import distribution  # noqa
from . import fft  # noqa
from . import geometric  # noqa
from . import signal  # noqa
from . import text  # noqa
from . import framework  # noqa
from . import incubate  # noqa
from . import io  # noqa
from . import jit  # noqa
from . import linalg  # noqa
from . import metric  # noqa
from . import nn  # noqa
from . import optimizer  # noqa
from . import inference  # noqa
from . import onnx  # noqa
from . import profiler  # noqa
from . import quantization  # noqa
from . import sparse  # noqa
from . import static  # noqa
from . import utils  # noqa
from . import vision  # noqa

from .jit import to_static  # noqa
from .distributed import DataParallel  # noqa
from .hapi.model import Model  # noqa

# dygraph flag compat: we are always in dygraph (eager) mode unless static capture
_in_dynamic = True


def in_dynamic_mode():
    return _in_dynamic


def disable_static():
    global _in_dynamic
    _in_dynamic = True
    static._disable_static_recording()


def enable_static():
    global _in_dynamic
    _in_dynamic = False
    static._enable_static_recording()


def disable_signal_handler():
    pass


def device(dev):  # paddle.device module shim is in utils; keep callable
    return set_device(dev)


class finfo:
    """ref paddle.finfo: floating-point type limits."""

    def __init__(self, dtype):
        import jax.numpy as _jnp
        from .core.dtype import to_np as _to_np
        fi = _jnp.finfo(_to_np(dtype))
        self.min = float(fi.min)
        self.max = float(fi.max)
        self.eps = float(fi.eps)
        self.tiny = float(fi.tiny)
        self.smallest_normal = float(fi.tiny)
        self.resolution = float(fi.resolution)
        self.bits = int(fi.bits)
        self.dtype = str(fi.dtype)


class iinfo:
    """ref paddle.iinfo: integer type limits."""

    def __init__(self, dtype):
        import jax.numpy as _jnp
        from .core.dtype import to_np as _to_np
        ii = _jnp.iinfo(_to_np(dtype))
        self.min = int(ii.min)
        self.max = int(ii.max)
        self.bits = int(ii.bits)
        self.dtype = str(ii.dtype)


dtype = _dtype_mod.DType  # paddle.dtype type object (ref VarType alias)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """ref paddle.set_printoptions — forwards to numpy's print options."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def tolist(x):
    """ref paddle.tolist: nested python list of tensor values."""
    import numpy as _np
    return _np.asarray(x.numpy() if hasattr(x, "numpy") else x).tolist()


class LazyGuard:
    """ref paddle.LazyGuard: delayed parameter init context.  Eager jax init is
    cheap, so this is a transparent shim (params materialize immediately)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def batch(reader, batch_size, drop_last=False):
    """ref paddle.batch (legacy reader decorator)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def check_shape(x):
    """ref static nn.check_shape helper (shape sanity assert shim)."""
    return x


def summary(net, input_size=None, dtypes=None, input=None):
    from .hapi.summary import summary as _s
    return _s(net, input_size, dtypes, input)


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .hapi.flops import flops as _f
    return _f(net, input_size, custom_ops, print_detail)


def _patch_tensor_methods():
    """Attach the functional namespace as Tensor methods, like the reference's
    monkey-patch in `python/paddle/fluid/dygraph/tensor_patch_methods.py`."""
    import sys
    mod = sys.modules[__name__]
    from .ops import (creation, inplace, linalg, logic, manipulation, math,
                      random, search, stat)
    from .ops.einsum import einsum as _einsum  # noqa

    method_sources = [math, manipulation, logic, search, stat, linalg, creation,
                      random, inplace]
    skip = {"broadcast_shape", "create_parameter", "meshgrid", "is_tensor",
            "get_rng_state", "set_rng_state", "get_cuda_rng_state", "set_cuda_rng_state"}
    for src in method_sources:
        for name in dir(src):
            if name.startswith("_") or name in skip:
                continue
            fn = getattr(src, name)
            if not callable(fn):
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    # explicit overrides where method semantics differ slightly
    Tensor.norm = linalg.norm
    Tensor.matmul = math.matmul
    Tensor.reshape = manipulation.reshape
    Tensor.cast = manipulation.cast

    # sparse conversions (ref Tensor.to_sparse_coo / to_sparse_csr / to_dense)
    def _to_sparse_coo(self, sparse_dim=None):
        from .sparse import _dense_to_coo
        return _dense_to_coo(self, sparse_dim)

    def _to_sparse_csr(self):
        from .sparse import _dense_to_coo
        return _dense_to_coo(self).to_sparse_csr()

    Tensor.to_sparse_coo = _to_sparse_coo
    Tensor.to_sparse_csr = _to_sparse_csr
    Tensor.to_dense = lambda self: self
    Tensor.is_sparse = lambda self: False
    Tensor.is_sparse_coo = lambda self: False
    Tensor.is_sparse_csr = lambda self: False


_patch_tensor_methods()

__version__ = "0.1.0"
version = type("version", (), {"full_version": __version__,
                               "commit": "tpu-native",
                               "cuda": staticmethod(lambda: None),
                               "show": staticmethod(lambda: print(__version__))})
