"""nn.utils (reference: `python/paddle/nn/utils/`)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.power(sum(jnp.sum(jnp.power(jnp.abs(g._data.astype(jnp.float32)),
                                                norm_type)) for g in grads),
                          1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError("non-finite grad norm")
    clip_coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for g in grads:
        g._data = (g._data * clip_coef).astype(g._data.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)


def parameters_to_vector(parameters, name=None):
    return Tensor(jnp.concatenate([p._data.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p._data.size
        p._data = vec._data[offset:offset + n].reshape(p._data.shape).astype(p._data.dtype)
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Weight normalization reparameterization (cold path: recompute on access)."""
    w = getattr(layer, name)
    from ..initializer import Assign
    g_data = jnp.linalg.norm(np.asarray(w._data).reshape(w._data.shape[dim], -1)
                             if dim == 0 else np.moveaxis(np.asarray(w._data), dim, 0)
                             .reshape(w._data.shape[dim], -1), axis=1)
    from ...core.tensor import Parameter
    layer.add_parameter(name + "_g", Parameter(jnp.asarray(g_data)))
    layer.add_parameter(name + "_v", Parameter(w._data))
    del layer._parameters[name]

    def hook(lyr, inputs):
        v = lyr._parameters[name + "_v"]
        g = lyr._parameters[name + "_g"]
        vm = jnp.moveaxis(v._data, dim, 0)
        norm = jnp.linalg.norm(vm.reshape(vm.shape[0], -1), axis=1)
        shape = [-1] + [1] * (v._data.ndim - 1)
        new_w = jnp.moveaxis(vm / norm.reshape(shape) * g._data.reshape(shape), 0, dim)
        object.__setattr__(lyr, "_wn_cache", Tensor(new_w, stop_gradient=True))
        lyr.__dict__[name] = lyr._wn_cache
    layer.register_forward_pre_hook(hook)
    return layer


def remove_weight_norm(layer, name="weight"):
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    return layer
