"""paddle.nn parity surface."""
from . import functional  # noqa
from . import initializer  # noqa
from .layer import *  # noqa
from .layer import Layer  # noqa
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa
from .utils import clip_grad_norm_, clip_grad_value_, parameters_to_vector, vector_to_parameters  # noqa
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa
