"""Extension functionals (reference: `python/paddle/nn/functional/extension.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import apply
from ...ops.creation import diag_embed  # noqa: F401  (re-export, paddle places it here)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ...core import dtype as _dt

    def f(lens):
        m = maxlen if maxlen is not None else int(lens.max())
        return (jnp.arange(m)[None, :] < lens[..., None]).astype(_dt.to_np(dtype))
    return apply("sequence_mask", f, x)


def gather_tree(ids, parents):
    def f(step_ids, parent_ids):
        T, B, W = step_ids.shape

        def body(carry, t):
            beams = carry
            new_beams = jnp.take_along_axis(parent_ids[t], beams, axis=-1)
            tokens = jnp.take_along_axis(step_ids[t], beams, axis=-1)
            return new_beams, tokens

        init = jnp.tile(jnp.arange(W)[None, :], (B, 1))
        _, toks = jax.lax.scan(body, init, jnp.arange(T - 1, -1, -1))
        return jnp.flip(toks, axis=0)
    return apply("gather_tree", f, ids, parents)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]),
                                 v[:, :-1, fold:2 * fold]], axis=1)
        rest = v[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out
    return apply("temporal_shift", f, x)
