"""Normalization functionals (reference: `python/paddle/nn/functional/norm.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply, _to_data


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    ns = (normalized_shape,) if isinstance(normalized_shape, int) else tuple(normalized_shape)
    axes = tuple(range(-len(ns), 0))

    def f(a, *rest):
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(a.dtype)
        it = iter(rest)
        if weight is not None:
            out = out * next(it)
        if bias is not None:
            out = out + next(it)
        return out
    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply("layer_norm", f, *args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-05, data_format="NCHW", use_global_stats=None,
               name=None):
    """BatchNorm with running-stat update (reference phi `batch_norm` kernel).

    Running stats update mutates the buffer tensors in place (matching the reference's
    in-place MeanOut/VarianceOut); under `to_static` capture the mutation is traced as
    functional state.
    """
    channel_last = data_format in ("NHWC", "NLC", "NDHWC") or data_format == "NHWC"
    use_stats = use_global_stats if use_global_stats is not None else not training

    data = _to_data(x)
    ch_axis = data.ndim - 1 if channel_last else (1 if data.ndim > 1 else 0)
    red_axes = tuple(i for i in range(data.ndim) if i != ch_axis)

    if not use_stats:
        # compute batch stats and update running buffers in place
        batch_mean = jnp.mean(data.astype(jnp.float32), axis=red_axes)
        batch_var = jnp.var(data.astype(jnp.float32), axis=red_axes)
        if isinstance(running_mean, Tensor):
            running_mean._data = (momentum * running_mean._data
                                  + (1 - momentum) * batch_mean).astype(running_mean._data.dtype)
            running_var._data = (momentum * running_var._data
                                 + (1 - momentum) * batch_var).astype(running_var._data.dtype)

        def f(a, *rest):
            m = jnp.mean(a.astype(jnp.float32), axis=red_axes)
            v = jnp.var(a.astype(jnp.float32), axis=red_axes)
            shape = [1] * a.ndim
            shape[ch_axis] = a.shape[ch_axis]
            out = (a.astype(jnp.float32) - m.reshape(shape)) * jax.lax.rsqrt(v.reshape(shape) + epsilon)
            out = out.astype(a.dtype)
            it = iter(rest)
            if weight is not None:
                out = out * next(it).reshape(shape)
            if bias is not None:
                out = out + next(it).reshape(shape)
            return out
        args = (x,) + tuple(t for t in (weight, bias) if t is not None)
        return apply("batch_norm", f, *args)

    def f(a, m, v, *rest):
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        out = (a.astype(jnp.float32) - m.astype(jnp.float32).reshape(shape)) \
            * jax.lax.rsqrt(v.astype(jnp.float32).reshape(shape) + epsilon)
        out = out.astype(a.dtype)
        it = iter(rest)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        return out
    args = (x, running_mean, running_var) + tuple(t for t in (weight, bias) if t is not None)
    return apply("batch_norm", f, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW",
                  name=None):
    def f(a, *rest):
        red = tuple(range(2, a.ndim))
        m = jnp.mean(a.astype(jnp.float32), axis=red, keepdims=True)
        v = jnp.var(a.astype(jnp.float32), axis=red, keepdims=True)
        out = ((a.astype(jnp.float32) - m) * jax.lax.rsqrt(v + eps)).astype(a.dtype)
        shape = [1] * a.ndim
        shape[1] = a.shape[1]
        it = iter(rest)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        return out
    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply("instance_norm", f, *args)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None, data_format="NCHW",
               name=None):
    channel_last = data_format.endswith("C") and data_format != "NC"

    def f(a, *rest):
        if channel_last:
            a_cf = jnp.moveaxis(a, -1, 1)
        else:
            a_cf = a
        n, c = a_cf.shape[0], a_cf.shape[1]
        g = num_groups
        grouped = a_cf.reshape((n, g, c // g) + a_cf.shape[2:])
        red = tuple(range(2, grouped.ndim))
        m = jnp.mean(grouped.astype(jnp.float32), axis=red, keepdims=True)
        v = jnp.var(grouped.astype(jnp.float32), axis=red, keepdims=True)
        out = ((grouped.astype(jnp.float32) - m) * jax.lax.rsqrt(v + epsilon))
        out = out.reshape(a_cf.shape).astype(a.dtype)
        shape = [1] * a_cf.ndim
        shape[1] = c
        it = iter(rest)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply("group_norm", f, *args)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        if p == 2:
            nrm = jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=True))
        else:
            nrm = jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=axis, keepdims=True), 1.0 / p)
        return a / jnp.maximum(nrm, epsilon)
    return apply("normalize", f, x)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                        name=None):
    def f(a):
        sq = jnp.square(a)
        half = size // 2
        c_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        sq_cf = jnp.moveaxis(sq, c_axis, 0)
        c = sq_cf.shape[0]
        padded = jnp.pad(sq_cf, [(half, size - half - 1)] + [(0, 0)] * (sq_cf.ndim - 1))
        acc = jnp.zeros_like(sq_cf)
        for i in range(size):
            acc = acc + padded[i:i + c]
        acc = jnp.moveaxis(acc, 0, c_axis)
        return a / jnp.power(k + alpha * acc / size, beta)
    return apply("local_response_norm", f, x)


def rms_norm(x, weight, epsilon=1e-6, name=None):
    """RMSNorm functional — fused path lives in incubate (Pallas kernel)."""
    def f(a, w):
        var = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1, keepdims=True)
        return (a.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype) * w
    return apply("rms_norm", f, x, weight)
