"""Common functionals: linear, dropout, interpolate, one_hot, pad…
(reference: `python/paddle/nn/functional/common.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dtype as _dt
from ...core import generator as _gen
from ...core.tensor import Tensor, apply, _to_data
from ...ops.manipulation import pad as _pad_op


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with W stored [in, out] (reference phi `matmul` + `elementwise_add`;
    maps to one MXU matmul with fused bias add under XLA)."""
    if bias is None:
        return apply("linear", lambda a, w: jnp.matmul(a, w), x, weight)
    return apply("linear", lambda a, w, b: jnp.matmul(a, w) + b, x, weight, bias)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply("dropout_scale", lambda a: a * (1.0 - p), x)
        return x
    if isinstance(p, Tensor):
        p = float(p.item())

    def f(a):
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(_gen.next_key(), 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)
    return apply("dropout", f, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x

    def f(a):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(_gen.next_key(), 1.0 - p, a.shape)
        a_scale = (1.0 / np.sqrt((1 - p) * (1 + p * alpha_p ** 2))).astype(np.float32)
        b = -a_scale * alpha_p * p
        return (jnp.where(keep, a, alpha_p) * a_scale + b).astype(a.dtype)
    return apply("alpha_dropout", f, x)


def one_hot(x, num_classes, name=None):
    return apply("one_hot", lambda a: jax.nn.one_hot(a.astype(jnp.int32), num_classes,
                                                     dtype=jnp.float32), x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(lab, *rest):
        k = lab.shape[-1]
        if rest:
            return (1 - epsilon) * lab + epsilon * rest[0]
        return (1 - epsilon) * lab + epsilon / k
    args = (label,) if prior_dist is None else (label, prior_dist)
    return apply("label_smooth", f, *args)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return apply("cosine_similarity", f, x1, x2)


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *rest):
        out = jnp.einsum("bm,omn,bn->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return apply("bilinear", f, *args)


pad = _pad_op


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return _pad_op(x, padding, mode="constant", value=0.0, data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    """Resize (reference `nn/functional/common.py` interpolate).  Supports
    nearest/bilinear/bicubic/trilinear/area/linear over NCHW/NHWC layouts via
    jax.image.resize (XLA-fused gather path)."""
    data = _to_data(x)
    nd = data.ndim
    channel_last = data_format in ("NHWC", "NDHWC", "NLC")
    spatial = nd - 2
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(v) for v in np.asarray(size._data)]
        out_sp = [int(v.item()) if isinstance(v, Tensor) else int(v) for v in
                  (size if isinstance(size, (list, tuple)) else [size] * spatial)]
    else:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * spatial
        in_sp = data.shape[1:-1] if channel_last else data.shape[2:]
        out_sp = [int(s * f) for s, f in zip(in_sp, scale_factor)]

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def f(a):
        if channel_last:
            shape = (a.shape[0],) + tuple(out_sp) + (a.shape[-1],)
        else:
            shape = a.shape[:2] + tuple(out_sp)
        if jmode == "nearest":
            return jax.image.resize(a, shape, method="nearest")
        if align_corners:
            # align_corners resize: explicit coordinate map via linear interp per axis
            return _resize_align_corners(a, shape, jmode, channel_last)
        return jax.image.resize(a, shape, method=jmode)
    return apply("interpolate", f, x)


def _resize_align_corners(a, shape, method, channel_last):
    nd = a.ndim
    sp_axes = list(range(1, nd - 1)) if channel_last else list(range(2, nd))
    out = a
    for ax in sp_axes:
        n_in = out.shape[ax]
        n_out = shape[ax]
        if n_in == n_out:
            continue
        if n_out == 1:
            idx_lo = jnp.zeros((1,), jnp.int32)
            idx_hi = idx_lo
            w = jnp.zeros((1,), out.dtype)
        else:
            pos = jnp.arange(n_out, dtype=jnp.float32) * (n_in - 1) / (n_out - 1)
            idx_lo = jnp.floor(pos).astype(jnp.int32)
            idx_hi = jnp.minimum(idx_lo + 1, n_in - 1)
            w = (pos - idx_lo).astype(out.dtype)
        lo = jnp.take(out, idx_lo, axis=ax)
        hi = jnp.take(out, idx_hi, axis=ax)
        bshape = [1] * out.ndim
        bshape[ax] = n_out
        w = w.reshape(bshape)
        out = lo * (1 - w) + hi * w
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference phi `unfold` kernel)."""
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    ph, pw = pair(paddings) if not (isinstance(paddings, (list, tuple)) and len(paddings) == 4) else (paddings[0], paddings[1])
    dh, dw = pair(dilations)

    def f(a):
        n, c, h, w = a.shape
        a2 = jnp.pad(a, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        out_h = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        out_w = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            a2, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return patches.reshape(n, c * kh * kw, out_h * out_w)
    return apply("unfold", f, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    oh, ow = pair(output_sizes)
    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    ph, pw = pair(paddings)
    dh, dw = pair(dilations)

    def f(a):
        n, ckk, l = a.shape
        c = ckk // (kh * kw)
        out_h = (oh + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        out_w = (ow + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        cols = a.reshape(n, c, kh, kw, out_h, out_w)
        out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), a.dtype)
        for i in range(kh):
            for j in range(kw):
                hi = i * dh
                wj = j * dw
                out = out.at[:, :, hi:hi + sh * out_h:sh, wj:wj + sw * out_w:sw].add(
                    cols[:, :, i, j])
        return out[:, :, ph:ph + oh, pw:pw + ow]
    return apply("fold", f, x)
