"""Distance functionals (reference: `python/paddle/nn/functional/distance.py`)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import apply


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def f(a, b):
        d = a - b + epsilon
        return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p), axis=-1, keepdims=keepdim),
                         1.0 / p)
    return apply("pairwise_distance", f, x, y)
