"""Input functionals (reference: `python/paddle/nn/functional/input.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import apply


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Embedding lookup (reference phi `embedding` kernel; `sparse` selects
    SelectedRows grad in the reference — here grads are dense scatter-adds, which is the
    XLA-native form)."""
    def f(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (ids == padding_idx)
            out = jnp.where(mask[..., None], 0.0, out)
        return out
    return apply("embedding", f, x, weight)


def one_hot(x, num_classes, name=None):
    return apply("one_hot", lambda a: jax.nn.one_hot(a.astype(jnp.int32), num_classes,
                                                     dtype=jnp.float32), x)
