"""Vision functionals (reference: `python/paddle/nn/functional/vision.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import apply


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            out = a.reshape(n, c // (r * r), r, r, h, w)
            out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
            return out.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        out = a.reshape(n, h, w, r, r, c // (r * r))
        out = jnp.transpose(out, (0, 1, 3, 2, 4, 5))
        return out.reshape(n, h * r, w * r, c // (r * r))
    return apply("pixel_shuffle", f, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            out = a.reshape(n, c, h // r, r, w // r, r)
            out = jnp.transpose(out, (0, 1, 3, 5, 2, 4))
            return out.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        out = a.reshape(n, h // r, r, w // r, r, c)
        out = jnp.transpose(out, (0, 1, 3, 2, 4, 5))
        return out.reshape(n, h // r, w // r, c * r * r)
    return apply("pixel_unshuffle", f, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            out = a.reshape(n, groups, c // groups, h, w)
            out = jnp.transpose(out, (0, 2, 1, 3, 4))
            return out.reshape(n, c, h, w)
        n, h, w, c = a.shape
        out = a.reshape(n, h, w, groups, c // groups)
        out = jnp.transpose(out, (0, 1, 2, 4, 3))
        return out.reshape(n, h, w, c)
    return apply("channel_shuffle", f, x)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def f(th):
        n, _, _ = th.shape
        if len(out_shape) == 4:
            _, _, h, w = out_shape
            if align_corners:
                ys = jnp.linspace(-1, 1, h)
                xs = jnp.linspace(-1, 1, w)
            else:
                ys = (jnp.arange(h) + 0.5) * 2 / h - 1
                xs = (jnp.arange(w) + 0.5) * 2 / w - 1
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            ones = jnp.ones_like(gx)
            base = jnp.stack([gx, gy, ones], axis=-1)  # [H, W, 3]
            out = jnp.einsum("hwk,nck->nhwc", base.astype(th.dtype), th)
            return out
        raise NotImplementedError("5-D affine_grid")
    return apply("affine_grid", f, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True,
                name=None):
    def f(a, g):
        n, c, h, w = a.shape
        gx = g[..., 0]
        gy = g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def sample(img, yy, xx):
            # img [C,H,W]; yy,xx [Ho,Wo] float
            if padding_mode == "border":
                yy = jnp.clip(yy, 0, h - 1)
                xx = jnp.clip(xx, 0, w - 1)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            if mode == "nearest":
                yi = jnp.clip(jnp.round(yy).astype(jnp.int32), 0, h - 1)
                xi = jnp.clip(jnp.round(xx).astype(jnp.int32), 0, w - 1)
                out = img[:, yi, xi]
                if padding_mode == "zeros":
                    valid = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
                    out = jnp.where(valid[None], out, 0.0)
                return out
            y1 = y0 + 1
            x1 = x0 + 1
            wy = yy - y0
            wx = xx - x0

            def at(yi, xi):
                yc = jnp.clip(yi, 0, h - 1)
                xc = jnp.clip(xi, 0, w - 1)
                v = img[:, yc, xc]
                if padding_mode == "zeros":
                    valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
                    v = jnp.where(valid[None], v, 0.0)
                return v
            out = (at(y0, x0) * ((1 - wy) * (1 - wx))[None]
                   + at(y0, x1) * ((1 - wy) * wx)[None]
                   + at(y1, x0) * (wy * (1 - wx))[None]
                   + at(y1, x1) * (wy * wx)[None])
            return out
        out = jax.vmap(sample)(a, fy, fx)
        return out.astype(a.dtype)
    return apply("grid_sample", f, x, grid)
