"""Loss functionals (reference: `python/paddle/nn/functional/loss.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply, _to_data


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    """Softmax cross entropy (reference phi `cross_entropy_with_softmax` kernel).

    Log-softmax + gather formulation: numerically stable and XLA fuses it into the
    preceding matmul's epilogue.
    """
    def f(logits, lab, *rest):
        w = rest[0] if rest else None
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis) if use_softmax \
            else jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
        if soft_label or (lab.dtype in (jnp.float32, jnp.float16, jnp.bfloat16)
                          and lab.shape == logits.shape):
            sl = lab.astype(jnp.float32)
            if label_smoothing > 0.0:
                k = logits.shape[axis]
                sl = sl * (1 - label_smoothing) + label_smoothing / k
            loss = -jnp.sum(sl * lp, axis=axis)
        else:
            li = lab.astype(jnp.int32)
            squeeze = li.ndim == lp.ndim and li.shape[axis] == 1
            if squeeze:
                li = jnp.squeeze(li, axis)
            if label_smoothing > 0.0:
                k = logits.shape[axis]
                onehot = jax.nn.one_hot(li, k, axis=axis, dtype=jnp.float32)
                sl = onehot * (1 - label_smoothing) + label_smoothing / k
                loss = -jnp.sum(sl * lp, axis=axis)
            else:
                safe = jnp.where(li == ignore_index, 0, li)
                loss = -jnp.take_along_axis(lp, jnp.expand_dims(safe, axis), axis=axis)
                loss = jnp.squeeze(loss, axis)
            mask = (li != ignore_index)
            loss = jnp.where(mask, loss, 0.0)
            if w is not None:
                wv = jnp.take(w.astype(jnp.float32), jnp.where(li == ignore_index, 0, li))
                wv = jnp.where(mask, wv, 0.0)
                loss = loss * wv
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(wv), 1e-12)
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply("cross_entropy", f, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    def f(lg, lab):
        sm = jax.nn.softmax(lg.astype(jnp.float32), axis=axis)
        lp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=axis)
        if soft_label:
            loss = -jnp.sum(lab.astype(jnp.float32) * lp, axis=axis, keepdims=True)
        else:
            li = lab.astype(jnp.int32)
            if li.ndim == lp.ndim and li.shape[axis] == 1:
                gather_idx = li
            else:
                gather_idx = jnp.expand_dims(li, axis)
            safe = jnp.where(gather_idx == ignore_index, 0, gather_idx)
            loss = -jnp.take_along_axis(lp, safe, axis=axis)
            loss = jnp.where(gather_idx == ignore_index, 0.0, loss)
        if return_softmax:
            return loss.astype(lg.dtype), sm.astype(lg.dtype)
        return loss.astype(lg.dtype)
    return apply("softmax_with_cross_entropy", f, logits, label)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def f(lp, lab, *rest):
        li = lab.astype(jnp.int32)
        safe = jnp.where(li == ignore_index, 0, li)
        loss = -jnp.take_along_axis(lp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        mask = (li != ignore_index).astype(jnp.float32)
        if rest:
            wv = jnp.take(rest[0], safe) * mask
            loss = loss * wv
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wv), 1e-12)
        else:
            loss = loss * mask
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1.0)
        return _reduce(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply("nll_loss", f, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return apply("mse_loss", lambda a, b: _reduce(jnp.square(a - b), reduction),
                 input, label)


def l1_loss(input, label, reduction="mean", name=None):
    return apply("l1_loss", lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply("smooth_l1_loss", f, input, label)


def square_error_cost(input, label):
    return apply("square_error_cost", lambda a, b: jnp.square(a - b), input, label)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, y, *rest):
        p32 = jnp.clip(p.astype(jnp.float32), 1e-12, 1.0 - 1e-7)
        loss = -(y * jnp.log(p32) + (1 - y) * jnp.log1p(-p32))
        if rest:
            loss = loss * rest[0]
        return _reduce(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply("binary_cross_entropy", f, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def f(z, y, *rest):
        it = iter(rest)
        w = next(it) if weight is not None else None
        pw = next(it) if pos_weight is not None else None
        z32 = z.astype(jnp.float32)
        y32 = y.astype(jnp.float32)
        # stable: max(z,0) - z*y + log(1+exp(-|z|)); pos_weight scales positive term
        if pw is None:
            loss = jnp.maximum(z32, 0) - z32 * y32 + jnp.log1p(jnp.exp(-jnp.abs(z32)))
        else:
            log_w = 1 + (pw - 1) * y32
            loss = (1 - y32) * z32 + log_w * (jnp.log1p(jnp.exp(-jnp.abs(z32)))
                                              + jnp.maximum(-z32, 0))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return apply("bce_with_logits", f, *args)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(lp, y):
        if log_target:
            loss = jnp.exp(y) * (y - lp)
        else:
            loss = jnp.where(y > 0, y * (jnp.log(jnp.maximum(y, 1e-30)) - lp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)
    return apply("kl_div", f, input, label)


def log_loss(input, label, epsilon=1e-4, name=None):
    def f(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return apply("log_loss", f, input, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def f(a, y):
        loss = jnp.where(y == 1.0, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply("hinge_embedding_loss", f, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, y):
        loss = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(loss, reduction)
    return apply("margin_ranking_loss", f, input, other, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply("cosine_embedding_loss", f, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        def dist(u, v):
            return jnp.power(jnp.sum(jnp.power(jnp.abs(u - v) + epsilon, p), axis=-1), 1.0 / p)
        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_pn = dist(pos, neg)
            d_an = jnp.minimum(d_an, d_pn)
        return _reduce(jnp.maximum(0.0, d_ap - d_an + margin), reduction)
    return apply("triplet_margin_loss", f, input, positive, negative)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    def f(z, y, *rest):
        loss = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        loss = jnp.mean(loss, axis=-1)
        if rest:
            loss = loss * rest[0]
        return _reduce(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply("multi_label_soft_margin_loss", f, *args)


def soft_margin_loss(input, label, reduction="mean", name=None):
    def f(z, y):
        return _reduce(jnp.log1p(jnp.exp(-y * z)), reduction)
    return apply("soft_margin_loss", f, input, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if rest:
            loss = loss / rest[0]
        return _reduce(loss, reduction)
    args = (logit, label) + ((normalizer,) if normalizer is not None else ())
    return apply("sigmoid_focal_loss", f, *args)


def dice_loss(input, label, epsilon=1e-5, name=None):
    def f(p, y):
        yf = jax.nn.one_hot(y.squeeze(-1).astype(jnp.int32), p.shape[-1], dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * yf, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(yf, axis=red)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply("dice_loss", f, input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def f(a, p, lab):
        batch = a.shape[0]
        logits = jnp.matmul(a, p.T)
        same = (lab.reshape(-1, 1) == lab.reshape(1, -1)).astype(jnp.float32)
        same = same / jnp.sum(same, axis=1, keepdims=True)
        xent = jnp.mean(jnp.sum(-same * jax.nn.log_softmax(logits, axis=1), axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1))
                        + jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return xent + reg
    return apply("npair_loss", f, anchor, positive, labels)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def f(z, y):
        if log_input:
            loss = jnp.exp(z) - y * z
        else:
            loss = z - y * jnp.log(z + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(2 * jnp.pi * (y + epsilon))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return apply("poisson_nll_loss", f, input, label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via optax's implementation (log-space forward algorithm on XLA)."""
    import optax

    def f(lp, lab, il, ll):
        # optax expects [B, T, V] logits and paddle gives [T, B, V]
        logits = jnp.transpose(lp, (1, 0, 2)).astype(jnp.float32)
        B, T, V = logits.shape
        logitpad = jnp.arange(T)[None, :] >= il[:, None]
        maxL = lab.shape[1]
        labelpad = jnp.arange(maxL)[None, :] >= ll[:, None]
        per = optax.ctc_loss(logits, logitpad.astype(jnp.float32),
                             lab.astype(jnp.int32), labelpad.astype(jnp.float32),
                             blank_id=blank)
        if reduction == "mean":
            return jnp.mean(per / jnp.maximum(ll.astype(jnp.float32), 1.0))
        return _reduce(per, reduction)
    return apply("ctc_loss", f, log_probs, labels, input_lengths, label_lengths)


# ---- breadth additions (reference nn/functional/loss.py) ----

def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """ref loss.py gaussian_nll_loss."""
    def f(mu, y, var):
        v = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(v) + (y - mu) ** 2 / v)
        if full:
            loss = loss + 0.5 * jnp.log(2 * jnp.pi)
        if reduction == "mean":
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply("gaussian_nll_loss", f, input, label, variance)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """ref loss.py multi_margin_loss (hinge over classes)."""
    def f(x, y, *w):
        n, c = x.shape
        picked = jnp.take_along_axis(x, y[:, None].astype(jnp.int32), axis=1)
        m = jnp.maximum(0.0, margin - picked + x) ** p
        if w:
            # reference semantics: the whole sample is weighted by weight[y]
            m = m * w[0][y.astype(jnp.int32)][:, None]
        m = m.at[jnp.arange(n), y.astype(jnp.int32)].set(0.0)
        loss = jnp.sum(m, axis=1) / c
        if reduction == "mean":
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply("multi_margin_loss", f, *args)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    """ref loss.py triplet_margin_with_distance_loss."""
    from ...core.tensor import Tensor as _T

    def pairwise(a, b):
        return jnp.sqrt(jnp.sum((a - b) ** 2, axis=-1) + 1e-12)

    if distance_function is not None:
        dp = distance_function(input, positive)
        dn = distance_function(input, negative)
        if swap:
            dpn = distance_function(positive, negative)
            dn = apply("minimum", jnp.minimum, dn, dpn)
        loss = apply("triplet_hinge",
                     lambda a, b: jnp.maximum(a - b + margin, 0.0), dp, dn)
    else:
        def f(x, pos, neg):
            dp = pairwise(x, pos)
            dn = pairwise(x, neg)
            if swap:
                dn = jnp.minimum(dn, pairwise(pos, neg))
            return jnp.maximum(dp - dn + margin, 0.0)
        loss = apply("triplet_margin_with_distance", f, input, positive, negative)
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None,
                  path_code=None, is_sparse=False, name=None):
    """ref loss.py hsigmoid_loss (hierarchical sigmoid over the default
    complete binary tree when no custom path is given).

    Heap layout: internal nodes are ids [0, C-2], leaves [C-1, 2C-2] (exactly
    C-1 internal nodes for ANY class count); class c's path walks parents from
    leaf id c + C - 1 to the root, so leaf probabilities sum to 1.

    Custom tree: path_table/path_code [N, L] give each sample's node ids and
    left/right codes from leaf to root (-1 padded); each step is a binary
    cross-entropy with the code as the label (ref loss.py:916-924)."""
    import math as _m
    if (path_table is None) != (path_code is None):
        raise ValueError("path_table and path_code must be given together")
    if path_table is not None:
        def fc(x, pt, pc, w, *b):
            nodes = pt.astype(jnp.int32).reshape(x.shape[0], -1)   # [N, L]
            codes = pc.astype(jnp.int32).reshape(x.shape[0], -1)
            valid = nodes >= 0
            safe = jnp.maximum(nodes, 0)
            logits = jnp.einsum("nld,nd->nl", w[safe], x)
            if b:
                logits = logits + b[0].reshape(-1)[safe]
            # BCE(sigmoid(z), c) = softplus(z) - c*z = softplus((1-2c)*z)
            z = jnp.where(codes > 0, -logits, logits)
            return jnp.mean(jnp.sum(jnp.where(valid, jax.nn.softplus(z), 0.0),
                                    axis=1))
        args = (input, path_table, path_code, weight) + \
            ((bias,) if bias is not None else ())
        return apply("hsigmoid_loss", fc, *args)
    C = int(num_classes)
    depth = max(int(_m.ceil(_m.log2(max(C, 2)))) + 1, 1)

    def f(x, y, w, *b):
        yy = y.astype(jnp.int32).reshape(-1)
        node = yy + (C - 1)                                  # leaf id
        total = 0.0
        for _ in range(depth):
            valid = node > 0
            parent = jnp.maximum((node - 1) // 2, 0)
            is_right = (node == 2 * parent + 2)
            logits = jnp.einsum("nd,nd->n", w[parent], x)
            if b:
                logits = logits + b[0].reshape(-1)[parent]
            sign = jnp.where(is_right, -1.0, 1.0)            # left: +, right: -
            total = total + jnp.where(valid,
                                      jax.nn.softplus(-sign * logits), 0.0)
            node = jnp.where(valid, parent, 0)
        return jnp.mean(total)
    args = (input, label, weight) + ((bias,) if bias is not None else ())
    return apply("hsigmoid_loss", f, *args)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean", name=None):
    """ref loss.py margin_cross_entropy (ArcFace/CosFace family margins):
    cos(m1*theta + m2) - m3 applied to the target logit, then scaled CE."""
    # group=False is the documented "no parallelism" value (ref loss.py) — the
    # local computation below is exactly right for it; a real group means
    # vocab-sharded logits needing a distributed softmax, which a local-only
    # CE would get silently wrong
    if group not in (None, False):
        raise NotImplementedError(
            "margin_cross_entropy(group=...) (model-parallel sharded logits) "
            "is not supported; gather logits or use the compiled trainer's "
            "vocab-parallel CE (paddle_tpu/parallel/hybrid.py _vp_ce)")
    def f(lg, y):
        yi = y.astype(jnp.int32).reshape(-1)
        n = lg.shape[0]
        target = lg[jnp.arange(n), yi]
        target = jnp.clip(target, -1.0, 1.0)
        theta = jnp.arccos(target)
        adj = jnp.cos(margin1 * theta + margin2) - margin3
        lg2 = lg.at[jnp.arange(n), yi].set(adj) * scale
        lsm = jax.nn.log_softmax(lg2, axis=-1)
        loss = -lsm[jnp.arange(n), yi]
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if return_softmax:
            return loss, jnp.exp(lsm)
        return loss
    return apply("margin_cross_entropy", f, logits, label)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-T transducer loss (ref loss.py rnnt_loss / warprnnt).

    input: [B, T, U+1, V] log-probs (or logits — log_softmax applied), label
    [B, U].  Forward-variable DP in log space with lax.scan over T; the U
    recurrence runs as an inner scan (log-semiring linear recurrence).
    """
    def f(acts, lab, ilen, llen):
        lp = jax.nn.log_softmax(acts, axis=-1)
        B, T, U1, V = lp.shape
        U = U1 - 1
        lab32 = lab.astype(jnp.int32)
        blank_lp = lp[..., blank]                              # [B, T, U+1]
        # emit[b, t, u] = log p(label_u | t, u)  for u < U
        emit_lp = jnp.take_along_axis(
            lp[:, :, :U, :], lab32[:, None, :, None], axis=-1)[..., 0]  # [B,T,U]
        if fastemit_lambda:
            # FastEmit (Yu et al. 2021), warprnnt formulation: loss value is
            # unchanged but emit-transition gradients scale by (1 + lambda)
            emit_lp = emit_lp + fastemit_lambda * (
                emit_lp - jax.lax.stop_gradient(emit_lp))
        NEG = -1e30

        def row(alpha_prev, t):
            # alpha_prev [B, U+1] = alpha[t-1, :]; move right via blank from
            # above, then left-to-right emits within the row
            from_top = jnp.where(t == 0,
                                 jnp.where(jnp.arange(U1)[None] == 0, 0.0, NEG),
                                 alpha_prev + blank_lp[:, jnp.maximum(t - 1, 0), :])

            def cell(carry, u):
                # carry: alpha[t, u-1]; combine with emit into u
                left = carry + emit_lp[:, t, u - 1]
                a = jnp.logaddexp(from_top[:, u], left)
                return a, a

            a0 = from_top[:, 0]
            _, rest = jax.lax.scan(cell, a0, jnp.arange(1, U1))
            alpha_t = jnp.concatenate([a0[:, None], rest.T], axis=1)
            return alpha_t, alpha_t

        _, alphas = jax.lax.scan(row, jnp.full((B, U1), NEG), jnp.arange(T))
        alphas = jnp.moveaxis(alphas, 0, 1)                    # [B, T, U+1]
        bi = jnp.arange(B)
        tl = ilen.astype(jnp.int32) - 1
        ul = llen.astype(jnp.int32)
        ll = alphas[bi, tl, ul] + blank_lp[bi, tl, ul]
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply("rnnt_loss", f, input, label, input_lengths, label_lengths)


def class_center_sample(label, num_classes, num_samples, group=None):
    """ref common.py class_center_sample: sample negative class centers.

    Returns (remapped_label, sampled_class_indices).  Positive classes always
    kept; negatives fill up to num_samples (deterministic fill, matching the
    reference's semantics though not its RNG)."""
    import numpy as _np
    from ...core.tensor import Tensor as _T
    y = _np.asarray(label.numpy() if hasattr(label, "numpy") else label).reshape(-1)
    pos = _np.unique(y)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        neg_pool = _np.setdiff1d(_np.arange(num_classes), pos)
        rng = _np.random.RandomState(0)
        extra = rng.choice(neg_pool, size=num_samples - len(pos), replace=False)
        sampled = _np.concatenate([pos, _np.sort(extra)])
    remap = -_np.ones(num_classes, _np.int64)
    remap[sampled] = _np.arange(len(sampled))
    return (_T(jnp.asarray(remap[y].reshape(y.shape), jnp.int64)),
            _T(jnp.asarray(sampled, jnp.int64)))
