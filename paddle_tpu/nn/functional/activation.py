"""Activation functionals (reference: `python/paddle/nn/functional/activation.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import generator as _gen
from ...core.tensor import Tensor, apply


def relu(x, name=None):
    return apply("relu", jax.nn.relu, x)


def relu_(x, name=None):
    return x._inplace_from(relu(x))


def relu6(x, name=None):
    return apply("relu6", jax.nn.relu6, x)


def elu(x, alpha=1.0, name=None):
    return apply("elu", lambda a: jax.nn.elu(a, alpha), x)


def celu(x, alpha=1.0, name=None):
    return apply("celu", lambda a: jax.nn.celu(a, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply("selu", lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


def gelu(x, approximate=False, name=None):
    return apply("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), x)


def silu(x, name=None):
    return apply("silu", jax.nn.silu, x)


swish = silu


def sigmoid(x, name=None):
    return apply("sigmoid", jax.nn.sigmoid, x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply("hardsigmoid", lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x)


def hardswish(x, name=None):
    return apply("hardswish", lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply("hardtanh", lambda a: jnp.clip(a, min, max), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply("hardshrink", lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x)


def softshrink(x, threshold=0.5, name=None):
    return apply("softshrink",
                 lambda a: jnp.where(a > threshold, a - threshold,
                                     jnp.where(a < -threshold, a + threshold, 0.0)), x)


def tanhshrink(x, name=None):
    return apply("tanhshrink", lambda a: a - jnp.tanh(a), x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply("thresholded_relu", lambda a: jnp.where(a > threshold, a, value), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), x)


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)
    return apply("prelu", f, x, weight)


def rrelu(x, lower=0.125, upper=0.3333333, training=False, name=None):
    if training:
        def f(a):
            k = _gen.next_key()
            slope = jax.random.uniform(k, a.shape, a.dtype, lower, upper)
            return jnp.where(a >= 0, a, slope * a)
        return apply("rrelu", f, x)
    mid = (lower + upper) / 2.0
    return apply("rrelu", lambda a: jnp.where(a >= 0, a, mid * a), x)


def maxout(x, groups, axis=1, name=None):
    def f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        newshape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(newshape), axis=ax + 1)
    return apply("maxout", f, x)


def mish(x, name=None):
    return apply("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply("softplus",
                 lambda a: jnp.where(beta * a > threshold, a,
                                     (1.0 / beta) * jnp.log1p(jnp.exp(beta * a))), x)


def softsign(x, name=None):
    return apply("softsign", jax.nn.soft_sign, x)


def tanh(x, name=None):
    return apply("tanh", jnp.tanh, x)


def tanh_(x, name=None):
    return x._inplace_from(tanh(x))


def log_sigmoid(x, name=None):
    return apply("log_sigmoid", jax.nn.log_sigmoid, x)


def softmax(x, axis=-1, dtype=None, name=None):
    def f(a):
        if dtype is not None:
            from ...core import dtype as _dt
            a = a.astype(_dt.to_np(dtype))
        return jax.nn.softmax(a, axis=axis)
    return apply("softmax", f, x)


def softmax_(x, axis=-1, dtype=None, name=None):
    return x._inplace_from(softmax(x, axis, dtype))


def log_softmax(x, axis=-1, dtype=None, name=None):
    def f(a):
        if dtype is not None:
            from ...core import dtype as _dt
            a = a.astype(_dt.to_np(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return apply("log_softmax", f, x)


def glu(x, axis=-1, name=None):
    return apply("glu", lambda a: jax.nn.glu(a, axis=axis), x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    def f(a):
        g = jax.random.gumbel(_gen.next_key(), a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis)
            onehot = jax.nn.one_hot(idx, y.shape[axis], axis=axis, dtype=y.dtype)
            y = onehot + y - jax.lax.stop_gradient(y)
        return y
    return apply("gumbel_softmax", f, x)


# ---- in-place variants (ref activation.py elu_ etc.) ----

def elu_(x, alpha=1.0, name=None):
    return x._inplace_from(elu(x, alpha))


def hardtanh_(x, min=-1.0, max=1.0, name=None):
    return x._inplace_from(hardtanh(x, min, max))


def leaky_relu_(x, negative_slope=0.01, name=None):
    return x._inplace_from(leaky_relu(x, negative_slope))


def thresholded_relu_(x, threshold=1.0, value=0.0, name=None):
    return x._inplace_from(thresholded_relu(x, threshold, value))
