"""Convolutions (reference: `python/paddle/nn/functional/conv.py`, phi conv kernels).

All variants lower to `lax.conv_general_dilated` / `lax.conv_transpose` — a single MXU
path XLA tiles onto the systolic array, replacing the reference's cuDNN dispatch.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import apply


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    if len(v) == 1:
        return v * n
    return v


def _padding(padding, n, strides, dilations, ksize):
    """Normalise paddle padding spec -> lax padding list or string."""
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    strides = _tup(stride, n)
    dilations = _tup(dilation, n)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC", "NWC")
    if n == 1:
        dn = ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    elif n == 2:
        dn = ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    else:
        dn = ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")
    pad = _padding(padding, n, strides, dilations, None)

    def f(a, w, *rest):
        # paddle weights are [out, in/groups, *k]; lax wants layout per dn[1]
        if channel_last:
            # OIHW... -> HWIO...
            perm = tuple(range(2, 2 + n)) + (1, 0)
            w = jnp.transpose(w, perm)
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad, rhs_dilation=dilations,
            dimension_numbers=dn, feature_group_count=groups)
        if rest:
            b = rest[0]
            bshape = [1] * out.ndim
            bshape[-1 if channel_last else 1] = b.size
            out = out + b.reshape(bshape)
        return out
    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(f"conv{n}d", f, *args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC",) else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, df)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups,
                    n, data_format, output_size):
    strides = _tup(stride, n)
    dilations = _tup(dilation, n)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC", "NWC")
    if n == 1:
        dn = ("NWC", "WIO", "NWC") if channel_last else ("NCW", "IOW", "NCW")
    elif n == 2:
        dn = ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "IOHW", "NCHW")
    else:
        dn = ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "IODHW", "NCDHW")
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        pad = _padding(padding, n, strides, dilations, None)
    opad = _tup(output_padding, n) if output_padding else (0,) * n

    def f(a, w, *rest):
        # paddle transpose-conv weights: [in, out/groups, *k] (IO layout)
        if channel_last:
            perm = tuple(range(2, 2 + n)) + (0, 1)  # IOHW -> HWIO
            wt = jnp.transpose(w, perm)
        else:
            wt = w
        if groups > 1:
            # grouped transpose conv: split and concat (cold path)
            a_groups = jnp.split(a, groups, axis=-1 if channel_last else 1)
            w_groups = jnp.split(wt, groups, axis=-2 if channel_last else 0)
            outs = [_transpose_one(ag, wg, strides, pad, dilations, dn, opad)
                    for ag, wg in zip(a_groups, w_groups)]
            out = jnp.concatenate(outs, axis=-1 if channel_last else 1)
        else:
            out = _transpose_one(a, wt, strides, pad, dilations, dn, opad)
        if rest:
            b = rest[0]
            bshape = [1] * out.ndim
            bshape[-1 if channel_last else 1] = b.size
            out = out + b.reshape(bshape)
        return out
    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply(f"conv{n}d_transpose", f, *args)


def _transpose_one(a, w, strides, pad, dilations, dn, opad):
    if isinstance(pad, str):
        lax_pad = pad
    else:
        # paddle conv_transpose padding p means: out = (in-1)*s - 2p + k; lax
        # conv_transpose with padding list interprets as output cropping
        k_axes = [i for i, ch in enumerate(dn[1]) if ch not in ("I", "O")]
        ks = [w.shape[i] for i in k_axes]
        lax_pad = [(d * (k - 1) - p[0], d * (k - 1) - p[1] + op)
                   for k, p, d, op in zip(ks, pad, dilations, opad)]
    return jax.lax.conv_transpose(a, w, strides=strides, padding=lax_pad,
                                  rhs_dilation=dilations, dimension_numbers=dn,
                                  transpose_kernel=True)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, 1, df, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCHW", output_size=None, name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCDHW", output_size=None, name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, 3, data_format, output_size)
