"""Attention functionals.

`scaled_dot_product_attention` is the paddle-API entry; on TPU it routes to the Pallas
flash-attention kernel (incubate) when shapes allow, else the XLA softmax path.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.tensor import apply


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """Inputs [B, L, H, D] (paddle layout).  Reference:
    `python/paddle/nn/functional/flash_attention.py:200`."""
    from ...incubate.nn.functional import fused_dot_product_attention
    return fused_dot_product_attention(query, key, value, attn_mask=attn_mask,
                                       dropout_p=dropout_p, is_causal=is_causal,
                                       training=training)
