"""Fused softmax-mask ops (reference: `incubate/softmax_mask_fuse*`, phi
`fused_softmax_mask_kernel.cu`) — on TPU these are single XLA fusions."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import apply


def softmax_mask_fuse(x, mask, name=None):
    return apply("softmax_mask_fuse",
                 lambda a, m: jax.nn.softmax(a.astype(jnp.float32) + m.astype(jnp.float32),
                                             axis=-1).astype(a.dtype), x, mask)


def softmax_mask_fuse_upper_triangle(x):
    def f(a):
        L = a.shape[-1]
        mask = jnp.tril(jnp.ones((L, L), bool))
        scores = jnp.where(mask, a.astype(jnp.float32), -1e30)
        return jax.nn.softmax(scores, axis=-1).astype(a.dtype)
    return apply("softmax_mask_fuse_upper_triangle", f, x)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """ref sparse_attention.py: attention restricted to a CSR sparsity pattern.

    TPU-native: materializes the CSR pattern as a dense mask and runs one fused
    masked softmax-matmul — on the MXU a dense masked matmul beats gather-based
    sparse compute for the block densities this API targets."""
    import jax
    import jax.numpy as jnp
    from ...core.tensor import apply

    def f(q, k, v, off, cols):
        B, H, T, D = q.shape
        scores = jnp.einsum("bhtd,bhsd->bhts", q, k) / jnp.sqrt(
            jnp.asarray(D, q.dtype))
        # CSR -> dense mask [B, H, T, T]: nnz j belongs to row r iff
        # off[r] <= j < off[r+1]; count boundaries <= j (batched searchsorted)
        nnz = cols.shape[-1]
        j = jnp.arange(nnz)
        r = jnp.sum(j[..., None, :] >= off[..., 1:, None], axis=-2)  # [B,H,nnz]
        mask = jnp.zeros((B, H, T, T), bool)
        bi = jnp.arange(B)[:, None, None]
        hi = jnp.arange(H)[None, :, None]
        mask = mask.at[bi, hi, r, cols.astype(jnp.int32)].set(True)
        scores = jnp.where(mask, scores, -1e30)
        w = jax.nn.softmax(scores, axis=-1)
        w = jnp.where(mask, w, 0.0)
        return jnp.einsum("bhts,bhsd->bhtd", w, v)
    return apply("sparse_attention", f, query, key, value, sparse_csr_offset,
                 sparse_csr_columns)
