"""Fused softmax-mask ops (reference: `incubate/softmax_mask_fuse*`, phi
`fused_softmax_mask_kernel.cu`) — on TPU these are single XLA fusions."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import apply


def softmax_mask_fuse(x, mask, name=None):
    return apply("softmax_mask_fuse",
                 lambda a, m: jax.nn.softmax(a.astype(jnp.float32) + m.astype(jnp.float32),
                                             axis=-1).astype(a.dtype), x, mask)


def softmax_mask_fuse_upper_triangle(x):
    def f(a):
        L = a.shape[-1]
        mask = jnp.tril(jnp.ones((L, L), bool))
        scores = jnp.where(mask, a.astype(jnp.float32), -1e30)
        return jax.nn.softmax(scores, axis=-1).astype(a.dtype)
    return apply("softmax_mask_fuse_upper_triangle", f, x)
