"""Flash attention API (reference: `python/paddle/nn/functional/flash_attention.py`).

The reference wraps the flashattn CUDA library; here the hot path is a Pallas TPU
flash-attention kernel (`paddle_tpu/incubate/kernels/flash_attention.py`) with an XLA
fallback on CPU.  Layout: [batch, seqlen, nheads, headdim] exactly like the reference.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor, apply


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    fixed_seed_offset=None, rng_name="", training=True,
                    segment_ids=None, name=None):
    """segment_ids [B, S] (TPU-native varlen form): when given, tokens attend
    only within their own segment via the Pallas varlen kernel."""
    if segment_ids is not None:
        seg = segment_ids._data if isinstance(segment_ids, Tensor) \
            else jnp.asarray(segment_ids)
        if dropout == 0.0:
            from ...incubate.kernels.flash_attention import \
                flash_attention_varlen
            out = apply("flash_attention_varlen",
                        lambda q, k, v: flash_attention_varlen(q, k, v, seg,
                                                               causal=causal),
                        query, key, value)
        else:
            # dropout path: segment mask through the composed XLA attention
            from ...incubate.kernels.flash_attention import attention_xla
            from ...core import generator as _gen
            key_ = _gen.next_key() if training else None
            mask = (seg[:, None, :, None] == seg[:, None, None, :])
            out = apply("flash_attention_seg_dropout",
                        lambda q, k, v: attention_xla(
                            q, k, v, mask=mask, causal=causal,
                            dropout_p=dropout if training else 0.0,
                            dropout_key=key_),
                        query, key, value)
        return out, None
    from ...incubate.nn.functional import fused_dot_product_attention
    out = fused_dot_product_attention(query, key, value, attn_mask=None,
                                      dropout_p=dropout, is_causal=causal,
                                      training=training)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                        max_seqlen_k, scale, dropout=0.0, causal=False,
                        return_softmax=False, fixed_seed_offset=None, rng_name="",
                        training=True, name=None):
    """Varlen flash attention: total-token packed layout [total, H, D] with cumulative
    sequence offsets (reference `flash_attn_unpadded`).  Implemented by segment-masked
    attention over the packed dimension — static shapes, so it stays jittable.
    On TPU with aligned shapes the Pallas varlen kernel runs; otherwise the XLA
    composed path."""
    from ...incubate.kernels.flash_attention import (_on_tpu,
                                                     flash_attention_varlen)

    def kernel_path(q, k, v, cu_q, cu_k):
        total_q, H, D = q.shape
        total_k = k.shape[0]
        nseq = cu_q.shape[0] - 1
        pad_q = (-total_q) % 128
        pad_k = (-total_k) % 128
        seg_q = jnp.searchsorted(cu_q[1:], jnp.arange(total_q), side="right")
        seg_k = jnp.searchsorted(cu_k[1:], jnp.arange(total_k), side="right")
        # pad tokens get segment ids that never match -> attend nothing
        seg_qp = jnp.concatenate([seg_q, jnp.full((pad_q,), nseq + 1,
                                                  seg_q.dtype)])[None]
        seg_kp = jnp.concatenate([seg_k, jnp.full((pad_k,), nseq + 2,
                                                  seg_k.dtype)])[None]
        qp = jnp.pad(q, ((0, pad_q), (0, 0), (0, 0)))[None]
        kp = jnp.pad(k, ((0, pad_k), (0, 0), (0, 0)))[None]
        vp = jnp.pad(v, ((0, pad_k), (0, 0), (0, 0)))[None]
        out = flash_attention_varlen(qp, kp, vp, seg_qp, seg_kp,
                                     causal=causal, scale=scale)
        return out[0, :total_q]

    def f(q, k, v, cu_q, cu_k):
        total_q = q.shape[0]
        total_k = k.shape[0]
        # segment id per token from cumulative offsets
        seg_q = jnp.searchsorted(cu_q[1:], jnp.arange(total_q), side="right")
        seg_k = jnp.searchsorted(cu_k[1:], jnp.arange(total_k), side="right")
        scores = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * scale
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(total_q) - jnp.take(cu_q, seg_q)
            pos_k = jnp.arange(total_k) - jnp.take(cu_k, seg_k)
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        scores = jnp.where(mask[None], scores, -1e30)
        p = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
        p = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
        out = jnp.einsum("hqk,khd->qhd", p, v.astype(jnp.float32))
        return out.astype(q.dtype)

    import numpy as _np
    D = (query._data if isinstance(query, Tensor) else query).shape[-1]
    cuq = cu_seqlens_q._data if isinstance(cu_seqlens_q, Tensor) else cu_seqlens_q
    cuk = cu_seqlens_k._data if isinstance(cu_seqlens_k, Tensor) else cu_seqlens_k
    # the kernel masks causality in packed-global coordinates, which equals the
    # reference's per-segment local causality only for self-attention layouts
    same_layout = _np.array_equal(_np.asarray(cuq), _np.asarray(cuk))
    use_kernel = _on_tpu() and D in (64, 128, 256) and dropout == 0.0 and \
        (same_layout or not causal)
    out = apply("flash_attn_unpadded", kernel_path if use_kernel else f,
                query, key, value, cu_seqlens_q, cu_seqlens_k)
    return out, None
