"""Flash attention API (reference: `python/paddle/nn/functional/flash_attention.py`).

The reference wraps the flashattn CUDA library; here the hot path is a Pallas TPU
flash-attention kernel (`paddle_tpu/incubate/kernels/flash_attention.py`) with an XLA
fallback on CPU.  Layout: [batch, seqlen, nheads, headdim] exactly like the reference.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor, apply


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    fixed_seed_offset=None, rng_name="", training=True, name=None):
    from ...incubate.nn.functional import fused_dot_product_attention
    out = fused_dot_product_attention(query, key, value, attn_mask=None,
                                      dropout_p=dropout, is_causal=causal,
                                      training=training)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                        max_seqlen_k, scale, dropout=0.0, causal=False,
                        return_softmax=False, fixed_seed_offset=None, rng_name="",
                        training=True, name=None):
    """Varlen flash attention: total-token packed layout [total, H, D] with cumulative
    sequence offsets (reference `flash_attn_unpadded`).  Implemented by segment-masked
    attention over the packed dimension — static shapes, so it stays jittable."""
    def f(q, k, v, cu_q, cu_k):
        total_q = q.shape[0]
        total_k = k.shape[0]
        # segment id per token from cumulative offsets
        seg_q = jnp.searchsorted(cu_q[1:], jnp.arange(total_q), side="right")
        seg_k = jnp.searchsorted(cu_k[1:], jnp.arange(total_k), side="right")
        scores = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * scale
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(total_q) - jnp.take(cu_q, seg_q)
            pos_k = jnp.arange(total_k) - jnp.take(cu_k, seg_k)
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        scores = jnp.where(mask[None], scores, -1e30)
        p = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
        p = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
        out = jnp.einsum("hqk,khd->qhd", p, v.astype(jnp.float32))
        return out.astype(q.dtype)
    out = apply("flash_attn_unpadded", f, query, key, value, cu_seqlens_q, cu_seqlens_k)
    return out, None
