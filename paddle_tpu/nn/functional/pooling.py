"""Pooling functionals (reference: `python/paddle/nn/functional/pooling.py`).

All pooling lowers to `lax.reduce_window` — XLA's native window reduction.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import apply, _to_data


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    return v * n if len(v) == 1 else v


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    p = list(padding)
    if len(p) == n:
        return [(int(v), int(v)) for v in p]
    if len(p) == 2 * n:
        return [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _pool(x, ksize, stride, padding, n, reducer, init, data_format, ceil_mode=False,
          count_include_pad=True, divisor_override=None, name="pool"):
    k = _tup(ksize, n)
    s = _tup(stride if stride is not None else ksize, n)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC", "NWC")
    pad = _pads(padding, n)

    def f(a):
        if channel_last:
            dims = (1,) + k + (1,)
            strides = (1,) + s + (1,)
            spatial = list(range(1, 1 + n))
        else:
            dims = (1, 1) + k
            strides = (1, 1) + s
            spatial = list(range(2, 2 + n))
        if isinstance(pad, str):
            padding_cfg = pad
        else:
            full = [(0, 0)] * a.ndim
            for i, ax in enumerate(spatial):
                lo, hi = pad[i]
                if ceil_mode:
                    size = a.shape[ax]
                    out = -(-(size + lo + hi - k[i]) // s[i]) + 1
                    need = (out - 1) * s[i] + k[i] - size - lo
                    hi = max(hi, need)
                full[ax] = (lo, hi)
            padding_cfg = full
        if reducer == "max":
            out = jax.lax.reduce_window(a, -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
                                        else jnp.iinfo(a.dtype).min,
                                        jax.lax.max, dims, strides, padding_cfg)
            return out
        # avg pooling: sum then divide by count
        summed = jax.lax.reduce_window(a.astype(jnp.float32), 0.0, jax.lax.add, dims,
                                       strides, padding_cfg)
        if divisor_override:
            return (summed / divisor_override).astype(a.dtype)
        if count_include_pad and not isinstance(padding_cfg, str):
            denom = float(np.prod(k))
            return (summed / denom).astype(a.dtype)
        ones = jnp.ones(a.shape, jnp.float32)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides, padding_cfg)
        return (summed / counts).astype(a.dtype)
    return apply(name, f, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    out = _pool(x, kernel_size, stride, padding, 1, "max", None, df, ceil_mode,
                name="max_pool1d")
    return (out, _pool_mask(x, out, kernel_size, stride, padding, 1)) if return_mask else out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, "max", None, data_format, ceil_mode,
                name="max_pool2d")
    return (out, _pool_mask(x, out, kernel_size, stride, padding, 2)) if return_mask else out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, "max", None, data_format, ceil_mode,
                name="max_pool3d")
    return (out, _pool_mask(x, out, kernel_size, stride, padding, 3)) if return_mask else out


def _pool_mask(x, out, ksize, stride, padding, n):
    """Argmax indices for return_mask (flat per-window index, paddle convention)."""
    data = _to_data(x)
    k = _tup(ksize, n)
    s = _tup(stride if stride is not None else ksize, n)
    pad = _pads(padding, n)
    # build via unfold-style patch extraction (cold path, used by unpool)
    if n != 2:
        return out  # mask only supported for 2d (reference GPU kernel also 2d-centric)
    kh, kw = k
    sh, sw = s
    ph, pw = (pad[0][0], pad[1][0]) if not isinstance(pad, str) else (0, 0)
    a = jnp.pad(data, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                constant_values=-jnp.inf)
    patches = jax.lax.conv_general_dilated_patches(
        a, (kh, kw), (sh, sw), "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"))
    nb, ckk, oh, ow = patches.shape
    c = data.shape[1]
    patches = patches.reshape(nb, c, kh * kw, oh, ow)
    idx = jnp.argmax(patches, axis=2)
    # convert window index -> flat input index (paddle mask convention)
    wi = idx // kw
    wj = idx % kw
    rows = (jnp.arange(oh).reshape(1, 1, -1, 1) * sh - ph) + wi
    cols = (jnp.arange(ow).reshape(1, 1, 1, -1) * sw - pw) + wj
    flat = rows * data.shape[3] + cols
    from ...core.tensor import Tensor
    return Tensor(flat.astype(jnp.int32))


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False,
               data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool(x, kernel_size, stride, padding, 1, "avg", None, df, ceil_mode,
                 count_include_pad=not exclusive, name="avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", None, data_format, ceil_mode,
                 count_include_pad=not exclusive, divisor_override=divisor_override,
                 name="avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", None, data_format, ceil_mode,
                 count_include_pad=not exclusive, divisor_override=divisor_override,
                 name="avg_pool3d")


def _adaptive(x, output_size, n, mode, data_format):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    osize = _tup(output_size, n)

    def f(a):
        spatial = list(range(1, 1 + n)) if channel_last else list(range(2, 2 + n))
        out = a
        for i, ax in enumerate(spatial):
            if osize[i] is None:
                continue
            out = _adaptive_1axis(out, ax, int(osize[i]), mode)
        return out
    return apply(f"adaptive_{mode}_pool{n}d", f, x)


def _adaptive_1axis(a, axis, out_size, mode):
    in_size = a.shape[axis]
    if in_size % out_size == 0:
        k = in_size // out_size
        shape = list(a.shape)
        shape[axis:axis + 1] = [out_size, k]
        r = a.reshape(shape)
        return jnp.max(r, axis=axis + 1) if mode == "max" else jnp.mean(r, axis=axis + 1)
    # uneven: per-output-bin reduce (static unrolled; output sizes are small)
    starts = [int(np.floor(i * in_size / out_size)) for i in range(out_size)]
    ends = [int(np.ceil((i + 1) * in_size / out_size)) for i in range(out_size)]
    pieces = []
    for s, e in zip(starts, ends):
        sl = [slice(None)] * a.ndim
        sl[axis] = slice(s, e)
        seg = a[tuple(sl)]
        red = jnp.max(seg, axis=axis, keepdims=True) if mode == "max" \
            else jnp.mean(seg, axis=axis, keepdims=True)
        pieces.append(red)
    return jnp.concatenate(pieces, axis=axis)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 1, "max", "NCL")
    return (out, out) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 2, "max", "NCHW")
    return (out, out) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 3, "max", "NCDHW")
    return (out, out) if return_mask else out


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
    k = _tup(kernel_size, 2)
    s = _tup(stride if stride is not None else kernel_size, 2)

    def f(a, idx):
        n, c, h, w = a.shape
        if output_size is not None:
            oh, ow = _tup(output_size, 2)[-2:]
        else:
            oh = (h - 1) * s[0] + k[0] - 2 * (padding if isinstance(padding, int) else 0)
            ow = (w - 1) * s[1] + k[1] - 2 * (padding if isinstance(padding, int) else 0)
        out = jnp.zeros((n, c, oh * ow), a.dtype)
        flat_idx = idx.reshape(n, c, -1).astype(jnp.int32)
        vals = a.reshape(n, c, -1)
        ni = jnp.arange(n).reshape(-1, 1, 1)
        ci = jnp.arange(c).reshape(1, -1, 1)
        out = out.at[ni, ci, flat_idx].set(vals)
        return out.reshape(n, c, oh, ow)
    return apply("max_unpool2d", f, x, indices)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """ref pooling.py max_unpool1d: scatter values back to argmax positions."""
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = (stride if isinstance(stride, int) else stride[0]) if stride is not None else k
    p = padding if isinstance(padding, int) else padding[0]

    def f(a, idx):
        n, c, l = a.shape
        ol = (output_size[-1] if output_size is not None
              else (l - 1) * s + k - 2 * p)
        out = jnp.zeros((n, c, ol), a.dtype)
        ni = jnp.arange(n).reshape(-1, 1, 1)
        ci = jnp.arange(c).reshape(1, -1, 1)
        return out.at[ni, ci, idx.astype(jnp.int32)].set(a)
    return apply("max_unpool1d", f, x, indices)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """ref pooling.py max_unpool3d."""
    k = _tup(kernel_size, 3)
    s = _tup(stride if stride is not None else kernel_size, 3)
    p = padding if isinstance(padding, int) else 0

    def f(a, idx):
        n, c, d, h, w = a.shape
        if output_size is not None:
            od, oh, ow = _tup(output_size, 3)[-3:]
        else:
            od = (d - 1) * s[0] + k[0] - 2 * p
            oh = (h - 1) * s[1] + k[1] - 2 * p
            ow = (w - 1) * s[2] + k[2] - 2 * p
        out = jnp.zeros((n, c, od * oh * ow), a.dtype)
        flat_idx = idx.reshape(n, c, -1).astype(jnp.int32)
        vals = a.reshape(n, c, -1)
        ni = jnp.arange(n).reshape(-1, 1, 1)
        ci = jnp.arange(c).reshape(1, -1, 1)
        out = out.at[ni, ci, flat_idx].set(vals)
        return out.reshape(n, c, od, oh, ow)
    return apply("max_unpool3d", f, x, indices)
