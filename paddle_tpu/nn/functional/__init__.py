from .activation import (celu, elu, gelu, glu, gumbel_softmax, hardshrink,  # noqa
                         hardsigmoid, hardswish, hardtanh, leaky_relu, log_sigmoid,
                         log_softmax, maxout, mish, prelu, relu, relu6, relu_, rrelu,
                         selu, sigmoid, silu, softmax, softmax_, softplus, softshrink,
                         softsign, swish, tanh, tanh_, tanhshrink, thresholded_relu,
                         elu_, hardtanh_, leaky_relu_, thresholded_relu_)
from .common import (alpha_dropout, bilinear, cosine_similarity, dropout, dropout2d,  # noqa
                     dropout3d, interpolate, label_smooth, linear, one_hot, pad,
                     unfold, fold, upsample, zeropad2d)
from .conv import conv1d, conv1d_transpose, conv2d, conv2d_transpose, conv3d, conv3d_transpose  # noqa
from .extension import (diag_embed, gather_tree, sequence_mask, temporal_shift)  # noqa
from .input import embedding, one_hot as _one_hot_input  # noqa
from .loss import (binary_cross_entropy, binary_cross_entropy_with_logits,  # noqa
                   cross_entropy, ctc_loss, dice_loss, hinge_embedding_loss, kl_div,
                   l1_loss, log_loss, margin_ranking_loss, mse_loss, nll_loss,
                   npair_loss, poisson_nll_loss, sigmoid_focal_loss, smooth_l1_loss,
                   softmax_with_cross_entropy, square_error_cost, triplet_margin_loss,
                   cosine_embedding_loss, multi_label_soft_margin_loss, soft_margin_loss,
                   gaussian_nll_loss, hsigmoid_loss, multi_margin_loss,
                   triplet_margin_with_distance_loss, margin_cross_entropy,
                   rnnt_loss, class_center_sample)
from .norm import batch_norm, group_norm, instance_norm, layer_norm, local_response_norm, normalize  # noqa
from .pooling import (adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,  # noqa
                      adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d,
                      avg_pool1d, avg_pool2d, avg_pool3d, max_pool1d, max_pool2d,
                      max_pool3d, max_unpool1d, max_unpool2d, max_unpool3d)
from .attention import scaled_dot_product_attention  # noqa
from .flash_attention import flash_attention, flash_attn_unpadded  # noqa
from .vision import affine_grid, grid_sample, pixel_shuffle, pixel_unshuffle, channel_shuffle  # noqa
from .distance import pairwise_distance  # noqa
from .sparse_ops import (softmax_mask_fuse, softmax_mask_fuse_upper_triangle,  # noqa
                         sparse_attention)
