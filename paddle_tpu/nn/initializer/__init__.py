"""Initializers (reference: `python/paddle/nn/initializer/` — 12 initializers).

Each initializer is a callable applied to a Parameter in place, drawing from the default
generator so `paddle.seed` reproduces the reference's determinism contract.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import generator as _gen
from ...core.tensor import Tensor


class Initializer:
    def __call__(self, param, block=None):
        raise NotImplementedError

    def _set(self, param, data):
        param._data = data.astype(param._data.dtype)


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, param, block=None):
        self._set(param, jnp.full(param._data.shape, self.value, jnp.float32))


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, param, block=None):
        v = value = self.value
        if isinstance(value, Tensor):
            v = value._data
        self._set(param, jnp.asarray(np.asarray(v)))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, param, block=None):
        self._set(param, jax.random.uniform(_gen.next_key(), param._data.shape,
                                            jnp.float32, self.low, self.high))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, param, block=None):
        z = jax.random.normal(_gen.next_key(), param._data.shape, jnp.float32)
        self._set(param, self.mean + self.std * z)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, param, block=None):
        z = jax.random.truncated_normal(_gen.next_key(), self.a, self.b,
                                        param._data.shape, jnp.float32)
        self._set(param, self.mean + self.std * z)


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        fi, fo = _fans(param._data.shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        self._set(param, jax.random.uniform(_gen.next_key(), param._data.shape,
                                            jnp.float32, -limit, limit))


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        fi, fo = _fans(param._data.shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        z = jax.random.normal(_gen.next_key(), param._data.shape, jnp.float32)
        self._set(param, std * z)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _gain(self):
        if self.nonlinearity == "relu":
            return math.sqrt(2.0)
        if self.nonlinearity == "leaky_relu":
            return math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        return 1.0

    def __call__(self, param, block=None):
        fi, _ = _fans(param._data.shape)
        fi = self.fan_in or fi
        limit = self._gain() * math.sqrt(3.0 / fi)
        self._set(param, jax.random.uniform(_gen.next_key(), param._data.shape,
                                            jnp.float32, -limit, limit))


class KaimingNormal(KaimingUniform):
    def __call__(self, param, block=None):
        fi, _ = _fans(param._data.shape)
        fi = self.fan_in or fi
        std = self._gain() / math.sqrt(fi)
        z = jax.random.normal(_gen.next_key(), param._data.shape, jnp.float32)
        self._set(param, std * z)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, param, block=None):
        shape = param._data.shape
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = (max(rows, cols), min(rows, cols))
        a = jax.random.normal(_gen.next_key(), flat, jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        self._set(param, self.gain * q[:rows, :cols].reshape(shape))


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, param, block=None):
        shape = param._data.shape
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        per = oc // self.groups
        for g in range(self.groups):
            for i in range(min(per, ic)):
                idx = (g * per + i, i) + tuple(s // 2 for s in shape[2:])
                out[idx] = 1.0
        self._set(param, jnp.asarray(out))


class Bilinear(Initializer):
    def __call__(self, param, block=None):
        shape = param._data.shape
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects 4-D conv weight")
        kh, kw = shape[2], shape[3]
        fh = (kh + 1) // 2
        ch = (kh - 1) / (2.0 * fh) if kh % 2 == 1 else (kh) / (2.0 * fh) - 0.5
        yy = (1 - np.abs(np.arange(kh) / fh - ch))
        fw = (kw + 1) // 2
        cw = (kw - 1) / (2.0 * fw) if kw % 2 == 1 else (kw) / (2.0 * fw) - 0.5
        xx = (1 - np.abs(np.arange(kw) / fw - cw))
        filt = np.outer(yy, xx).astype(np.float32)
        out = np.zeros(shape, np.float32)
        for i in range(shape[0]):
            out[i, min(i, shape[1] - 1)] = filt
        self._set(param, jnp.asarray(out))


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0, "conv3d": 1.0,
             "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
             "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             "selu": 3.0 / 4}
    return gains.get(nonlinearity, 1.0)


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


_global_weight_init = None
_global_bias_init = None
