"""Gradient clipping (reference: `python/paddle/nn/clip.py` — ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)

    def _dygraph_clip(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or getattr(p, "_need_clip", True) is False:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max),
                                  stop_gradient=True)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._data * scale).astype(g._data.dtype),
                                  stop_gradient=True)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip.  In hybrid-parallel runs HybridParallelOptimizer wraps this to
    reduce the squared norms across TP/PP groups first (reference
    `hybrid_parallel_optimizer.py:251`)."""

    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _dygraph_clip(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None or getattr(p, "_need_clip", True) is False:
                continue
            sq.append(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        global_norm = self._reduce_global_norm_sq(global_norm)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or getattr(p, "_need_clip", True) is False:
                out.append((p, g))
                continue
            out.append((p, Tensor((g._data * scale).astype(g._data.dtype),
                                  stop_gradient=True)))
        return out

    def _reduce_global_norm_sq(self, global_norm):
        # hook point for hybrid-parallel cross-group reduction
        return global_norm


GradientClipBase = ClipGradBase
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm
