"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference parity: `python/paddle/nn/decode.py` (Decoder/BeamSearchDecoder,
dynamic_decode loop).  TPU-native: the decode loop runs eagerly step by step
(each step's cell is jit-compiled by the eager dispatch); beam bookkeeping is
vectorized jnp — no data-dependent Python branching inside a step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply


class Decoder:
    """Abstract decoder interface (ref nn/decode.py Decoder)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search over a step cell (ref nn/decode.py BeamSearchDecoder).

    cell: callable (inputs [B*W, D], states) -> (logits [B*W, V], new_states)
    embedding_fn maps token ids -> embeddings.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        d = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        tiled = jnp.repeat(d[:, None], beam_size, axis=1)
        return Tensor(tiled.reshape((-1,) + d.shape[1:]))

    def initialize(self, initial_cell_states):
        states = initial_cell_states
        flat = jax.tree_util.tree_leaves(states)
        B = (flat[0]._data.shape[0] if isinstance(flat[0], Tensor)
             else jnp.asarray(flat[0]).shape[0]) // self.beam_size
        W = self.beam_size
        tokens = jnp.full((B, W), self.start_token, jnp.int64)
        # only beam 0 is live initially
        log_probs = jnp.where(jnp.arange(W)[None] == 0, 0.0, -1e9) * jnp.ones((B, 1))
        finished = jnp.zeros((B, W), bool)
        return tokens, (states, log_probs, finished)

    def step(self, time, tokens, state):
        cell_states, log_probs, finished = state
        B, W = tokens.shape
        inp = Tensor(tokens.reshape(-1))
        if self.embedding_fn is not None:
            inp = self.embedding_fn(inp)
        logits, new_states = self.cell(inp, cell_states)
        ldata = logits._data if isinstance(logits, Tensor) else jnp.asarray(logits)
        if self.output_fn is not None:
            ldata = self.output_fn(Tensor(ldata))._data
        V = ldata.shape[-1]
        step_lp = jax.nn.log_softmax(ldata.astype(jnp.float32), -1).reshape(B, W, V)
        # finished beams only extend with end_token at no cost
        pen = jnp.full((V,), -1e9).at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[..., None], pen[None, None], step_lp)
        total = log_probs[..., None] + step_lp                   # [B, W, V]
        flat = total.reshape(B, W * V)
        top_lp, top_ix = jax.lax.top_k(flat, W)                  # [B, W]
        beam_ix = top_ix // V
        tok = (top_ix % V).astype(jnp.int64)
        new_finished = jnp.take_along_axis(finished, beam_ix, axis=1) | \
            (tok == self.end_token)

        def reorder(leaf):
            d = leaf._data if isinstance(leaf, Tensor) else jnp.asarray(leaf)
            d = d.reshape((B, W) + d.shape[1:])
            d = jnp.take_along_axis(
                d, beam_ix.reshape((B, W) + (1,) * (d.ndim - 2)), axis=1)
            return Tensor(d.reshape((B * W,) + d.shape[2:]))
        new_states = jax.tree_util.tree_map(
            reorder, new_states,
            is_leaf=lambda x: isinstance(x, Tensor))
        return tok, (new_states, top_lp, new_finished), new_finished

    def finalize(self, outputs, final_state, seq_lens):
        return outputs, final_state


def dynamic_decode(decoder, inits=None, max_step_num=100, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Run decoder to completion (ref nn/decode.py dynamic_decode)."""
    tokens, state = decoder.initialize(inits)
    outs = []
    lengths = None
    done = None
    for t in range(max_step_num):
        tokens, state, finished = decoder.step(t, tokens, state)
        outs.append(tokens)
        if lengths is None:
            lengths = jnp.full(finished.shape, t + 1, jnp.int64)
            done = finished
        else:
            # beams not yet done extend to the current step; done beams freeze
            lengths = jnp.where(done, lengths, t + 1)
            done = done | finished
        if bool(jnp.all(finished)):
            break
    stacked = jnp.stack(outs, axis=0 if output_time_major else 1)
    out_t = Tensor(stacked)
    if return_length:
        return out_t, state, Tensor(lengths)
    return out_t, state
