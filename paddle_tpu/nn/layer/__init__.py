from .layers import Layer  # noqa
from .activation import *  # noqa
from .common import *  # noqa
from .container import LayerDict, LayerList, ParameterList, Sequential  # noqa
from .conv import (Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D,  # noqa
                   Conv3DTranspose)
from .loss import *  # noqa
from .norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm,  # noqa
                   InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, LayerNorm,
                   LocalResponseNorm, SpectralNorm, SyncBatchNorm)
from .pooling import *  # noqa
from .rnn import (GRU, GRUCell, LSTM, LSTMCell, RNN, BiRNN, RNNCellBase, SimpleRNN,  # noqa
                  SimpleRNNCell)
from .transformer import (MultiHeadAttention, Transformer, TransformerDecoder,  # noqa
                          TransformerDecoderLayer, TransformerEncoder,
                          TransformerEncoderLayer)
from .vision import ChannelShuffle, PixelShuffle, PixelUnshuffle  # noqa
