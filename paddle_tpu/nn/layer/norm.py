"""Norm layers (reference: `python/paddle/nn/layer/norm.py`)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant
from .layers import Layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=self._normalized_shape, attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = "NHWC" if data_format in ("NHWC", "NLC", "NDHWC") else "NCHW"
        self._use_global_stats = use_global_stats
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_features], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features], jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features], jnp.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format,
                            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    """Legacy paddle.nn.BatchNorm (acts on given num_channels)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", **kw):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == "relu":
            out = F.relu(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN.  Under GSPMD/jit the batch axis is globally sharded, so the
    mean/var reduction is already global (XLA inserts the collective); eager falls back
    to local stats (single-chip).  Reference: `nn/layer/norm.py` SyncBatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            out = cls(layer._num_features, layer._momentum, layer._epsilon,
                      None, None, layer._data_format)
            out.weight = layer.weight
            out.bias = layer.bias
            out._mean = layer._mean
            out._variance = layer._variance
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_channels], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias,
                            self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_features], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Spectral normalization of a weight (power iteration)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        import jax
        from ...core import generator as _gen
        self.register_buffer("weight_u", Tensor(
            jax.random.normal(_gen.next_key(), (h,), jnp.float32)))
        self.register_buffer("weight_v", Tensor(
            jax.random.normal(_gen.next_key(), (w,), jnp.float32)))

    def forward(self, weight):
        from ...core.tensor import apply
        dim, iters, eps = self._dim, self._power_iters, self._eps
        u0 = self.weight_u._data
        v0 = self.weight_v._data

        def f(w):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma
        out = apply("spectral_norm", f, weight)
        return out
