"""Conv layers (reference: `python/paddle/nn/layer/conv.py`)."""
from __future__ import annotations

import numpy as np

from .. import functional as F
from ..initializer import KaimingUniform, Uniform
from .layers import Layer


class _ConvND(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n, transpose=False,
                 stride=1, padding=0, output_padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NCHW"):
        super().__init__()
        self._n = n
        self._transpose = transpose
        self._stride = stride
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        k = (kernel_size,) * n if isinstance(kernel_size, int) else tuple(kernel_size)
        if transpose:
            wshape = [in_channels, out_channels // groups] + list(k)
        else:
            wshape = [out_channels, in_channels // groups] + list(k)
        fan_in = in_channels * int(np.prod(k)) // groups
        self.weight = self.create_parameter(
            shape=wshape, attr=weight_attr, default_initializer=KaimingUniform())
        bound = 1.0 / np.sqrt(fan_in)
        self.bias = self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-bound, bound)) if bias_attr is not False else None

    def forward(self, x):
        if self._transpose:
            fn = [F.conv1d_transpose, F.conv2d_transpose, F.conv3d_transpose][self._n - 1]
            return fn(x, self.weight, self.bias, stride=self._stride,
                      padding=self._padding, output_padding=self._output_padding,
                      groups=self._groups, dilation=self._dilation,
                      data_format=self._data_format)
        fn = [F.conv1d, F.conv2d, F.conv3d][self._n - 1]
        return fn(x, self.weight, self.bias, stride=self._stride, padding=self._padding,
                  dilation=self._dilation, groups=self._groups,
                  data_format=self._data_format)


class Conv1D(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, False, stride,
                         padding, 0, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv2D(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, False, stride,
                         padding, 0, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv3D(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, False, stride,
                         padding, 0, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv1DTranspose(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, True, stride,
                         padding, output_padding, dilation, groups, "zeros",
                         weight_attr, bias_attr, data_format)


class Conv2DTranspose(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, True, stride,
                         padding, output_padding, dilation, groups, "zeros",
                         weight_attr, bias_attr, data_format)


class Conv3DTranspose(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, True, stride,
                         padding, output_padding, dilation, groups, "zeros",
                         weight_attr, bias_attr, data_format)
