"""Activation layers (reference: `python/paddle/nn/layer/activation.py`)."""
from __future__ import annotations

from .. import functional as F
from ..initializer import Constant
from .layers import Layer


def _mk(fname, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = dict(fixed)
            # positional args map onto the functional's named params in order
            fn = getattr(F, fname)
            import inspect
            params = [p for p in inspect.signature(fn).parameters if p not in ("x", "name")]
            for i, a in enumerate(args):
                self._kwargs[params[i]] = a
            for k, v in kwargs.items():
                if k != "name":
                    self._kwargs[k] = v

        def forward(self, x):
            return getattr(F, fname)(x, **self._kwargs)
    _Act.__name__ = fname
    return _Act


CELU = _mk("celu")
ELU = _mk("elu")
GELU = _mk("gelu")
Hardshrink = _mk("hardshrink")
Hardsigmoid = _mk("hardsigmoid")
Hardswish = _mk("hardswish")
Hardtanh = _mk("hardtanh")
LeakyReLU = _mk("leaky_relu")
LogSigmoid = _mk("log_sigmoid")
LogSoftmax = _mk("log_softmax")
Maxout = _mk("maxout")
Mish = _mk("mish")
ReLU = _mk("relu")
ReLU6 = _mk("relu6")
RReLU = _mk("rrelu")
SELU = _mk("selu")
Sigmoid = _mk("sigmoid")
Silu = _mk("silu")
Softmax = _mk("softmax")
Softplus = _mk("softplus")
Softshrink = _mk("softshrink")
Softsign = _mk("softsign")
Swish = _mk("swish")
Tanh = _mk("tanh")
Tanhshrink = _mk("tanhshrink")
ThresholdedReLU = _mk("thresholded_relu")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)


class Softmax2D(Layer):
    """ref activation.py Softmax2D: softmax over channel dim of NCHW/CHW."""

    def forward(self, x):
        assert x.ndim in (3, 4), f"Softmax2D expects 3D/4D input, got {x.ndim}D"
        return F.softmax(x, axis=-3)
