"""RNN layers (reference: `python/paddle/nn/layer/rnn.py` — SimpleRNN/LSTM/GRU + cells).

TPU-native design: the time loop is a `lax.scan` inside one traced op, so the whole
sequence compiles to a single fused XLA while-loop (the reference dispatches per-step
kernels or cuDNN). Parameters follow paddle layout: weight_ih [gates*H, I],
weight_hh [gates*H, H].
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply
from .. import functional as F
from ..initializer import Uniform
from .layers import Layer


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0,
                           batch_dim_idx=0):
        from ...ops.creation import full
        B = batch_ref.shape[batch_dim_idx]
        st = self.state_shape
        if isinstance(st[0], (list, tuple)):
            return tuple(full([B] + list(s), init_value) for s in st)
        return full([B] + list(st), init_value)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr, is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr, is_bias=True,
                                             default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def f(x, h, wi, wh, bi, bh):
            out = act(x @ wi.T + bi + h @ wh.T + bh)
            return out
        h = apply("simple_rnn_cell", f, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh)
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None,
                 proj_size=0):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h0, c0 = states

        def f(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, fg, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            fg = jax.nn.sigmoid(fg)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = fg * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        h, c = apply("lstm_cell", f, inputs, h0, c0, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh)
        return h, (h, c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def f(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h
        h = apply("gru_cell", f, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh)
        return h, h


class RNN(Layer):
    """Wraps a cell into a sequence scan (reference `nn/layer/rnn.py` RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        # python-level scan over Tensor ops (keeps tape semantics in eager)
        from ...ops.manipulation import stack
        axis = 0 if self.time_major else 1
        steps = inputs.shape[axis]
        states = initial_states
        outs = []
        rng = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        for tstep in rng:
            x = inputs[:, tstep] if axis == 1 else inputs[tstep]
            out, states = self.cell(x, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        seq = stack(outs, axis=axis)
        return seq, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.manipulation import concat
        st_fw, st_bw = (initial_states if initial_states is not None else (None, None))
        out_fw, s_fw = self.rnn_fw(inputs, st_fw, sequence_length)
        out_bw, s_bw = self.rnn_bw(inputs, st_bw, sequence_length)
        return concat([out_fw, out_bw], axis=-1), (s_fw, s_bw)


class _RNNBase(Layer):
    """Multi-layer (bi)directional RNN driven by lax.scan for the jit path."""

    MODE = "RNN_TANH"

    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=0):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirect else 1
        cell_cls = {"LSTM": LSTMCell, "GRU": GRUCell,
                    "RNN_TANH": SimpleRNNCell, "RNN_RELU": SimpleRNNCell}[mode]
        from .container import LayerList
        cells = []
        for layer in range(num_layers):
            isz = input_size if layer == 0 else hidden_size * num_dir
            kw = {}
            if mode == "RNN_RELU":
                kw["activation"] = "relu"
            cells.append(cell_cls(isz, hidden_size, weight_ih_attr, weight_hh_attr,
                                  bias_ih_attr, bias_hh_attr, **kw))
            if self.bidirect:
                cells.append(cell_cls(isz, hidden_size, weight_ih_attr, weight_hh_attr,
                                      bias_ih_attr, bias_hh_attr, **kw))
        self.cells = LayerList(cells)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.manipulation import concat, stack
        num_dir = 2 if self.bidirect else 1
        B_axis = 1 if self.time_major else 0
        B = inputs.shape[B_axis]
        if initial_states is None:
            from ...ops.creation import zeros
            if self.mode == "LSTM":
                h0 = zeros([self.num_layers * num_dir, B, self.hidden_size])
                c0 = zeros([self.num_layers * num_dir, B, self.hidden_size])
                initial_states = (h0, c0)
            else:
                initial_states = zeros([self.num_layers * num_dir, B, self.hidden_size])

        out = inputs
        final_h, final_c = [], []
        for layer in range(self.num_layers):
            dir_outs = []
            for d in range(num_dir):
                idx = layer * num_dir + d
                cell = self.cells[idx]
                if self.mode == "LSTM":
                    st = (initial_states[0][idx], initial_states[1][idx])
                else:
                    st = initial_states[idx]
                rnn = RNN(cell, is_reverse=(d == 1), time_major=self.time_major)
                o, s = rnn(out, st)
                dir_outs.append(o)
                if self.mode == "LSTM":
                    final_h.append(s[0])
                    final_c.append(s[1])
                else:
                    final_h.append(s)
            out = dir_outs[0] if num_dir == 1 else concat(dir_outs, axis=-1)
            if self.dropout > 0 and layer < self.num_layers - 1:
                out = F.dropout(out, self.dropout, training=self.training)
        h = stack(final_h, axis=0)
        if self.mode == "LSTM":
            c = stack(final_c, axis=0)
            return out, (h, c)
        return out, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        mode = "RNN_RELU" if activation == "relu" else "RNN_TANH"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, proj_size=0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)
