"""nn.Layer — the module system.

Reference parity: `python/paddle/nn/layer/layers.py:339` (`Layer`): named
params/buffers/sublayers, forward pre/post hooks, `state_dict`/`set_state_dict`,
train/eval mode, dtype/device casts, `apply`, `register_buffer`.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ...core import dtype as _dt
from ...core.tensor import Parameter, Tensor
from ...utils import unique_name


class HookRemoveHelper:
    def __init__(self, hooks, idx):
        self._hooks = hooks
        self._idx = idx

    def remove(self):
        self._hooks.pop(self._idx, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = _dt.convert_dtype(dtype) if dtype else _dt.float32
        self._full_name = unique_name.generate(
            name_scope or self.__class__.__name__.lower())
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0

    # ---- construction helpers ----
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from ..initializer import Constant, XavierNormal
        from ... import ParamAttr
        dtype = dtype or self._dtype
        p = Parameter(jnp.zeros([int(s) for s in shape], _dt.to_np(dtype)))
        init = default_initializer
        learning_rate = 1.0
        regularizer = None
        name = None
        trainable = True
        if isinstance(attr, ParamAttr):
            init = attr.initializer or init
            learning_rate = attr.learning_rate
            regularizer = attr.regularizer
            name = attr.name
            trainable = attr.trainable
        elif attr is False:
            return None
        if init is None:
            init = Constant(0.0) if is_bias else XavierNormal()
        init(p)
        if name:
            p.name = name
        p.stop_gradient = not trainable
        p.trainable = trainable
        p._optimize_attrs = {"learning_rate": learning_rate, "regularizer": regularizer}
        return p

    def create_variable(self, name=None, persistable=False, dtype=None):
        t = Tensor(jnp.zeros([], _dt.to_np(dtype or self._dtype)))
        t.persistable = persistable
        return t

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is not None and not isinstance(parameter, Tensor):
            raise TypeError("parameter must be a Tensor/Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ---- attribute routing ----
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                buffers[name].set_value(value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._buffers) \
            + list(self._sub_layers)

    # ---- call path ----
    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ---- traversal ----
    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, layer in self._sub_layers.items():
            if layer is not None and id(layer) not in seen:
                seen.add(id(layer))
                yield name, layer

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self.named_children():
            if layer is None or id(layer) in layers_set:
                continue
            p = prefix + ("." if prefix else "") + name
            yield from layer.named_sublayers(prefix=p, include_self=True,
                                             layers_set=layers_set)

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for lp, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (lp + ("." if lp else "") + name, p)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for lp, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (lp + ("." if lp else "") + name, b)

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    def full_name(self):
        return self._full_name

    # ---- modes ----
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # ---- state ----
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip("."),
                                             include_sublayers=include_sublayers):
            dest[name] = p
        non_persist = set()
        for lp, layer in self.named_sublayers(prefix=structured_name_prefix.rstrip("."),
                                              include_self=True):
            for short in layer._non_persistable_buffer_names:
                non_persist.add(lp + ("." if lp else "") + short)
        for name, b in self.named_buffers(prefix=structured_name_prefix.rstrip("."),
                                          include_sublayers=include_sublayers):
            if name not in non_persist:
                dest[name] = b
        return dest

    to_static_state_dict = state_dict

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            tgt = own[k]
            data = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            if tuple(data.shape) != tuple(tgt._data.shape):
                raise ValueError(
                    f"shape mismatch for {k}: got {tuple(data.shape)}, expected "
                    f"{tuple(tgt._data.shape)}")
            tgt._data = data.astype(tgt._data.dtype)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ---- casts ----
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast(dtype)
        return self

    def astype(self, dtype):
        self._cast(dtype)
        return self

    def _cast(self, dtype):
        npd = _dt.to_np(dtype)
        for p in self.parameters():
            if jnp.issubdtype(p._data.dtype, jnp.floating):
                p._data = p._data.astype(npd)
        for b in self.buffers():
            if b is not None and jnp.issubdtype(b._data.dtype, jnp.floating):
                b._data = b._data.astype(npd)
        self._dtype = _dt.convert_dtype(dtype)

    def float(self):
        return self.astype("float32")

    def half(self):
        return self.astype("float16")

    def bfloat16(self):
        return self.astype("bfloat16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self.named_children():
            mod_str = repr(layer)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "(" + extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"
