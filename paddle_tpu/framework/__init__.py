"""paddle.framework parity: core runtime surface re-exports + IO."""
from ..core.tensor import Parameter, EagerParamBase  # noqa
from ..core.dtype import get_default_dtype, set_default_dtype  # noqa
from ..core.place import (CPUPlace, CUDAPlace, TPUPlace, _get_expected_place)  # noqa
from ..core import generator as _generator
from .io import save, load  # noqa
from .random import get_rng_state, set_rng_state, seed  # noqa


def in_dygraph_mode():
    return True


def in_dynamic_mode():
    return True


def in_pir_mode():
    return False


def use_pir_api():
    return False


class core:
    """Shim namespace standing in for the pybind `libpaddle` module: the runtime the
    reference binds from C++ is the XLA runtime here."""
    from ..core.tensor import Tensor as eager_tensor  # noqa

    @staticmethod
    def is_compiled_with_cuda():
        return False

    @staticmethod
    def is_compiled_with_xpu():
        return False

    @staticmethod
    def nvprof_nvtx_push(name):
        pass

    @staticmethod
    def nvprof_nvtx_pop():
        pass
