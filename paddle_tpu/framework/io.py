"""paddle.save / paddle.load.

Reference parity: `python/paddle/framework/io.py` — pickle-compatible nested-state
serialization.  Tensors serialize as numpy arrays (portable across hosts/devices);
loading re-wraps them as Tensors unless `return_numpy=True`.
"""
from __future__ import annotations

import io as _io
import os
import pickle

import numpy as np

from ..core.tensor import Tensor


def _pack(obj):
    # Tensors serialize as plain ndarrays (the reference's _build_saved_state_dict
    # layout) so checkpoints interoperate with reference paddle.load both ways.
    if isinstance(obj, Tensor):
        return np.asarray(obj._data)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return packed if isinstance(obj, list) else tuple(packed)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, np.ndarray):
        if return_numpy:
            return obj
        t = Tensor(obj, stop_gradient=True)
        t.persistable = True
        return t
    if isinstance(obj, dict):
        if obj.get("__ptensor__"):  # legacy round-1 marker format
            if return_numpy:
                return obj["data"]
            t = Tensor(obj["data"], stop_gradient=obj.get("stop_gradient", True),
                       name=obj.get("name"))
            t.persistable = True
            return t
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v, return_numpy) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    if hasattr(path, "write"):
        pickle.dump(_pack(obj), path, protocol=protocol)
        return
    path = str(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, **configs):
    return_numpy = configs.get("return_numpy", False)
    if hasattr(path, "read"):
        return _unpack(pickle.load(path), return_numpy)
    with open(str(path), "rb") as f:
        return _unpack(pickle.load(f), return_numpy)
