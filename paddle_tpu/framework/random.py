from ..core import generator as _gen
from ..ops.random import get_rng_state, set_rng_state  # noqa

seed = _gen.seed
