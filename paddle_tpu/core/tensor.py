"""The eager Tensor and the op-dispatch layer.

Reference parity: the public `paddle::Tensor` handle (`paddle/phi/api/include/tensor.h:82`)
plus `AutogradMeta` (`paddle/fluid/eager/autograd_meta.h:61`) and the generated
`*_ad_func` dispatch (`eager/auto_code_generator/generator/eager_gen.py:214`) that wraps
every phi API with GradNode creation.

TPU-native design: `Tensor` wraps a `jnp.ndarray` (device buffer managed by XLA — the
reference's allocator/DeviceContext layers collapse into the XLA runtime).  `apply()` is
the single dispatch point every op goes through: it decides whether to record a GradNode
(capturing the pullback via `jax.vjp`) and wraps outputs.  AMP autocast and the NaN/Inf
checker hook in here, mirroring the AMP_LOGIC / nan_inf_utils stages of the generated
ad_func.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd as _ag
from . import dtype as _dt
from . import flags as _flags
from .place import CPUPlace, Place, TPUPlace, _get_expected_place


def _to_data(x, dtype=None):
    """Anything -> jnp array."""
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (jnp.ndarray, jax.Array)):
        return x
    return jnp.asarray(x, dtype=_dt.to_np(dtype) if dtype is not None else None)


class Tensor:
    """Eager tensor: a jnp device array + autograd metadata."""

    # keep Tensor light: one data slot + autograd meta (AutogradMeta parity)
    # hot fields get slots; __dict__ stays for cold metadata (dist axes, marks)
    __slots__ = ("_data", "stop_gradient", "grad", "_grad_node", "_out_index",
                 "persistable", "name", "_backward_hooks", "trainable",
                 "is_distributed", "_optimize_attrs", "_retain_grad", "__weakref__",
                 "__dict__")

    _name_counter = 0

    def __init__(self, data=None, dtype=None, place=None, stop_gradient=True, name=None):
        if data is None:
            data = jnp.zeros((), _dt.to_np(dtype or _dt._default_dtype))
        d = _to_data(data, dtype)
        if dtype is not None and d.dtype != _dt.to_np(dtype):
            d = d.astype(_dt.to_np(dtype))
        if isinstance(place, CPUPlace):
            d = jax.device_put(d, place.jax_device())
        self._data = d
        self.stop_gradient = bool(stop_gradient)
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        self.persistable = False
        self.trainable = True
        self.is_distributed = False
        self._optimize_attrs = {}
        self._backward_hooks = []
        self._version = 0  # inplace version counter (ref inplace_version)
        if name is None:
            Tensor._name_counter += 1
            name = f"generated_tensor_{Tensor._name_counter}"
        self.name = name

    # ---- structural properties ----
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    dim = ndim

    @property
    def dtype(self):
        return _dt.convert_dtype(self._data.dtype)

    @property
    def size(self):
        return int(self._data.size)

    def numel(self):
        return int(self._data.size)

    @property
    def place(self):
        try:
            dev = list(self._data.devices())[0]
        except Exception:
            return CPUPlace()
        if dev.platform in ("tpu", "axon"):
            return TPUPlace(dev.id)
        return CPUPlace()

    @property
    def is_leaf(self):
        return self._grad_node is None

    @property
    def T(self):
        # paddle semantics: reverse ALL dimensions (fluid/dygraph/math_op_patch.py:174)
        return apply("t", lambda x: jnp.transpose(x), self)

    @property
    def mT(self):
        return apply("mT", lambda x: jnp.swapaxes(x, -2, -1) if x.ndim >= 2 else x, self)

    # ---- conversion ----
    def numpy(self):
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        if args:
            return np.asarray(self._data).item(*args)
        return np.asarray(self._data).item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def astype(self, dtype):
        npd = _dt.to_np(dtype)
        return apply("cast", lambda x: x.astype(npd), self)

    cast = astype

    def clone(self):
        return apply("clone", lambda x: x + jnp.zeros((), x.dtype) if jnp.issubdtype(x.dtype, jnp.inexact) else jnp.array(x), self)

    def detach(self):
        t = Tensor.__new__(Tensor)
        t._data = self._data
        t.stop_gradient = True
        t.grad = None
        t._grad_node = None
        t._out_index = 0
        t.persistable = False
        t.trainable = True
        t.is_distributed = False
        t._optimize_attrs = {}
        t._backward_hooks = []
        t.name = self.name + ".detach"
        return t

    def cpu(self):
        return Tensor(jax.device_put(self._data, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient)

    def tpu(self):
        return Tensor(jax.device_put(self._data, _get_expected_place().jax_device()),
                      stop_gradient=self.stop_gradient)

    cuda = tpu  # compat: accelerator move

    def pin_memory(self):
        return self.cpu()

    def to(self, *args, **kwargs):
        t = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, (str,)) and a in ("cpu",):
                t = t.cpu()
            elif isinstance(a, str) and a.split(":")[0] in ("tpu", "gpu", "cuda", "xpu"):
                t = t.tpu()
            elif isinstance(a, Place):
                t = t.cpu() if isinstance(a, CPUPlace) else t.tpu()
            else:
                try:
                    t = t.astype(a)
                except Exception:
                    pass
        return t

    # ---- autograd surface ----
    def backward(self, grad_tensor=None, retain_graph=False):
        _ag.run_backward([self], [grad_tensor], retain_graph)

    def register_hook(self, hook):
        self._backward_hooks.append(hook)
        if self._grad_node is not None:
            # non-leaf: the engine consults hooks via the producing node's out_refs
            self._grad_node.register_output_ref(self)

        class _Handle:
            def remove(h_self):
                try:
                    self._backward_hooks.remove(hook)
                except ValueError:
                    pass

        return _Handle()

    def clear_grad(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            self.grad._data = jnp.zeros_like(self.grad._data)
        else:
            self.grad = None

    clear_gradient = clear_grad

    def retain_grads(self):
        """Retain .grad on a non-leaf tensor (reference Tensor.retain_grads)."""
        if self._grad_node is None:
            return  # leaf: engine writes .grad anyway
        self._retain_grad = True
        self._grad_node.register_output_ref(self)

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    def _grad_ivar(self):
        return self.grad

    # ---- python protocol ----
    def __len__(self):
        if self._data.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        prefix = "Tensor(shape={}, dtype={}, place={}, stop_gradient={},\n       ".format(
            self.shape, self.dtype.name, self.place, self.stop_gradient)
        body = np.array2string(np.asarray(self._data), prefix=" " * 7)
        return prefix + body + ")"

    def __bool__(self):
        if self._data.size != 1:
            raise ValueError("The truth value of a multi-element Tensor is ambiguous")
        return bool(np.asarray(self._data))

    def __int__(self):
        return int(np.asarray(self._data))

    def __float__(self):
        return float(np.asarray(self._data))

    def __index__(self):
        return int(np.asarray(self._data))

    def __format__(self, spec):
        if self._data.size == 1:
            return format(self.item(), spec)
        return object.__format__(self, spec)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    def __dlpack__(self, stream=None):
        return self._data.__dlpack__()

    def __dlpack_device__(self):
        return self._data.__dlpack_device__()

    # ---- indexing ----
    def _norm_index(self, idx):
        def conv(i):
            if isinstance(i, Tensor):
                return i._data
            if isinstance(i, (list, np.ndarray)):
                return jnp.asarray(i)
            return i
        if isinstance(idx, tuple):
            return tuple(conv(i) for i in idx)
        return conv(idx)

    def __getitem__(self, idx):
        nidx = self._norm_index(idx)
        return apply("slice", lambda x: x[nidx], self)

    def __setitem__(self, idx, value):
        nidx = self._norm_index(idx)
        vt = value if isinstance(value, Tensor) else Tensor(_to_data(value), stop_gradient=True)
        # In-place scatter: self becomes the output of a set_value node whose inputs are
        # a shadow of the old self and the value (reference: set_value op + inplace
        # version bump; prior readers of self in the live tape are not version-checked).
        prev = self.detach()
        prev.stop_gradient = self.stop_gradient
        prev._grad_node = self._grad_node
        prev._out_index = self._out_index
        vdata = vt._data
        if vdata.dtype != self._data.dtype and vdata.dtype.kind == self._data.dtype.kind:
            vt = vt.astype(self._data.dtype)
        def _setfn(x, v):
            tgt_shape = x[nidx].shape
            v = v.astype(x.dtype)
            if v.shape != tgt_shape:
                if v.size == int(np.prod(tgt_shape)):
                    v = v.reshape(tgt_shape)
                else:
                    v = jnp.broadcast_to(v, tgt_shape)
            return x.at[nidx].set(v)
        out = apply("set_value", _setfn, prev, vt)
        self._data = out._data
        self._grad_node = out._grad_node
        self._out_index = out._out_index
        self.stop_gradient = out.stop_gradient
        self._version += 1  # prior tape readers of self now error in backward

    # ---- arithmetic dunders (full set; implementations are jnp lambdas) ----
    def __add__(self, o):
        return apply("add", jnp.add, self, o)

    def __radd__(self, o):
        return apply("add", jnp.add, o, self)

    def __sub__(self, o):
        return apply("subtract", jnp.subtract, self, o)

    def __rsub__(self, o):
        return apply("subtract", jnp.subtract, o, self)

    def __mul__(self, o):
        return apply("multiply", jnp.multiply, self, o)

    def __rmul__(self, o):
        return apply("multiply", jnp.multiply, o, self)

    def __truediv__(self, o):
        return apply("divide", jnp.true_divide, self, o)

    def __rtruediv__(self, o):
        return apply("divide", jnp.true_divide, o, self)

    def __floordiv__(self, o):
        return apply("floor_divide", jnp.floor_divide, self, o)

    def __rfloordiv__(self, o):
        return apply("floor_divide", jnp.floor_divide, o, self)

    def __mod__(self, o):
        return apply("remainder", jnp.remainder, self, o)

    def __rmod__(self, o):
        return apply("remainder", jnp.remainder, o, self)

    def __pow__(self, o):
        return apply("pow", jnp.power, self, o)

    def __rpow__(self, o):
        return apply("pow", jnp.power, o, self)

    def __matmul__(self, o):
        return apply("matmul", jnp.matmul, self, o)

    def __rmatmul__(self, o):
        return apply("matmul", jnp.matmul, o, self)

    def __neg__(self):
        return apply("neg", jnp.negative, self)

    def __abs__(self):
        return apply("abs", jnp.abs, self)

    def __invert__(self):
        return apply("invert", jnp.invert, self)

    # comparison (stop_gradient outputs)
    def __eq__(self, o):
        return apply("equal", jnp.equal, self, o)

    def __ne__(self, o):
        return apply("not_equal", jnp.not_equal, self, o)

    def __lt__(self, o):
        return apply("less_than", jnp.less, self, o)

    def __le__(self, o):
        return apply("less_equal", jnp.less_equal, self, o)

    def __gt__(self, o):
        return apply("greater_than", jnp.greater, self, o)

    def __ge__(self, o):
        return apply("greater_equal", jnp.greater_equal, self, o)

    def __and__(self, o):
        return apply("bitwise_and", jnp.bitwise_and, self, o)

    def __or__(self, o):
        return apply("bitwise_or", jnp.bitwise_or, self, o)

    def __xor__(self, o):
        return apply("bitwise_xor", jnp.bitwise_xor, self, o)

    # in-place variants (trailing-underscore, paddle style): rebind data
    def _inplace_from(self, out: "Tensor"):
        node = out._grad_node
        if node is not None:
            # the producing node recorded *this object* as its input; after the
            # rebind that would be a self-loop in the tape (and a stale read).
            # Swap in a snapshot carrying the pre-op state (reference: eager
            # inplace version snapshot in TensorWrapper).
            snap = None
            for i, inp in enumerate(node.inputs):
                if inp is self:
                    if snap is None:
                        snap = Tensor(self._data, stop_gradient=self.stop_gradient)
                        snap._grad_node = self._grad_node
                        snap._out_index = self._out_index
                        snap._version = self._version
                    node.inputs[i] = snap
        self._data = out._data
        self._grad_node = out._grad_node
        self._out_index = out._out_index
        self._version += 1
        return self

    def add_(self, o):
        return self._inplace_from(self.__add__(o))

    def subtract_(self, o):
        return self._inplace_from(self.__sub__(o))

    def multiply_(self, o):
        return self._inplace_from(self.__mul__(o))

    def divide_(self, o):
        return self._inplace_from(self.__truediv__(o))

    def scale_(self, scale=1.0, bias=0.0):
        return self._inplace_from(apply("scale", lambda x: x * scale + bias, self))

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        self._version += 1
        return self

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        self._version += 1
        return self

    def copy_(self, other, blocking=True):
        self._data = _to_data(other).astype(self._data.dtype)
        self._version += 1
        return self

    def set_value(self, value):
        self._data = _to_data(value).astype(self._data.dtype)
        self._version += 1  # stale tape readers must error, same as copy_
        return self

    # value state used by optimizers/Layer
    def _is_initialized(self):
        return True


class Parameter(Tensor):
    """Trainable tensor (paddle.framework.Parameter parity): stop_gradient=False."""

    def __init__(self, data=None, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable


EagerParamBase = Parameter  # reference alias


# ---------------------------------------------------------------------------
# op dispatch
# ---------------------------------------------------------------------------

_amp_state = None  # set by paddle_tpu.amp to an active autocast state or None


def _set_amp_state(state):
    global _amp_state
    _amp_state = state


# static-graph recorder slot: when paddle.enable_static() is on, every apply()
# also appends (name, jfn, inputs, outputs) to the current static Program so
# Executor.run can re-execute the graph with feed substitution (the TPU-native
# ProgramDesc: the recorded eager tape IS the program)
_static_recorder = [None]


def apply(name: str, jfn: Callable, *inputs, n_outputs: Optional[int] = None,
          _data_override: Optional[Sequence] = None) -> Any:
    """Single dispatch point for every eager op.

    Mirrors the generated ad_func pipeline (`eager_gen.py:214`): AMP cast -> forward ->
    optional NaN check -> GradNode capture via jax.vjp when any input requires grad.
    `jfn` consumes/produces jnp arrays; attrs are closed over by the caller.
    `_data_override`: per-slot replacement arrays (None = use the input's data) —
    used by the create_graph replay to linearize at the forward-time primals while
    keeping the original tensor objects as graph edges.
    """
    if _amp_state is not None and _amp_state.enabled:
        inputs = _amp_state.cast_inputs(name, inputs)

    datas = [_to_data(x) for x in inputs]
    if _data_override is not None:
        datas = [d if ov is None else ov
                 for d, ov in zip(datas, _data_override)]

    need_grad = _ag.is_grad_enabled() and any(
        isinstance(x, Tensor) and not x.stop_gradient
        and jnp.issubdtype(x._data.dtype, jnp.inexact)
        for x in inputs)

    if not need_grad:
        out = jfn(*datas)
        res = _wrap_outputs(name, out, node=None)
        if _static_recorder[0] is not None:
            _static_recorder[0]._record(name, jfn, inputs, res)
        return res

    outs, vjp_fn = jax.vjp(jfn, *datas)
    tensor_inputs = [x if isinstance(x, Tensor) else None for x in inputs]
    multi = isinstance(outs, (tuple, list))
    out_list = list(outs) if multi else [outs]
    specs = [(o.shape, o.dtype) for o in out_list]
    node = _ag.GradNode(name, vjp_fn, tensor_inputs, len(out_list), specs,
                        jfn=jfn, in_datas=datas, out_tuple=multi)
    res = _wrap_outputs(name, outs, node=node)
    if _static_recorder[0] is not None:
        _static_recorder[0]._record(name, jfn, inputs, res)
    return res


def _wrap_outputs(name, out, node):
    if _flags.flag("check_nan_inf"):
        _check_numerics(name, out)
    if isinstance(out, (tuple, list)):
        res = []
        for i, o in enumerate(out):
            t = Tensor(o)
            if node is not None and jnp.issubdtype(o.dtype, jnp.inexact):
                t.stop_gradient = False
                t._grad_node = node
                t._out_index = i
            res.append(t)
        return tuple(res)
    t = Tensor(out)
    if node is not None and jnp.issubdtype(out.dtype, jnp.inexact):
        t.stop_gradient = False
        t._grad_node = node
        t._out_index = 0
    return t


def _check_numerics(name, out):
    """FLAGS_check_nan_inf parity (`fluid/eager/nan_inf_utils.h:38`)."""
    outs = out if isinstance(out, (tuple, list)) else [out]
    for o in outs:
        if jnp.issubdtype(jnp.asarray(o).dtype, jnp.inexact):
            bad = bool(jnp.any(~jnp.isfinite(o)))
            if bad:
                msg = f"Operator {name} output contains NaN/Inf"
                if _flags.flag("check_nan_inf_level") == 0:
                    raise FloatingPointError(msg)
                print("WARNING:", msg)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor."""
    if isinstance(data, Tensor):
        t = data.astype(dtype) if dtype is not None else Tensor(data._data)
        t.stop_gradient = stop_gradient
        return t
    if dtype is None and isinstance(data, (float,)):
        dtype = _dt._default_dtype
    if dtype is None and isinstance(data, (list, tuple)):
        flat = np.asarray(data)
        if flat.dtype == np.float64:
            dtype = _dt._default_dtype
    if dtype is None and isinstance(data, np.ndarray) and data.dtype == np.float64:
        dtype = _dt.float64  # paddle keeps fp64 numpy as fp64
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
