"""Device places.

Reference parity: `phi::Place` / `AllocationType` (`paddle/phi/common/place.h:28`) and the
Python ``paddle.CPUPlace()/CUDAPlace(i)`` objects.  Here a Place maps to a jax.Device;
``TPUPlace`` is the first-class accelerator (the reference's CUDAPlace analog).
"""
from __future__ import annotations

import functools

import jax


class Place:
    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Place) and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def jax_device(self):
        devs = [d for d in jax.devices() if _kind(d) == self.device_type]
        if not devs:
            devs = jax.devices("cpu")
        return devs[min(self.device_id, len(devs) - 1)]


class CPUPlace(Place):
    device_type = "cpu"

    def jax_device(self):
        return jax.devices("cpu")[0]


class TPUPlace(Place):
    device_type = "tpu"


# CUDA alias kept so reference-style code ports over; resolves to the accelerator.
class CUDAPlace(TPUPlace):
    pass


class CUDAPinnedPlace(CPUPlace):
    pass


class XPUPlace(TPUPlace):
    pass


class CustomPlace(Place):
    def __init__(self, device_type: str, device_id: int = 0):
        super().__init__(device_id)
        self.device_type = device_type


def _kind(dev) -> str:
    plat = dev.platform
    if plat in ("tpu", "axon"):
        return "tpu"
    if plat in ("gpu", "cuda", "rocm"):
        return "gpu"
    return "cpu"


@functools.lru_cache(None)
def _accelerator_available() -> bool:
    try:
        return any(_kind(d) == "tpu" for d in jax.devices())
    except RuntimeError:        # no backend could initialize
        return False


_expected_place = None


def set_device(device) -> Place:
    """paddle.set_device("tpu"/"cpu"/"tpu:0")."""
    global _expected_place
    if isinstance(device, Place):
        _expected_place = device
        return device
    name, _, idx = str(device).partition(":")
    idx = int(idx) if idx else 0
    name = {"gpu": "tpu", "cuda": "tpu", "xpu": "tpu"}.get(name, name)
    if name == "tpu":
        _expected_place = TPUPlace(idx)
    elif name == "cpu":
        _expected_place = CPUPlace()
    else:
        _expected_place = CustomPlace(name, idx)
    return _expected_place


def get_device() -> str:
    p = _get_expected_place()
    return f"{p.device_type}:{p.device_id}" if p.device_type != "cpu" else "cpu"


def _get_expected_place() -> Place:
    global _expected_place
    if _expected_place is None:
        _expected_place = TPUPlace(0) if _accelerator_available() else CPUPlace()
    return _expected_place


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def device_count() -> int:
    try:
        return len([d for d in jax.devices() if _kind(d) == "tpu"]) or 1
    except RuntimeError:        # no backend could initialize
        return 1
