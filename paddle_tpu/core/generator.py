"""RNG state.

Reference parity: `phi::Generator` (`paddle/phi/core/generator.h`) — per-device seeded
Philox state — and the fleet `RNGStatesTracker` (`fleet/layers/mpu/random.py`).  JAX's
threefry key IS the Philox-analog counter state; we keep a mutable default generator that
splits a fresh key per draw so eager random ops are stateful like the reference, while
`rng_state()`/`set_state` expose the raw key for capture inside jit.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np


class Generator:
    """Stateful RNG built on splitting a jax PRNG key.

    The key materializes lazily on first draw: creating it eagerly would
    initialize the XLA backend at import time, which breaks
    `jax.distributed.initialize` (must run before any backend use — see
    distributed/parallel_env.py)."""

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._key = None
        self._lock = threading.Lock()

    def manual_seed(self, seed: int) -> "Generator":
        self._seed = int(seed)
        self._key = jax.random.key(self._seed)
        return self

    seed = manual_seed

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        """Split and return a fresh subkey (advances state)."""
        with self._lock:
            if self._key is None:
                self._key = jax.random.key(self._seed)
            self._key, sub = jax.random.split(self._key)
        return sub

    def get_state(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.key(self._seed)
            return self._key

    def set_state(self, key):
        self._key = key


_default = Generator(int(np.random.randint(0, 2**31 - 1)))


def default_generator() -> Generator:
    return _default


def seed(s: int) -> Generator:
    """paddle.seed — seeds the default (and tracker) generators."""
    _default.manual_seed(s)
    _tracker.reset(s)
    return _default


def next_key():
    return _default.next_key()


class RNGStatesTracker:
    """Named parallel RNG states (fleet/layers/mpu/random.py parity).

    Model-parallel dropout needs different streams on different TP ranks for activation
    dropout but identical streams for weight init; named states provide both.
    """

    def __init__(self):
        self._states = {}

    def reset(self, base_seed=None):
        self._states = {}

    def add(self, name: str, seed: int):
        if name in self._states:
            raise ValueError(f"rng state {name!r} already exists")
        self._states[name] = Generator(seed)

    def get_states_tracker(self):
        return dict(self._states)

    def set_states_tracker(self, states):
        self._states = dict(states)

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed"):
        if name == "global_seed" and name not in self._states:
            yield _default
            return
        if name not in self._states:
            raise ValueError(f"rng state {name!r} not added")
        yield self._states[name]


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker
