from . import autograd, dtype, flags, generator, place, tensor  # noqa
