"""Tape-based eager autograd engine.

Reference parity: the eager GradNode DAG and backward engine —
`GradNodeBase` (`paddle/fluid/eager/grad_node_info.h:168`), `egr::Backward` /
`RunBackward` (`paddle/fluid/eager/backward.cc:421,:104`), in-degree computation
(`general_grad.h:23-69`), `GradTensorHolder` accumulation, leaf accumulation
(`accumulation/accumulation_node.h:23`), partial `paddle.grad` (`general_grad.h`).

TPU-native design: instead of ~900 hand-written grad kernels, each recorded op captures
its pullback from `jax.vjp` over the op's jnp implementation, so XLA differentiates the
kernel while this engine owns the *graph semantics* (topological traversal, fan-in
accumulation, retain_graph, hooks, partial `grad()`).  The jit/`to_static` path bypasses
this tape entirely and uses `jax.grad` over the captured program.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class _TLS(threading.local):
    def __init__(self):
        self.grad_enabled = True


_tls = _TLS()


def is_grad_enabled() -> bool:
    return _tls.grad_enabled


def set_grad_enabled(mode: bool):
    class _Guard:
        def __init__(self, mode):
            self.prev = _tls.grad_enabled
            _tls.grad_enabled = bool(mode)

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            _tls.grad_enabled = self.prev

    return _Guard(mode)


class no_grad(contextlib.ContextDecorator):
    """Context manager / decorator disabling grad recording (paddle.no_grad)."""

    def __enter__(self):
        self._prev = _tls.grad_enabled
        _tls.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _tls.grad_enabled = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _tls.grad_enabled
        _tls.grad_enabled = True
        return self

    def __exit__(self, *exc):
        _tls.grad_enabled = self._prev
        return False


class GradNode:
    """One recorded op in the tape (GradNodeBase parity).

    Holds the vjp pullback and edges to input tensors.  Output tensors point back at
    their producing node via (tensor._grad_node, tensor._out_index).
    """

    __slots__ = ("name", "vjp_fn", "inputs", "n_outputs", "out_specs", "out_refs",
                 "jfn", "in_datas", "out_tuple", "id", "input_versions",
                 "__weakref__")

    _counter = 0

    def __init__(self, name: str, vjp_fn: Callable, inputs: Sequence[Any], n_outputs: int,
                 out_specs=None, jfn=None, in_datas=None, out_tuple=None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)  # strong refs (TensorWrapper parity)
        # inplace-version snapshot (ref: TensorWrapper inplace_version_snapshot_):
        # backward errors if an input was modified in place after being recorded
        self.input_versions = [getattr(t, "_version", 0) if t is not None else None
                               for t in self.inputs]
        self.n_outputs = n_outputs
        self.out_specs = out_specs  # [(shape, dtype)] per output, for zero-filling
        self.out_refs = None  # {out_index: [weakref(Tensor)]} for hooks/retain_grads
        # jfn: the forward jnp function; kept so create_graph=True can re-linearize
        # the pullback as a *recorded* op (double backward). in_datas: the original
        # primal arrays for non-Tensor input slots.
        self.jfn = jfn
        self.in_datas = in_datas
        # whether jfn's output is a tuple/list (pytree structure for the pullback);
        # None = infer from n_outputs (legacy nodes)
        self.out_tuple = out_tuple
        GradNode._counter += 1
        self.id = GradNode._counter

    def register_output_ref(self, tensor):
        import weakref
        if self.out_refs is None:
            self.out_refs = {}
        self.out_refs.setdefault(tensor._out_index, []).append(weakref.ref(tensor))

    def __repr__(self):
        return f"<GradNode {self.name}#{self.id}>"


def _accumulate(buf: dict, idx: int, value):
    cur = buf.get(idx)
    buf[idx] = value if cur is None else cur + value


def _is_float_dtype(dt) -> bool:
    return jnp.issubdtype(dt, jnp.floating) or jnp.issubdtype(dt, jnp.complexfloating)


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


# callbacks invoked when a full (non-partial) backward traversal completes
_post_backward_callbacks = []


def register_post_backward_callback(cb):
    _post_backward_callbacks.append(cb)
    return cb


def unregister_post_backward_callback(cb):
    try:
        _post_backward_callbacks.remove(cb)
    except ValueError:
        pass


def run_backward(tensors: Sequence, grad_tensors: Optional[Sequence] = None,
                 retain_graph: bool = False) -> None:
    """Full backward from seeds, accumulating into leaf `.grad` (`RunBackward` parity)."""
    _engine(tensors, grad_tensors, retain_graph, inputs=None, create_graph=False,
            allow_unused=True)
    for t in _as_list(tensors):
        # minimize() consults this: with retain_graph=True the tape stays live, so
        # vjp_fn liveness alone can't tell whether backward already ran
        t._backward_ran = True


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         only_inputs=True, allow_unused=False, no_grad_vars=None):
    """Partial gradient (paddle.grad / `general_grad.h` parity): returns grads of
    `outputs` w.r.t. `inputs` without writing `.grad` fields."""
    outputs = _as_list(outputs)
    inputs = _as_list(inputs)
    if retain_graph is None:
        retain_graph = create_graph
    return _engine(outputs, grad_outputs, retain_graph, inputs=inputs,
                   create_graph=create_graph, allow_unused=allow_unused)


def _replay_pullback(node, bufs):
    """create_graph=True path: recompute this node's vjp as a *recorded* tape op.

    The stored raw pullback closes over the primals as constants, so differentiating
    through it alone would drop the d(vjp)/d(primal) term (e.g. grad-of-grad of x**3
    would come out zero).  Instead re-linearize `node.jfn` at the current primals
    inside a fresh `apply()` so both the cotangents AND the primal inputs are
    connected for higher-order backward.  Reference capability: higher-order AD via
    composite grad rules (`fluid/prim/api/composite_backward/`).
    """
    from .tensor import Tensor, apply

    if node.jfn is None:
        raise NotImplementedError(
            f"create_graph=True through '{node.name}' is not supported: this node "
            "records no replayable forward function")

    n_in = len(node.inputs)
    float_outs = [i for i in range(node.n_outputs)
                  if _is_float_dtype(jnp.dtype(node.out_specs[i][1]))]
    # graph edges stay the original tensor objects; values come from the
    # forward-time primals (in_datas) so an in-place mutation between forward and
    # this replay can't silently shift the linearization point
    prim_tensors = []
    overrides = []
    for k, inp in enumerate(node.inputs):
        if isinstance(inp, Tensor):
            prim_tensors.append(inp)
            overrides.append(node.in_datas[k])
        else:
            prim_tensors.append(Tensor(node.in_datas[k], stop_gradient=True))
            overrides.append(None)
    float_ins = [k for k in range(n_in)
                 if _is_float_dtype(jnp.asarray(node.in_datas[k]).dtype)]

    cot_tensors = []
    for i in float_outs:
        c = bufs.get(i)
        if c is None:
            shape, dt = node.out_specs[i]
            c = Tensor(jnp.zeros(shape, dt), stop_gradient=True)
        elif not isinstance(c, Tensor):
            c = Tensor(c, stop_gradient=True)
        cot_tensors.append(c)

    jfn, n_outs, out_specs = node.jfn, node.n_outputs, node.out_specs
    out_tuple = node.out_tuple if node.out_tuple is not None else n_outs > 1
    float_out_set = set(float_outs)

    def replay(*flat):
        prim = flat[:n_in]
        cotd = flat[n_in:]
        _, pull = jax.vjp(jfn, *prim)
        cots, j = [], 0
        for i in range(n_outs):
            if i in float_out_set:
                cots.append(cotd[j])
                j += 1
            else:
                shape, dt = out_specs[i]
                cots.append(np.zeros(shape, dtype=jax.dtypes.float0))
        grads = pull(tuple(cots) if out_tuple else cots[0])
        return tuple(grads[k] for k in float_ins)

    outs = apply(f"grad_{node.name}", replay, *prim_tensors, *cot_tensors,
                 _data_override=overrides + [None] * len(cot_tensors))
    if not isinstance(outs, (tuple, list)):
        outs = (outs,)
    in_cots = [None] * n_in
    for j, k in enumerate(float_ins):
        in_cots[k] = outs[j]
    return in_cots


def _engine(tensors, grad_tensors, retain_graph, inputs, create_graph, allow_unused):
    from .tensor import Tensor  # cycle: tensor builds nodes, engine consumes them

    partial = inputs is not None
    tensors = _as_list(tensors)
    grad_tensors = _as_list(grad_tensors) or [None] * len(tensors)
    if len(grad_tensors) != len(tensors):
        raise ValueError("grad_tensors length must match tensors")

    if create_graph:
        # the backward computation itself must be recorded: cotangents flow through
        # the engine as Tensors and every accumulation/vjp is a tape op
        with enable_grad():
            return _engine_impl(tensors, grad_tensors, retain_graph, inputs,
                                True, allow_unused, partial)
    return _engine_impl(tensors, grad_tensors, retain_graph, inputs, False,
                        allow_unused, partial)


def _engine_impl(tensors, grad_tensors, retain_graph, inputs, create_graph,
                 allow_unused, partial):
    from .tensor import Tensor

    # pending[node] = {out_index: accumulated cotangent jnp array}
    pending: Dict[GradNode, Dict[int, Any]] = {}
    input_grads: Dict[int, Any] = {}  # id(input tensor) -> cotangent data
    input_ids = {id(t): t for t in inputs} if partial else {}
    # requested intermediate inputs, keyed by producing (node id, out_index)
    want_from_node: Dict[tuple, List] = {}
    if partial:
        for t in inputs:
            if t._grad_node is not None:
                want_from_node.setdefault((t._grad_node, t._out_index), []).append(t)

    def leaf_hit(tensor, gdata):
        """Cotangent arrived at a graph endpoint."""
        if partial:
            if id(tensor) in input_ids:
                cur = input_grads.get(id(tensor))
                input_grads[id(tensor)] = gdata if cur is None else cur + gdata
            return
        for hook in tensor._backward_hooks:
            res = hook(Tensor(gdata, stop_gradient=True))
            if res is not None:
                gdata = res._data if isinstance(res, Tensor) else jnp.asarray(res)
        if tensor.grad is None:
            g = Tensor(gdata, stop_gradient=True)
            g.persistable = True
            tensor.grad = g
        else:
            tensor.grad._data = tensor.grad._data + gdata

    # ---- seeds ----
    for t, g in zip(tensors, grad_tensors):
        if not isinstance(t, Tensor):
            raise TypeError("backward seeds must be Tensors")
        if g is None:
            if t._data.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; got shape "
                    f"{tuple(t._data.shape)}")
            gdata = jnp.ones(t._data.shape, t._data.dtype)
            if create_graph:
                gdata = Tensor(gdata, stop_gradient=True)
        elif create_graph:
            # keep the seed as a live Tensor: a grad_outputs that itself requires
            # grad must stay connected for third-order chains
            gdata = g if isinstance(g, Tensor) else Tensor(jnp.asarray(g),
                                                           stop_gradient=True)
        else:
            gdata = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        node = t._grad_node
        if node is None:
            if not t.stop_gradient:
                leaf_hit(t, gdata)
            continue
        _accumulate(pending.setdefault(node, {}), t._out_index, gdata)

    # ---- phase 1: reachable set + in-degree over node graph (general_grad.h:23-69) ----
    indeg: Dict[GradNode, int] = {}
    seen = set()
    stack = list(pending.keys())
    while stack:
        node = stack.pop()
        if node in seen:
            continue
        seen.add(node)
        for inp in node.inputs:
            if isinstance(inp, Tensor) and inp._grad_node is not None:
                nxt = inp._grad_node
                indeg[nxt] = indeg.get(nxt, 0) + 1
                if nxt not in seen:
                    stack.append(nxt)

    # ---- phase 2: ready-queue topo traversal ----
    ready = [n for n in seen if indeg.get(n, 0) == 0]
    while ready:
        node = ready.pop()
        bufs = pending.pop(node, None)
        in_cots = None
        # non-leaf hooks + retain_grads registered on this node's outputs
        if node.out_refs and bufs:
            for i, wrefs in node.out_refs.items():
                c = bufs.get(i)
                if c is None:
                    continue
                for wref in wrefs:
                    t = wref()
                    if t is None:
                        continue
                    for hook in t._backward_hooks:
                        ct = c if isinstance(c, Tensor) else Tensor(c, stop_gradient=True)
                        res = hook(ct)
                        if res is not None:
                            if create_graph:
                                c = res if isinstance(res, Tensor) else \
                                    Tensor(jnp.asarray(res), stop_gradient=True)
                            else:
                                c = res._data if isinstance(res, Tensor) else jnp.asarray(res)
                    if getattr(t, "_retain_grad", False):
                        craw = c._data if isinstance(c, Tensor) else c
                        if t.grad is None:
                            g = Tensor(craw, stop_gradient=True)
                            g.persistable = True
                            t.grad = g
                        else:
                            t.grad._data = t.grad._data + craw
                bufs[i] = c
        if bufs:
            # capture cotangents for requested intermediates produced by this node
            for i in range(node.n_outputs):
                for t in want_from_node.get((node, i), ()):  # partial-grad intermediates
                    c = bufs.get(i)
                    if c is not None:
                        cur = input_grads.get(id(t))
                        input_grads[id(t)] = c if cur is None else cur + c
            if node.vjp_fn is None:
                raise RuntimeError(
                    f"Trying to run backward through {node.name} a second time. Set "
                    "retain_graph=True on the first backward if you need this.")
            # inplace version check (ref eager inplace version counter): a tensor
            # recorded as this node's input must not have been modified in place
            # since — silent wrong gradients are worse than an exception
            for _inp, _ver in zip(node.inputs, node.input_versions):
                if _ver is not None and getattr(_inp, "_version", 0) != _ver:
                    raise RuntimeError(
                        "one of the variables needed for gradient computation has "
                        f"been modified by an inplace operation: input of "
                        f"'{node.name}' is at version "
                        f"{getattr(_inp, '_version', 0)}, expected {_ver}")
            if create_graph:
                in_cots = _replay_pullback(node, bufs)
            else:
                cots = []
                for i in range(node.n_outputs):
                    c = bufs.get(i)
                    if c is None:
                        shape, dt = node.out_specs[i]
                        if _is_float_dtype(jnp.dtype(dt)):
                            c = jnp.zeros(shape, dt)
                        else:
                            # integer/bool outputs (e.g. topk indices): jax.vjp
                            # expects float0 cotangents, not integer zeros
                            c = np.zeros(shape, dtype=jax.dtypes.float0)
                    cots.append(c)
                as_tuple = node.out_tuple if node.out_tuple is not None \
                    else node.n_outputs > 1
                cot_arg = tuple(cots) if as_tuple else cots[0]
                with set_grad_enabled(False):
                    in_cots = node.vjp_fn(cot_arg)
        if not retain_graph and node.vjp_fn is not None:
            # release saved residuals; jfn/in_datas too, else the forward closure
            # and primal arrays outlive backward (create_graph implies
            # retain_graph, so the replay path never reads them from a freed node)
            node.vjp_fn = None
            node.jfn = None
            node.in_datas = None
        for k, inp in enumerate(node.inputs):
            if not isinstance(inp, Tensor):
                continue
            ic = None
            if in_cots is not None:
                ic = in_cots[k]
                if ic is not None:
                    dt = ic._data.dtype if isinstance(ic, Tensor) else \
                        jnp.asarray(ic).dtype
                    if not _is_float_dtype(dt):
                        ic = None  # int/bool primal: float0 cotangent, nothing to propagate
            nxt = inp._grad_node
            if nxt is not None:
                if ic is not None:
                    _accumulate(pending.setdefault(nxt, {}), inp._out_index, ic)
                indeg[nxt] -= 1
                if indeg[nxt] == 0:
                    ready.append(nxt)  # fires even with no cotangent (zero-pass skip)
            elif ic is not None and not inp.stop_gradient:
                leaf_hit(inp, ic)

    if not partial:
        # post-backward callbacks (DataParallel bucket flush etc.): the engine
        # is the only place that knows the traversal truly finished — counting
        # leaf-hook fires cannot (shared params fire once per consumer edge)
        for cb in list(_post_backward_callbacks):
            cb()
        return None
    out = []
    for t in inputs:
        g = input_grads.get(id(t))
        if g is None:
            if not allow_unused:
                raise ValueError(
                    "one of the input tensors was not used in the graph; set "
                    "allow_unused=True to return None for it")
            out.append(None)
        elif isinstance(g, Tensor):
            out.append(g)  # create_graph path: already a live tape Tensor
        else:
            out.append(Tensor(g, stop_gradient=not create_graph))
    return out
