"""Dtype system.

Reference parity: phi DataType enum (`paddle/phi/common/data_type.h`) exposed as
``paddle.float32`` etc.  Here dtypes are thin singletons wrapping numpy/jnp dtypes so they
interoperate directly with XLA; string forms ("float32") are accepted everywhere.
"""
from __future__ import annotations

import numpy as np

try:
    import ml_dtypes  # ships with jax

    _BF16 = np.dtype(ml_dtypes.bfloat16)
    _FP8_E4M3 = np.dtype(ml_dtypes.float8_e4m3fn)
    _FP8_E5M2 = np.dtype(ml_dtypes.float8_e5m2)
except Exception:  # pragma: no cover
    _BF16 = np.dtype("float32")
    _FP8_E4M3 = None
    _FP8_E5M2 = None


class DType:
    """A framework dtype: hashable, comparable with strings and numpy dtypes."""

    __slots__ = ("name", "np_dtype")

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        if isinstance(other, str):
            return self.name == other or str(self.np_dtype) == other
        try:
            return self.np_dtype == np.dtype(other)
        except Exception:
            return NotImplemented

    def __hash__(self):
        return hash(self.name)

    @property
    def itemsize(self):
        return self.np_dtype.itemsize

    def is_floating_point(self):
        return self.name in ("float16", "bfloat16", "float32", "float64", "float8_e4m3fn", "float8_e5m2")

    def is_integer(self):
        return self.name in ("int8", "int16", "int32", "int64", "uint8", "uint16", "uint32", "uint64")

    def is_complex(self):
        return self.name in ("complex64", "complex128")


bool_ = DType("bool", np.bool_)
uint8 = DType("uint8", np.uint8)
int8 = DType("int8", np.int8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", _BF16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)
if _FP8_E4M3 is not None:
    float8_e4m3fn = DType("float8_e4m3fn", _FP8_E4M3)
    float8_e5m2 = DType("float8_e5m2", _FP8_E5M2)

_ALL = [bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32, float64,
        complex64, complex128]
_BY_NAME = {d.name: d for d in _ALL}
_BY_NAME["bool"] = bool_
_BY_NAME["float"] = float32
_BY_NAME["double"] = float64
_BY_NAME["half"] = float16
_BY_NAME["int"] = int32
_BY_NAME["long"] = int64


def convert_dtype(dtype) -> DType:
    """Normalise any dtype spec (DType, str, numpy dtype, jnp dtype) to a DType."""
    if dtype is None:
        return None
    if isinstance(dtype, DType):
        return dtype
    if isinstance(dtype, str):
        if dtype in _BY_NAME:
            return _BY_NAME[dtype]
        raise ValueError(f"unknown dtype string {dtype!r}")
    npd = np.dtype(dtype)
    for d in _ALL:
        if d.np_dtype == npd:
            return d
    raise ValueError(f"unsupported dtype {dtype!r}")


def to_np(dtype):
    """DType/str/np dtype -> numpy dtype usable by jnp."""
    d = convert_dtype(dtype)
    return d.np_dtype if d is not None else None


# default dtype machinery (paddle.set_default_dtype)
_default_dtype = float32


def set_default_dtype(dtype):
    global _default_dtype
    d = convert_dtype(dtype)
    if not d.is_floating_point():
        raise TypeError("default dtype must be floating point, got %s" % d)
    _default_dtype = d


def get_default_dtype() -> str:
    return _default_dtype.name
