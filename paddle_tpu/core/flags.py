"""Runtime flag registry.

Reference parity: gflags + ``PHI_DEFINE_EXPORTED_*`` (`paddle/phi/core/flags.cc`, 93 flags)
surfaced to Python via ``paddle.set_flags/get_flags``
(`paddle/fluid/pybind/global_value_getter_setter.cc`).  Flags read their default from the
environment (``FLAGS_<name>``), like the reference.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterable, Union

_REGISTRY: Dict[str, dict] = {}


def _coerce(value, proto):
    if isinstance(proto, bool):
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if isinstance(proto, int) and not isinstance(proto, bool):
        return int(value)
    if isinstance(proto, float):
        return float(value)
    return value


def define_flag(name: str, default: Any, doc: str = "") -> None:
    env = os.environ.get(name if name.startswith("FLAGS_") else f"FLAGS_{name}")
    value = _coerce(env, default) if env is not None else default
    _REGISTRY[_norm(name)] = {"value": value, "default": default, "doc": doc}


def _norm(name: str) -> str:
    return name if name.startswith("FLAGS_") else f"FLAGS_{name}"


def get_flags(flags: Union[str, Iterable[str]]) -> Dict[str, Any]:
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for f in flags:
        key = _norm(f)
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {f!r}")
        out[key] = _REGISTRY[key]["value"]
    return out


def set_flags(flags: Dict[str, Any]) -> None:
    for k, v in flags.items():
        key = _norm(k)
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {k!r}")
        _REGISTRY[key]["value"] = _coerce(v, _REGISTRY[key]["default"])


def flag(name: str) -> Any:
    return _REGISTRY[_norm(name)]["value"]


# Core flag set (subset of the reference's 93, the ones with behavioural meaning here).
define_flag("check_nan_inf", False, "check every op output for NaN/Inf (nan_inf_utils parity)")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; >=1: log only")
define_flag("benchmark", False, "sync after every op for timing")
define_flag("eager_delete_tensor_gb", 0.0, "GC threshold (no-op: XLA owns memory)")
define_flag("fraction_of_gpu_memory_to_use", 0.92, "accepted for compat; XLA preallocation governs")
define_flag("allocator_strategy", "auto_growth", "compat; device memory is XLA-managed")
define_flag("cudnn_deterministic", False, "map to deterministic XLA reductions")
define_flag("embedding_deterministic", 0, "deterministic scatter in embedding grad")
define_flag("matmul_precision", "default", "default|high|highest -> jax default_matmul_precision")
define_flag("use_stride_kernel", True, "compat only")
define_flag("tensor_construct_check", False, "validate values on Tensor construction")
define_flag("low_precision_op_list", 0, "record ops run in low precision (amp audit)")
define_flag("log_memory_stats", False, "log live buffer stats each step")
define_flag("init_allocated_mem", False, "compat only")
define_flag("conv_workspace_size_limit", 512, "compat only")
define_flag("enable_pir_api", False, "compat; the jaxpr program IS the new IR here")
define_flag("prim_all", False, "decompose composite ops before compile")
define_flag("use_fused_attention", True, "route nn attention through fused/pallas path when possible")
define_flag("flash_attn_version", 2, "compat")
define_flag("tpu_matmul_bf16", False, "force bf16 matmuls outside amp")
