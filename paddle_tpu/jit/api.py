"""jit.to_static / jit.save / jit.load (reference: `python/paddle/jit/api.py` :233/:816).

Serialization uses `jax.export` (StableHLO) — the compiled program is portable across
processes without the original Python code, matching the reference's
Program+params `jit.save` contract (`translated_layer.py`).
"""
from __future__ import annotations

import os
import pickle
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..static.input_spec import InputSpec
from .program import StaticFunction, functionalize


_to_static_enabled = [True]


def enable_to_static(enable=True):
    """ref jit/api.py enable_to_static: global switch — when off, @to_static
    functions run eagerly (debugging escape hatch)."""
    _to_static_enabled[0] = bool(enable)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              **kwargs):
    """Decorator/wrapper converting a dygraph function or Layer to a compiled program."""
    def decorate(fn):
        if isinstance(fn, Layer):
            static = StaticFunction(fn, input_spec)
            fn.forward = static
            fn._static_function = static
            return fn
        return StaticFunction(fn, input_spec)
    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def _resolve_specs(layer, input_spec):
    if input_spec is None:
        raise ValueError("jit.save needs input_spec (or call the layer once and pass "
                         "the example inputs as input_spec)")
    specs = []
    for s in input_spec:
        if isinstance(s, InputSpec):
            specs.append(jax.ShapeDtypeStruct(
                tuple(1 if (d is None or d == -1) else int(d) for d in s.shape),
                np.dtype(s.dtype.np_dtype if hasattr(s.dtype, "np_dtype") else s.dtype)))
        elif isinstance(s, Tensor):
            specs.append(jax.ShapeDtypeStruct(tuple(s._data.shape), s._data.dtype))
        else:
            arr = np.asarray(s)
            specs.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
    return specs


def save(layer, path, input_spec=None, **configs):
    """Serialize layer program (StableHLO via jax.export) + params."""
    from jax import export as jax_export

    was_training = layer.training if isinstance(layer, Layer) else False
    if isinstance(layer, Layer):
        layer.eval()
    try:
        fn = layer.forward if isinstance(layer, Layer) else layer
        if isinstance(fn, StaticFunction):
            fn = fn._fn
        pure_fn, params, buffers = functionalize(fn, layer if isinstance(layer, Layer) else None)
        specs = _resolve_specs(layer, input_spec)
        p_datas = [p._data for _, p in params]
        b_datas = [b._data for _, b in buffers]
        from .program import _flatten_inputs
        dummy_tensors = tuple(Tensor(jnp.zeros(s.shape, s.dtype)) for s in specs)
        _, in_tree = _flatten_inputs(dummy_tensors, {})
        pure_fn._in_tree = in_tree

        def infer_fn(*in_datas):
            flat = pure_fn(p_datas, b_datas, *in_datas)
            return flat[:len(flat) - len(buffers)]

        exported = jax_export.export(jax.jit(infer_fn))(*specs)
        blob = exported.serialize()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path + ".pdmodel", "wb") as f:
            f.write(blob)
        state = {name: np.asarray(p._data) for name, p in params}
        state.update({name: np.asarray(b._data) for name, b in buffers})
        meta = {"out_tree": getattr(pure_fn, "_out_tree", None),
                "n_outputs": None}
        with open(path + ".pdiparams", "wb") as f:
            pickle.dump({"state": state, "meta": meta}, f)
    finally:
        if isinstance(layer, Layer) and was_training:
            layer.train()


class TranslatedLayer(Layer):
    """Loaded program wrapper (reference `translated_layer.py` TranslatedLayer)."""

    def __init__(self, exported, meta):
        super().__init__()
        self._exported = exported
        self._meta = meta

    def forward(self, *args):
        datas = [a._data if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        outs = self._exported.call(*datas)
        tree = self._meta.get("out_tree")
        tensors = [Tensor(o) for o in (outs if isinstance(outs, (tuple, list)) else [outs])]
        if tree is not None:
            from .program import _unflatten_outputs
            try:
                return _unflatten_outputs(tensors, tree)
            except Exception:
                pass
        return tensors[0] if len(tensors) == 1 else tuple(tensors)


def load(path, **configs):
    from jax import export as jax_export
    with open(path + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    meta = {}
    if os.path.exists(path + ".pdiparams"):
        with open(path + ".pdiparams", "rb") as f:
            meta = pickle.load(f).get("meta", {})
    return TranslatedLayer(exported, meta)
