"""dy2static — AST rewriting of Python control flow on tensor values.

Reference parity: `python/paddle/jit/dy2static/` (`ast_transformer.py` rewrites
`if`/`while` statements into `convert_ifelse`/`convert_while_loop` calls;
`convert_operators.py` dispatches tensor-valued predicates to control-flow ops
and python values to plain python).

TPU-native: the converted calls land on `static.nn.cond` (both-branch select)
and `static.nn.while_loop` (`jax.lax.while_loop`) under capture, plain Python
eagerly.  `StaticFunction` applies the transform lazily: the untransformed
function traces first, and only a tensor-bool error during tracing triggers
the rewrite + retrace — existing traces never change.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
from typing import Callable, Set


# ---------------------------------------------------------------------------
# runtime converters (ref convert_operators.py)
# ---------------------------------------------------------------------------

def convert_ifelse(pred, true_fn, false_fn, args):
    """ref convert_ifelse: tensor pred -> cond op, python pred -> branch."""
    from ..core.tensor import Tensor
    import jax
    if isinstance(pred, Tensor) and isinstance(pred._data, jax.core.Tracer):
        from ..static.nn import cond
        return cond(pred, lambda: true_fn(*args), lambda: false_fn(*args))
    taken = bool(pred._data) if isinstance(pred, Tensor) else bool(pred)
    return true_fn(*args) if taken else false_fn(*args)


def convert_while_loop(cond_fn, body_fn, args):
    """ref convert_while_loop: tensor condition -> while op, else python."""
    from ..core.tensor import Tensor
    import jax
    first = cond_fn(*args)
    traced = (isinstance(first, Tensor)
              and isinstance(first._data, jax.core.Tracer)) or \
        any(isinstance(a, Tensor) and isinstance(a._data, jax.core.Tracer)
            for a in args)
    if traced:
        from ..static.nn import while_loop
        out = while_loop(cond_fn, lambda *a: tuple(body_fn(*a)), list(args))
        return tuple(out)
    vals = tuple(args)
    while bool(first._data) if isinstance(first, Tensor) else bool(first):
        vals = tuple(body_fn(*vals))
        first = cond_fn(*vals)
    return vals


# ---------------------------------------------------------------------------
# AST transformer (ref ast_transformer.py IfElse/Loop transformers)
# ---------------------------------------------------------------------------

class _StoredNames(ast.NodeVisitor):
    def __init__(self):
        self.names: Set[str] = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Store):
            self.names.add(node.id)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Name):
            self.names.add(node.target.id)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)  # don't descend into nested scopes


def _stored(nodes) -> Set[str]:
    v = _StoredNames()
    for n in nodes:
        v.visit(n)
    return v.names


def _loaded(nodes) -> Set[str]:
    out: Set[str] = set()
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                out.add(sub.id)
    return out


def _certainly_stored(stmt) -> Set[str]:
    """Names DEFINITELY bound after executing stmt (conditional branches count
    only when both sides bind; loops may run zero times -> nothing counts)."""
    if isinstance(stmt, ast.If):
        return (_certain_all(stmt.body) & _certain_all(stmt.orelse))
    if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
        return set()
    if isinstance(stmt, (ast.Try,)):
        return set()
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return _certain_all(stmt.body) | _stored(
            [i.optional_vars for i in stmt.items if i.optional_vars is not None])
    return _stored([stmt])


def _certain_all(stmts) -> Set[str]:
    out: Set[str] = set()
    for s in stmts:
        out |= _certainly_stored(s)
    return out


class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites `if`/`while` whose out-vars are known before the statement.

    Simplifications vs the reference (documented): no `break`/`continue`/
    `return` inside converted bodies, out-vars must be bound before the
    statement (else the statement is left as plain Python)."""

    def __init__(self):
        self._defined: Set[str] = set()
        self._uid = 0

    def _fresh(self, base):
        self._uid += 1
        return f"__jst_{base}_{self._uid}"

    # track CERTAIN sequential definitions (conditionally-bound names must not
    # be read by a converted statement's args tuple -> UnboundLocalError)
    def _note_defined(self, stmt):
        self._defined |= _certainly_stored(stmt)

    def visit_FunctionDef(self, node):
        a = node.args
        params = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
        if a.vararg:
            params.add(a.vararg.arg)
        if a.kwarg:
            params.add(a.kwarg.arg)
        # nested defs get their own scope state (restore the outer one after)
        saved_defined, saved_rest = self._defined, getattr(self, "_rest", [])
        self._defined = params
        new_body = []
        for i, stmt in enumerate(node.body):
            self._rest = node.body[i + 1:]   # lookahead for while out-vars
            res = self.visit(stmt)
            if isinstance(res, list):
                new_body.extend(res)
            elif res is not None:
                new_body.append(res)
            self._note_defined(stmt)
        node.body = new_body
        self._defined = saved_defined
        self._rest = saved_rest
        return node

    @staticmethod
    def _has_escape(nodes) -> bool:
        """Return/break/continue/yield in THIS scope (nested function defs —
        including converted branch fns — have their own scope)."""
        def walk(n):
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                if isinstance(child, (ast.Return, ast.Break, ast.Continue,
                                      ast.Yield, ast.YieldFrom)):
                    return True
                if walk(child):
                    return True
            return False

        for n in nodes:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, (ast.Return, ast.Break, ast.Continue)):
                return True
            if walk(n):
                return True
        return False

    def _make_branch_fn(self, name, out_vars, body):
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=v, ctx=ast.Load()) for v in out_vars],
            ctx=ast.Load()))
        return ast.FunctionDef(
            name=name,
            args=ast.arguments(
                posonlyargs=[], kwonlyargs=[], kw_defaults=[], defaults=[],
                args=[ast.arg(arg=v) for v in out_vars]),
            body=(body or [ast.Pass()]) + [ret],
            decorator_list=[])

    def visit_If(self, node):
        self.generic_visit(node)
        t_stored, f_stored = _stored(node.body), _stored(node.orelse)
        # out-vars: bound before the statement, OR introduced by BOTH branches
        out_vars = sorted(((t_stored | f_stored) & self._defined)
                          | (t_stored & f_stored))
        if not out_vars or self._has_escape(node.body + node.orelse):
            return node
        tname, fname = self._fresh("true"), self._fresh("false")
        tfn = self._make_branch_fn(tname, out_vars, list(node.body))
        ffn = self._make_branch_fn(fname, out_vars, list(node.orelse))

        def arg_of(v):
            if v in self._defined:
                return ast.Name(id=v, ctx=ast.Load())
            return ast.Constant(value=None)  # both branches rebind it

        call = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=v, ctx=ast.Store()) for v in out_vars],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id="__jst_convert_ifelse", ctx=ast.Load()),
                args=[node.test,
                      ast.Name(id=tname, ctx=ast.Load()),
                      ast.Name(id=fname, ctx=ast.Load()),
                      ast.Tuple(elts=[arg_of(v) for v in out_vars],
                                ctx=ast.Load())],
                keywords=[]))
        return [tfn, ffn, call]

    def visit_While(self, node):
        self.generic_visit(node)
        stored = _stored(node.body)
        out_vars = sorted(stored & self._defined)
        if not out_vars or node.orelse or self._has_escape(node.body):
            return node
        # a body-introduced name read AFTER the loop would vanish inside the
        # generated body fn: leave such loops as plain Python (the original
        # tracer error then points the user at the unsupported shape)
        escaping = (stored - self._defined) & _loaded(
            getattr(self, "_rest", []))
        if escaping:
            return node
        cname, bname = self._fresh("cond"), self._fresh("body")
        cfn = ast.FunctionDef(
            name=cname,
            args=ast.arguments(
                posonlyargs=[], kwonlyargs=[], kw_defaults=[], defaults=[],
                args=[ast.arg(arg=v) for v in out_vars]),
            body=[ast.Return(value=node.test)],
            decorator_list=[])
        bfn = self._make_branch_fn(bname, out_vars, list(node.body))
        call = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=v, ctx=ast.Store()) for v in out_vars],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id="__jst_convert_while", ctx=ast.Load()),
                args=[ast.Name(id=cname, ctx=ast.Load()),
                      ast.Name(id=bname, ctx=ast.Load()),
                      ast.Tuple(elts=[ast.Name(id=v, ctx=ast.Load())
                                      for v in out_vars], ctx=ast.Load())],
                keywords=[]))
        return [cfn, bfn, call]


def ast_transform(fn: Callable) -> Callable:
    """Rewrite fn's if/while statements; returns the transformed function
    (raises on unsupported sources — callers fall back to the original)."""
    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    fdef = tree.body[0]
    # strip decorators (the transform runs under to_static already)
    if isinstance(fdef, ast.FunctionDef):
        fdef.decorator_list = []
    tree = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(tree)
    code = compile(tree, filename=f"<dy2static {fn.__name__}>", mode="exec")

    class _Env(dict):
        """Overlay namespace: helper names + closure snapshots resolve here,
        everything else falls through LIVE to the function's real globals (a
        dict copy would freeze later module-level mutations)."""

        def __missing__(self, k):
            return fn.__globals__[k]

    glb = _Env()
    glb["__jst_convert_ifelse"] = convert_ifelse
    glb["__jst_convert_while"] = convert_while_loop
    glb["__builtins__"] = fn.__globals__.get("__builtins__", __builtins__)
    # closure cells snapshot by value (transformed code has no closure);
    # later cell mutations are not observed — a documented limitation
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            glb[name] = cell.cell_contents
    ns: dict = {}
    exec(code, glb, ns)
    new_fn = ns[fn.__name__]
    if isinstance(fn, types.MethodType):
        new_fn = types.MethodType(new_fn, fn.__self__)
    return functools.wraps(fn)(new_fn)


def convert_call(fn):
    """ref convert_call: nested callables pass through (tracing follows them)."""
    return fn


__all__ = ["ast_transform", "convert_ifelse", "convert_while_loop",
           "convert_call"]
