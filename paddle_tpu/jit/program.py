"""Static capture: dygraph -> compiled XLA program.

Reference parity: dy2static (`python/paddle/jit/dy2static/program_translator.py` —
`StaticFunction` :311, `CacheKey` :184, `ConcreteProgram` :1129) and its executor
(`PartialProgramLayer` -> `run_program` op).

TPU-native design: *tracing*, not AST rewriting — the idiomatic JAX capture. A Layer's
forward is functionalized over (params, buffers, inputs); the jaxpr IS the Program IR
(the reference's ProgramDesc / new-IR layer both collapse into it).  Forward runs as one
jitted XLA executable; for training the whole program becomes a single GradNode on the
eager tape whose pullback is a separately-jitted rematerializing VJP — `.backward()`
then costs one compiled backward pass, exactly the run_program_op grad-node pattern.
Buffer mutations (BN running stats, RNG-free side state) are captured as extra outputs
and written back, keeping eager semantics.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd as _ag
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


class CacheKey:
    """Program-cache key from input specs + train flag (reference `CacheKey` :184)."""

    @staticmethod
    def make(args, kwargs, training, with_grad):
        def spec(x):
            if isinstance(x, Tensor):
                return ("T", tuple(x._data.shape), str(x._data.dtype),
                        bool(x.stop_gradient))
            if isinstance(x, (np.ndarray, jnp.ndarray)):
                return ("A", tuple(np.shape(x)), str(np.asarray(x).dtype))
            if isinstance(x, (list, tuple)):
                return tuple(spec(v) for v in x)
            if isinstance(x, dict):
                return tuple(sorted((k, spec(v)) for k, v in x.items()))
            return ("P", x)
        return (spec(args), spec(kwargs), training, with_grad)


def functionalize(fn: Callable, layer: Optional[Layer]):
    """Build (pure_fn, params, buffers): pure_fn(param_datas, buffer_datas, *in_datas)
    -> (flat outputs, out_treedef, new_buffer_datas), executed with the eager tape off
    so ops trace straight into jnp."""
    params: List[Tuple[str, Tensor]] = []
    buffers: List[Tuple[str, Tensor]] = []
    if layer is not None:
        params = list(layer.named_parameters())
        buffers = list(layer.named_buffers())

    def pure_fn(param_datas, buffer_datas, *in_datas):
        saved_p = [p._data for _, p in params]
        saved_b = [b._data for _, b in buffers]
        try:
            for (_, p), d in zip(params, param_datas):
                p._data = d
            for (_, b), d in zip(buffers, buffer_datas):
                b._data = d
            args, kwargs = _unflatten_inputs(in_datas, pure_fn._in_tree)
            with _ag.set_grad_enabled(False):
                out = fn(*args, **kwargs)
            flat_out, tree = _flatten_outputs(out)
            new_buf = [b._data for _, b in buffers]
            pure_fn._out_tree = tree
            return tuple(flat_out) + tuple(new_buf)
        finally:
            for (_, p), d in zip(params, saved_p):
                p._data = d
            for (_, b), d in zip(buffers, saved_b):
                b._data = d

    return pure_fn, params, buffers


def _flatten_inputs(args, kwargs):
    """Split (args, kwargs) into (leaf jnp datas, treedef with Tensor positions)."""
    leaves = []

    def rec(x):
        if isinstance(x, Tensor):
            leaves.append(x._data)
            return ("__leaf__", len(leaves) - 1, x.stop_gradient)
        if isinstance(x, (list, tuple)):
            return type(x)(rec(v) for v in x)
        if isinstance(x, dict):
            return {k: rec(v) for k, v in x.items()}
        return x
    tree = (tuple(rec(a) for a in args), {k: rec(v) for k, v in kwargs.items()})
    return leaves, tree


def _unflatten_inputs(datas, tree):
    def rec(x):
        if isinstance(x, tuple) and len(x) == 3 and x[0] == "__leaf__":
            t = Tensor(datas[x[1]], stop_gradient=x[2])
            return t
        if isinstance(x, tuple):
            return tuple(rec(v) for v in x)
        if isinstance(x, list):
            return [rec(v) for v in x]
        if isinstance(x, dict):
            return {k: rec(v) for k, v in x.items()}
        return x
    args_tree, kw_tree = tree
    return tuple(rec(a) for a in args_tree), {k: rec(v) for k, v in kw_tree.items()}


def _flatten_outputs(out):
    leaves = []

    def rec(x):
        if isinstance(x, Tensor):
            leaves.append(x._data)
            return ("__leaf__", len(leaves) - 1)
        if isinstance(x, (jnp.ndarray, jax.Array)):
            leaves.append(x)
            return ("__leaf__", len(leaves) - 1)
        if isinstance(x, (list, tuple)):
            return type(x)(rec(v) for v in x)
        if isinstance(x, dict):
            return {k: rec(v) for k, v in x.items()}
        return x
    tree = rec(out)
    return leaves, tree


def _unflatten_outputs(leaf_tensors, tree):
    def rec(x):
        if isinstance(x, tuple) and len(x) == 2 and x[0] == "__leaf__":
            return leaf_tensors[x[1]]
        if isinstance(x, tuple):
            return tuple(rec(v) for v in x)
        if isinstance(x, list):
            return [rec(v) for v in x]
        if isinstance(x, dict):
            return {k: rec(v) for k, v in x.items()}
        return x
    return rec(tree)


class ConcreteProgram:
    """One compiled specialization (reference `ConcreteProgram` :1129)."""

    def __init__(self, pure_fn, params, buffers, in_tree, donate=False):
        self.pure_fn = pure_fn
        self.params = params
        self.buffers = buffers
        self.in_tree = in_tree
        self.out_tree = None
        self.n_outputs = None
        self._fwd = jax.jit(pure_fn)
        self._vjp = None  # built lazily for training

    def run(self, in_datas, with_grad, input_tensors):
        self.pure_fn._in_tree = self.in_tree
        p_datas = [p._data for _, p in self.params]
        b_datas = [b._data for _, b in self.buffers]

        if not with_grad:
            flat = self._fwd(p_datas, b_datas, *in_datas)
            return self._postprocess(flat, node=None)

        # Training: whole-program GradNode; pullback = jitted remat VJP.
        if self._vjp is None:
            def vjp_run(pd, bd, ins, cots):
                def fwd_only(pd_, ins_):
                    self.pure_fn._in_tree = self.in_tree
                    return self.pure_fn(pd_, bd, *ins_)
                _, pull = jax.vjp(fwd_only, pd, ins)
                return pull(cots)
            self._vjp = jax.jit(vjp_run)

        flat = self._fwd(p_datas, b_datas, *in_datas)
        n_out = len(flat) - len(self.buffers)
        out_specs = [(tuple(o.shape), o.dtype) for o in flat]

        prog = self
        in_datas_saved = tuple(in_datas)
        pd_saved = tuple(p_datas)
        bd_saved = tuple(b_datas)

        def vjp_fn(cots):
            if not isinstance(cots, tuple):
                cots = (cots,)
            full_cots = list(cots)
            # zero cotangents for buffer outputs
            while len(full_cots) < len(flat):
                i = len(full_cots)
                full_cots.append(jnp.zeros(out_specs[i][0], out_specs[i][1]))
            gp, gins = prog._vjp(pd_saved, bd_saved, in_datas_saved, tuple(full_cots))
            return tuple(gp) + tuple(gins)

        node_inputs = [p for _, p in self.params] + list(input_tensors)

        def vjp_wrap(cots):
            grads = vjp_fn(cots)
            return grads
        node = _ag.GradNode("run_program", vjp_wrap, node_inputs, len(flat), out_specs)
        return self._postprocess(flat, node=node)

    def _postprocess(self, flat, node):
        n_buf = len(self.buffers)
        n_out = len(flat) - n_buf
        out_leaves = flat[:n_out]
        new_buf = flat[n_out:]
        for (_, b), d in zip(self.buffers, new_buf):
            b._data = d
        tensors = []
        for i, o in enumerate(out_leaves):
            t = Tensor(o)
            if node is not None and jnp.issubdtype(o.dtype, jnp.inexact):
                t.stop_gradient = False
                t._grad_node = node
                t._out_index = i
            tensors.append(t)
        tree = self.pure_fn._out_tree
        return _unflatten_outputs(tensors, tree)


class StaticFunction:
    """`@to_static` callable with a program cache (reference `StaticFunction` :311)."""

    def __init__(self, function, input_spec=None, build_strategy=None, backend=None,
                 **kwargs):
        if isinstance(function, Layer):
            self._layer = function
            self._fn = function.forward
            self._bound_instance = function
        else:
            self._layer = getattr(function, "__self__", None)
            self._fn = function
            self._bound_instance = None
        self._input_spec = input_spec
        self._cache: Dict[Any, ConcreteProgram] = {}
        functools.update_wrapper(self, self._fn)

    @property
    def program_cache(self):
        return self._cache

    def concrete_program_specify_input_spec(self, input_spec=None):
        return None

    def __call__(self, *args, **kwargs):
        from .api import _to_static_enabled
        if not _to_static_enabled[0]:
            # enable_to_static(False): run the original dygraph function (the
            # check is per-call so the switch works after decoration too)
            return self._fn(*args, **kwargs)
        layer = self._layer if isinstance(self._layer, Layer) else None
        training = layer.training if layer is not None else False
        with_grad = _ag.is_grad_enabled() and (
            (layer is not None and any(not p.stop_gradient for p in layer.parameters()))
            or any(isinstance(a, Tensor) and not a.stop_gradient for a in args))
        key = CacheKey.make(args, kwargs, training, with_grad)
        in_datas, in_tree = _flatten_inputs(args, kwargs)

        def build():
            pure_fn, params, buffers = functionalize(self._fn, layer)
            pure_fn._in_tree = in_tree
            prog = ConcreteProgram(pure_fn, params, buffers, in_tree)
            self._cache[key] = prog
            return prog

        prog = self._cache.get(key)
        fresh = prog is None
        if fresh:
            prog = build()
        input_tensors = [a for a in args if isinstance(a, Tensor)]
        try:
            return prog.run(in_datas, with_grad, input_tensors)
        except Exception as e:  # dy2static retry on tensor control flow
            import jax
            cf_error = isinstance(
                e, (jax.errors.TracerBoolConversionError,
                    jax.errors.TracerArrayConversionError,
                    jax.errors.ConcretizationTypeError)) or \
                (isinstance(e, ValueError) and "truth value" in str(e).lower())
            if not fresh or not cf_error or \
                    getattr(self, "_ast_transformed", False):
                raise
            # Python `if`/`while` hit a traced tensor: rewrite the source AST
            # to convert_ifelse/convert_while (ref dy2static ast_transformer)
            # and retrace — untransformable sources re-raise the original
            from .dy2static import ast_transform
            try:
                self._fn = ast_transform(self._fn)
            except Exception:
                raise e
            self._ast_transformed = True
            self._cache.pop(key, None)
            prog = build()
            return prog.run(in_datas, with_grad, input_tensors)
