from .api import TranslatedLayer, load, not_to_static, save, to_static  # noqa
from .program import StaticFunction, functionalize  # noqa


from .api import enable_to_static  # noqa


def ignore_module(modules):
    """ref dy2static ignore_module: tracing capture has no AST blacklist; no-op."""
    return None


def set_code_level(level=100, also_to_stdout=False):
    """ref dy2static logging: tracing capture emits no transformed code."""
    return None


def set_verbosity(level=0, also_to_stdout=False):
    return None
from . import dy2static  # noqa  (AST control-flow conversion)
