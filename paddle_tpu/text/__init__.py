"""paddle.text (ref python/paddle/text/): ViterbiDecoder + dataset surface.

The dataset classes (Imdb, Imikolov, ...) download external corpora in the
reference; this build has no network egress, so they exist with the reference
constructor signature and raise a clear pointer at materialization time.
viterbi_decode / ViterbiDecoder are fully implemented (lax.scan DP).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ..nn.layer.layers import Layer

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "ViterbiDecoder", "WMT14", "WMT16", "viterbi_decode"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decoding (ref text/viterbi_decode.py, phi viterbi kernel).

    potentials [B, T, N] emission scores, transition_params [N, N] (or
    [N+2, N+2] with BOS/EOS rows when include_bos_eos_tag), lengths [B].
    Returns (scores [B], paths [B, T]).
    """
    def f(emis, trans, lens):
        B, T, N = emis.shape
        if include_bos_eos_tag:
            # reference layout: trans is [N+2, N+2] with BOS=N, EOS=N+1
            bos, eos = N, N + 1
            start = trans[bos, :N][None]                   # [1, N]
            stop = trans[:N, eos][None]
            tr = trans[:N, :N]
        else:
            start = jnp.zeros((1, N), emis.dtype)
            stop = jnp.zeros((1, N), emis.dtype)
            tr = trans
        alpha0 = emis[:, 0] + start                        # [B, N]

        def step(carry, t):
            alpha, = carry
            # scores[b, i, j] = alpha[b, i] + tr[i, j] + emis[b, t, j]
            s = alpha[:, :, None] + tr[None] + emis[:, t][:, None, :]
            best = jnp.argmax(s, axis=1)                   # [B, N]
            alpha_new = jnp.max(s, axis=1)
            valid = (t < lens)[:, None]
            alpha_new = jnp.where(valid, alpha_new, alpha)
            return (alpha_new,), (best, valid[:, 0])

        (alpha,), (backptrs, valids) = jax.lax.scan(
            step, (alpha0,), jnp.arange(1, T))
        alpha_final = alpha + (stop if include_bos_eos_tag else 0.0)
        scores = jnp.max(alpha_final, axis=-1)
        last_tag = jnp.argmax(alpha_final, axis=-1)        # [B]

        def backtrace(carry, inp):
            tag = carry
            bp, valid = inp                                # bp [B, N]
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            tag_new = jnp.where(valid, prev, tag)
            return tag_new, tag
        # walk backpointers in reverse
        tag_first, tags_rev = jax.lax.scan(
            backtrace, last_tag, (backptrs, valids), reverse=True)
        paths = jnp.concatenate([tag_first[:, None],
                                 jnp.moveaxis(tags_rev, 0, 1)], axis=1)
        return scores, paths.astype(jnp.int64)
    return apply("viterbi_decode", f, potentials, transition_params, lengths)


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class _DownloadDataset:
    _NAME = "dataset"

    def __init__(self, *args, **kwargs):
        raise RuntimeError(
            f"paddle.text.{type(self).__name__} downloads its corpus from the "
            "internet in the reference; this build has no network egress. "
            "Provide the files locally and use paddle.io.Dataset directly.")


class Conll05st(_DownloadDataset):
    pass


class Imdb(_DownloadDataset):
    pass


class Imikolov(_DownloadDataset):
    pass


class Movielens(_DownloadDataset):
    pass


class UCIHousing(_DownloadDataset):
    pass


class WMT14(_DownloadDataset):
    pass


class WMT16(_DownloadDataset):
    pass
