"""paddle.audio.functional (ref python/paddle/audio/functional/functional.py):
mel scales, filterbanks, dct, window functions, dB conversion — all jnp, so
they compose into jitted feature pipelines.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply, _to_data

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct", "get_window"]


def hz_to_mel(freq, htk=False):
    scalar = isinstance(freq, (int, float))
    f = float(freq) if scalar else _to_data(freq)
    if htk:
        out = 2595.0 * (jnp.log10(1.0 + jnp.asarray(f) / 700.0) if not scalar
                        else math.log10(1.0 + f / 700.0))
        return out if scalar else Tensor(out)
    # Slaney scale
    f_min, f_sp = 0.0, 200.0 / 3
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    if scalar:
        return (min_log_mel + math.log(f / min_log_hz) / logstep
                if f >= min_log_hz else (f - f_min) / f_sp)
    f = jnp.asarray(f)
    mels = (f - f_min) / f_sp
    log_t = f >= min_log_hz
    mels = jnp.where(log_t, min_log_mel +
                     jnp.log(jnp.maximum(f, 1e-10) / min_log_hz) / logstep,
                     mels)
    return Tensor(mels)


def mel_to_hz(mel, htk=False):
    scalar = isinstance(mel, (int, float))
    m = float(mel) if scalar else jnp.asarray(_to_data(mel))
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
        return out if scalar else Tensor(out)
    f_min, f_sp = 0.0, 200.0 / 3
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    if scalar:
        return (min_log_hz * math.exp(logstep * (m - min_log_mel))
                if m >= min_log_mel else f_min + f_sp * m)
    freqs = f_min + f_sp * m
    log_t = m >= min_log_mel
    freqs = jnp.where(log_t, min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                      freqs)
    return Tensor(freqs)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    lo = hz_to_mel(float(f_min), htk)
    hi = hz_to_mel(float(f_max), htk)
    mels = jnp.linspace(lo, hi, n_mels)
    return mel_to_hz(Tensor(mels), htk)


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(jnp.linspace(0.0, sr / 2.0, 1 + n_fft // 2))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Mel filterbank [n_mels, 1 + n_fft//2] (ref compute_fbank_matrix)."""
    f_max = f_max or sr / 2.0
    fftfreqs = np.asarray(fft_frequencies(sr, n_fft)._data)
    melfreqs = np.asarray(mel_frequencies(n_mels + 2, f_min, f_max, htk)._data)
    fdiff = np.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0.0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2:n_mels + 2] - melfreqs[:n_mels])
        weights *= enorm[:, None]
    return Tensor(jnp.asarray(weights.astype(np.float32)))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    def f(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
        log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec
    return apply("power_to_db", f, spect)


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II matrix [n_mels, n_mfcc] (ref create_dct)."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    dct = np.cos(np.pi / n_mels * (n + 0.5) * k)      # [n_mfcc, n_mels]
    if norm == "ortho":
        dct[0] *= 1.0 / math.sqrt(2.0)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct.T.astype(np.float32)))


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """hann/hamming/blackman/bartlett/... (ref window.py get_window)."""
    if isinstance(window, tuple):
        name, *args = window
    else:
        name, args = window, []
    M = win_length + (0 if fftbins else -1)
    n = jnp.arange(win_length)
    denom = max(M, 1)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * jnp.cos(2 * jnp.pi * n / denom)
    elif name == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * jnp.pi * n / denom)
    elif name == "blackman":
        w = (0.42 - 0.5 * jnp.cos(2 * jnp.pi * n / denom)
             + 0.08 * jnp.cos(4 * jnp.pi * n / denom))
    elif name == "bartlett":
        w = 1.0 - jnp.abs(2.0 * n / denom - 1.0)
    elif name in ("rect", "rectangular", "boxcar", "ones"):
        w = jnp.ones(win_length)
    elif name == "gaussian":
        std = args[0] if args else 7.0
        w = jnp.exp(-0.5 * ((n - M / 2.0) / std) ** 2)
    else:
        raise ValueError(f"unsupported window: {window!r}")
    return Tensor(w.astype(jnp.float32))
