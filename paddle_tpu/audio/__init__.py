"""paddle.audio — spectral features (ref python/paddle/audio/)."""
from . import features, functional  # noqa

__all__ = ["features", "functional"]
