"""paddle.audio.features (ref python/paddle/audio/features/layers.py):
Spectrogram / MelSpectrogram / LogMelSpectrogram / MFCC layers composed from
paddle.signal.stft + audio.functional — the whole pipeline stays jittable.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ..nn.layer.layers import Layer
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = AF.get_window(window, self.win_length)

    def forward(self, x):
        from ..signal import stft
        spec = stft(x, self.n_fft, self.hop_length, self.win_length,
                    self.window, self.center, self.pad_mode)
        return apply("spec_power",
                     lambda s: jnp.abs(s) ** self.power, spec)


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                       power, center, pad_mode)
        self.fbank = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                             htk, norm)

    def forward(self, x):
        spec = self.spectrogram(x)          # [..., n_fft//2+1, frames]
        fb = self.fbank._data

        def f(s):
            return jnp.einsum("mf,...ft->...mt", fb, s)
        return apply("mel_fbank", f, spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                  power, center, pad_mode, n_mels, f_min,
                                  f_max, htk, norm)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x):
        return AF.power_to_db(self.mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr, n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        n_mels, f_min, f_max, htk, norm,
                                        ref_value, amin, top_db)
        self.dct = AF.create_dct(n_mfcc, n_mels)

    def forward(self, x):
        lm = self.logmel(x)                 # [..., n_mels, frames]
        d = self.dct._data                  # [n_mels, n_mfcc]
        return apply("mfcc_dct",
                     lambda s: jnp.einsum("mk,...mt->...kt", d, s), lm)
