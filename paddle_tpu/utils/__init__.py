from . import dlpack  # noqa
from . import unique_name  # noqa


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(f"{name} is required: {e}")


def run_check():
    """paddle.utils.run_check parity: verify the accelerator works end to end."""
    import jax
    import jax.numpy as jnp
    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    dev = list(y.devices())[0]
    print(f"paddle_tpu is installed successfully! device={dev.platform}:{dev.id}, "
          f"matmul check sum={float(y.sum()):.1f}")


def get_env_info():
    import jax
    return {"jax": jax.__version__, "devices": [str(d) for d in jax.devices()]}
