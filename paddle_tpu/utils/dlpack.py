"""DLPack interop (reference: `fluid/framework/dlpack_tensor.cc`, `paddle.utils.dlpack`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def to_dlpack(x: Tensor):
    return x._data.__dlpack__()


def from_dlpack(capsule) -> Tensor:
    if hasattr(capsule, "__dlpack__") and not isinstance(capsule, Tensor):
        return Tensor(jnp.from_dlpack(capsule))
    if isinstance(capsule, Tensor):
        return capsule
    arr = jax.dlpack.from_dlpack(capsule)
    return Tensor(arr)
