"""paddle.utils.cpp_extension — JIT-build native extensions.

Reference parity: `python/paddle/utils/cpp_extension/` (load() JIT-compiles a
user C++ op into a shared library; CppExtension/CUDAExtension/setup for wheel
builds).

TPU-native: pybind11 isn't vendored, so extensions expose a C ABI consumed via
ctypes (the reference's custom-device plugin ABI, `phi/backends/device_ext.h`,
makes the same choice).  Built artifacts are content-hashed and cached under
the build directory, so repeat loads are instant.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import List, Optional, Sequence


class ExtensionError(RuntimeError):
    pass


def _build_dir(name):
    root = os.environ.get("PADDLE_EXTENSION_DIR") or os.path.join(
        tempfile.gettempdir(), f"paddle_tpu_extensions_{os.getuid()}")
    d = os.path.join(root, name)
    os.makedirs(d, exist_ok=True)
    return d


def load(name: str, sources: Sequence[str], extra_cxx_cflags: Optional[List[str]] = None,
         extra_ldflags: Optional[List[str]] = None, extra_include_paths=None,
         build_directory: Optional[str] = None, verbose: bool = False,
         interpreter=None):
    """JIT-compile C++ sources into a shared library and dlopen it.

    Returns a ctypes.CDLL (C-ABI symbols; the reference returns a python
    module of pybind-registered ops — declare your restypes/argtypes on the
    handle)."""
    srcs = [os.path.abspath(s) for s in sources]
    for s in srcs:
        if not os.path.exists(s):
            raise ExtensionError(f"source not found: {s}")
    cflags = ["-O2", "-fPIC", "-shared", "-std=c++17"] + (extra_cxx_cflags or [])
    for inc in (extra_include_paths or []):
        cflags.append(f"-I{inc}")
    ldflags = (extra_ldflags or []) + ["-lrt", "-lpthread"]
    h = hashlib.sha1()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(cflags + ldflags).encode())
    out_dir = build_directory or _build_dir(name)
    so_path = os.path.join(out_dir, f"{name}_{h.hexdigest()[:12]}.so")
    if not os.path.exists(so_path):
        # build to a temp path and rename: concurrent builders must never
        # dlopen a half-written .so (rename is atomic within the directory)
        tmp_path = f"{so_path}.tmp.{os.getpid()}"
        cmd = ["g++"] + cflags + srcs + ["-o", tmp_path] + ldflags
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise ExtensionError(
                f"building {name} failed:\n{proc.stderr[-4000:]}")
        os.rename(tmp_path, so_path)
    return ctypes.CDLL(so_path)


class CppExtension:
    """setup()-style extension description (ref cpp_extension.CppExtension)."""

    def __init__(self, sources, *args, **kwargs):
        self.sources = sources
        self.kwargs = kwargs


def CUDAExtension(sources, *args, **kwargs):
    raise ExtensionError(
        "CUDAExtension has no TPU analog — device kernels are Pallas "
        "(paddle_tpu/incubate/kernels); host-side native code uses CppExtension")


class BuildExtension:
    @classmethod
    def with_options(cls, **kwargs):
        return cls


def setup(name=None, ext_modules=None, **kwargs):
    """Build every CppExtension immediately into the cache (wheel-less JIT
    variant of the reference setup())."""
    built = []
    for ext in (ext_modules or []):
        if isinstance(ext, CppExtension):
            built.append(load(name or "ext", ext.sources, **{
                k: v for k, v in ext.kwargs.items()
                if k in ("extra_cxx_cflags", "extra_ldflags",
                         "extra_include_paths")}))
    return built


def get_build_directory():
    return _build_dir("")


__all__ = ["load", "CppExtension", "CUDAExtension", "BuildExtension", "setup",
           "get_build_directory", "ExtensionError"]
