"""paddle.flops (reference: `python/paddle/hapi/dynamic_flops.py`)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def flops(net, input_size, custom_ops=None, print_detail=False):
    total = [0]
    hooks = []

    def conv_hook(layer, inputs, outputs):
        out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
        w = layer.weight
        k = int(np.prod(w.shape[1:]))
        total[0] += 2 * k * int(np.prod(out.shape))

    def linear_hook(layer, inputs, outputs):
        w = layer.weight
        out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
        total[0] += 2 * int(np.prod(out.shape)) * w.shape[0]

    from ..nn.layer.common import Linear
    from ..nn.layer.conv import _ConvND
    for _, layer in net.named_sublayers(include_self=True):
        if isinstance(layer, _ConvND):
            hooks.append(layer.register_forward_post_hook(conv_hook))
        elif isinstance(layer, Linear):
            hooks.append(layer.register_forward_post_hook(linear_hook))

    x = Tensor(np.zeros([1 if (s is None or s == -1) else s for s in input_size],
                        np.float32))
    was_training = net.training
    net.eval()
    try:
        net(x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()
    if print_detail:
        print(f"FLOPs: {total[0]:,}")
    return total[0]
