"""hapi callbacks (reference: `python/paddle/hapi/callbacks.py`)."""
from __future__ import annotations

import os
import time

import numpy as np


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def fire(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return fire
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose >= 2 and step % self.log_freq == 0:
            items = ", ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                              if isinstance(v, float))
            print(f"Epoch {self.epoch} step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose >= 1:
            dt = time.time() - self._t0
            items = ", ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                              if isinstance(v, float))
            print(f"Epoch {epoch} done in {dt:.1f}s: {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.best = None
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        better = (self.best is None or
                  (self.mode == "min" and cur < self.best - self.min_delta) or
                  (self.mode == "max" and cur > self.best + self.min_delta))
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        opt = self.model._optimizer
        if opt is not None and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s is not None and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s is not None and self.by_epoch:
            s.step()


class VisualDL(Callback):
    """Scalar logger (reference writes VisualDL records; here JSONL, zero-dep)."""

    def __init__(self, log_dir="./log"):
        self.log_dir = log_dir
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        import json
        os.makedirs(self.log_dir, exist_ok=True)
        self._step += 1
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            f.write(json.dumps({"step": self._step,
                                **{k: v for k, v in (logs or {}).items()
                                   if isinstance(v, (int, float))}}) + "\n")


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.best = None
        self.wait = 0
        self.min_lr = min_lr
        self.mode = "max" if (mode == "auto" and "acc" in monitor) else "min"

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        better = (self.best is None or
                  (self.mode == "min" and cur < self.best) or
                  (self.mode == "max" and cur > self.best))
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                opt = self.model._optimizer
                try:
                    opt.set_lr(max(opt.get_lr() * self.factor, self.min_lr))
                except RuntimeError:
                    pass
                self.wait = 0
