"""paddle.summary (reference: `python/paddle/hapi/model_summary.py`)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def summary(net, input_size=None, dtypes=None, input=None):
    rows = []
    hooks = []

    def make_hook(name):
        def hook(layer, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
            n_params = sum(int(np.prod(p.shape)) for p in layer._parameters.values()
                           if p is not None)
            shape = list(out.shape) if isinstance(out, Tensor) else "?"
            rows.append((name, type(layer).__name__, shape, n_params))
        return hook

    for name, layer in net.named_sublayers(include_self=False):
        if not layer._sub_layers:  # leaves only
            hooks.append(layer.register_forward_post_hook(make_hook(name)))

    if input is not None:
        x = input if isinstance(input, (list, tuple)) else [input]
    else:
        if isinstance(input_size, tuple) and input_size and \
                isinstance(input_size[0], (tuple, list)):
            shapes = input_size
        else:
            shapes = [input_size]
        x = [Tensor(np.zeros([1 if (s is None or s == -1) else s for s in shape],
                             np.float32)) for shape in shapes]
    was_training = net.training
    net.eval()
    try:
        net(*x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    line = "-" * 80
    print(line)
    print(f"{'Layer (type)':<35} {'Output Shape':<25} {'Param #':<12}")
    print(line)
    for name, tname, shape, n in rows:
        print(f"{name + ' (' + tname + ')':<35} {str(shape):<25} {n:<12}")
    print(line)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}
