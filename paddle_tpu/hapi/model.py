"""hapi Model (reference: `python/paddle/hapi/model.py` — Keras-like
fit/evaluate/predict over a Layer)."""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..io import DataLoader
from .callbacks import CallbackList, ProgBarLogger


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else \
            ([metrics] if metrics else [])

    # ---- single-batch ops ----
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outs = self.network(*[_to_tensor(x) for x in inputs])
        losses = self._compute_loss(outs, labels)
        losses.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outs, labels)
        return [float(losses.numpy())] + metrics

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outs = self.network(*[_to_tensor(x) for x in inputs])
        losses = self._compute_loss(outs, labels)
        metrics = self._update_metrics(outs, labels)
        return [float(losses.numpy())] + metrics

    @no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outs = self.network(*[_to_tensor(x) for x in inputs])
        return [o.numpy() if isinstance(o, Tensor) else o
                for o in (outs if isinstance(outs, (list, tuple)) else [outs])]

    def _compute_loss(self, outs, labels):
        if self._loss is None:
            return outs if isinstance(outs, Tensor) else outs[0]
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        labels = [_to_tensor(l) for l in labels]
        out_list = outs if isinstance(outs, (list, tuple)) else [outs]
        return self._loss(*out_list, *labels)

    def _update_metrics(self, outs, labels):
        res = []
        out0 = outs[0] if isinstance(outs, (list, tuple)) else outs
        lab0 = labels[0] if isinstance(labels, (list, tuple)) else labels
        for m in self._metrics:
            correct = m.compute(out0, _to_tensor(lab0))
            r = m.update(correct.numpy() if isinstance(correct, Tensor) else correct)
            res.append(r if not isinstance(r, (list, tuple)) else r[0])
        return res

    # ---- loops ----
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None, **kw):
        train_loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                       drop_last=drop_last, num_workers=num_workers)
        eval_loader = None
        if eval_data is not None:
            eval_loader = eval_data if isinstance(eval_data, DataLoader) else \
                DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)
        cbks = CallbackList((callbacks or []) + ([ProgBarLogger(log_freq, verbose)]
                                                if verbose else []))
        cbks.set_model(self)
        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cbks.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                         "metrics": self._metrics_names()})
        cbks.on_train_begin()
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, data in enumerate(train_loader):
                cbks.on_train_batch_begin(step)
                ins, labs = _split_batch(data)
                vals = self.train_batch(ins, labs)
                logs = dict(zip(self._metrics_names(), vals))
                logs["step"] = step
                cbks.on_train_batch_end(step, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0, _callbacks=cbks)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
        cbks.on_train_end()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, _callbacks=None, **kw):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        logs = {}
        total = 0.0
        n = 0
        for data in loader:
            ins, labs = _split_batch(data)
            vals = self.eval_batch(ins, labs)
            total += vals[0]
            n += 1
            logs = dict(zip(self._metrics_names(), vals))
        logs["loss"] = total / max(n, 1)
        for m in self._metrics:
            logs[m.name()] = m.accumulate()
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                callbacks=None, verbose=1, **kw):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size, num_workers=num_workers)
        outputs = []
        for data in loader:
            ins, _ = _split_batch(data, has_label=False)
            try:
                outputs.append(self.predict_batch(ins))
            except TypeError:
                # dataset yields (inputs..., label): drop the trailing label
                outputs.append(self.predict_batch(ins[:-1]))
        if stack_outputs:
            k = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(k)]
        return outputs

    def _metrics_names(self):
        return ["loss"] + [m.name() for m in self._metrics]

    # ---- persistence ----
    def save(self, path, training=True):
        from ..framework.io import save as psave
        psave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            psave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as pload
        self.network.set_state_dict(pload(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(pload(path + ".pdopt"))

    def parameters(self, *a, **kw):
        return self.network.parameters(*a, **kw)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _s
        return _s(self.network, input_size)


def _to_tensor(x):
    if isinstance(x, Tensor) or x is None:
        return x
    return Tensor(np.asarray(x))


def _split_batch(data, has_label=True):
    if isinstance(data, (list, tuple)):
        if has_label and len(data) >= 2:
            return list(data[:-1]), data[-1]
        return list(data), None
    return [data], None
