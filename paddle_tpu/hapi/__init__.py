from .callbacks import (Callback, EarlyStopping, LRScheduler, ModelCheckpoint,  # noqa
                        ProgBarLogger, VisualDL)
from .model import Model  # noqa
from .summary import summary  # noqa
from .flops import flops  # noqa
