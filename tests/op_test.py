"""OpTest harness (reference: `test/legacy_test/eager_op_test.py:379`).

Each op test supplies a callable + numpy reference; `check_output` runs the op in
eager AND under to_static capture and compares both against numpy (dual-mode parity,
the reference's dygraph/static check); `check_grad` does numeric-vs-analytic gradient
checking.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def check_output(fn, np_fn, inputs, atol=1e-5, rtol=1e-5, check_static=True):
    tensors = [paddle.to_tensor(v) for v in inputs]
    out = fn(*tensors)
    expect = np_fn(*inputs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    expects = expect if isinstance(expect, (tuple, list)) else [expect]
    for o, e in zip(outs, expects):
        np.testing.assert_allclose(np.asarray(o.numpy(), np.float64),
                                   np.asarray(e, np.float64), atol=atol, rtol=rtol)
    if check_static:
        static_fn = paddle.jit.to_static(lambda *ts: fn(*ts))
        sout = static_fn(*tensors)
        souts = sout if isinstance(sout, (tuple, list)) else [sout]
        for o, e in zip(souts, expects):
            np.testing.assert_allclose(np.asarray(o.numpy(), np.float64),
                                       np.asarray(e, np.float64), atol=atol, rtol=rtol)


def check_grad(fn, inputs, input_idx=0, eps=1e-3, atol=1e-2, rtol=1e-2):
    """Numeric vs analytic gradient on a scalarized output."""
    tensors = [paddle.to_tensor(np.asarray(v, np.float32), stop_gradient=(i != input_idx))
               for i, v in enumerate(inputs)]
    out = fn(*tensors)
    loss = out.sum() if out.size > 1 else out
    loss.backward()
    analytic = tensors[input_idx].grad.numpy().astype(np.float64)

    base = np.asarray(inputs[input_idx], np.float64)
    numeric = np.zeros_like(base)
    flat = base.reshape(-1)
    num_flat = numeric.reshape(-1)

    def eval_at(vals):
        args = [np.asarray(v, np.float32) for v in inputs]
        args[input_idx] = vals.reshape(base.shape).astype(np.float32)
        ts = [paddle.to_tensor(a) for a in args]
        o = fn(*ts)
        return float(np.sum(o.numpy().astype(np.float64)))

    for i in range(flat.size):
        plus = flat.copy()
        plus[i] += eps
        minus = flat.copy()
        minus[i] -= eps
        num_flat[i] = (eval_at(plus) - eval_at(minus)) / (2 * eps)
    np.testing.assert_allclose(analytic, numeric, atol=atol, rtol=rtol)
