"""KV tiering (ISSUE 15): device -> host prefix spill, one-scatter session
restore, the rolling-hash partial-page index, the disk level, fault
degradation, and the unified host-pool accounting.

The load-bearing bars:
- byte-exact greedy parity for a session resumed from the host tier (and
  from a `spill_dir` disk tier) vs the undisturbed engine AND vs the full
  re-prefill (`kv_tier=False`) baseline;
- the eviction cascade device -> host -> disk -> drop keeps
  `check_invariants` green with zero leaked pages at every level;
- `FaultPlan.fail_d2h` degrades spill -> drop and `fail_h2d` degrades
  restore -> re-prefill, both parity-lossless;
- `host_pool_room` counts spilled prefix pages against the same ceiling as
  preemption swap parking, and `tier_make_room` reclaims tier room for live
  victims;
- the multi-turn bench: returning-session prefill drops >= 50% and TTFT p50
  improves vs --no-kv-tier on the same stream, byte-exact parity, zero new
  compiled programs (spill/restore reuse the <= 2 swap bucket).
"""
from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.inference.cache import PagedKVCache
from paddle_tpu.inference.engine import LLMEngine
from paddle_tpu.inference.faults import FaultPlan
from paddle_tpu.models import gpt as G


@pytest.fixture(scope="module")
def cfg():
    return G.gpt_tiny(64)


@pytest.fixture(scope="module")
def params(cfg):
    return G.init_params(cfg, jax.random.key(0))


def _engine(params, cfg, **kw):
    base = dict(num_slots=2, page_size=8, num_pages=9, max_model_len=64,
                prefill_chunk=16, seed=3, swap_pool_pages=64)
    base.update(kw)
    return LLMEngine(params, cfg, **base)


def _session_stream(eng, rng_seed=7, churn=6):
    """Turn 1 of a session, distinct-prompt churn that evicts its pages,
    then the returning turn (prompt + reply + fresh tokens).  Returns
    (outputs keyed oldest-first, returning-turn output)."""
    rng = np.random.RandomState(rng_seed)
    shared = rng.randint(0, eng.config.vocab_size, (20,)).astype(np.int32)
    outs = {}
    r1 = eng.add_request(shared, max_new_tokens=5)
    outs.update(eng.run())
    for _ in range(churn):
        eng.add_request(rng.randint(0, eng.config.vocab_size, (30,))
                        .astype(np.int32), max_new_tokens=4)
    outs.update(eng.run())
    t2 = np.concatenate([shared, np.asarray(outs[r1].token_ids, np.int32),
                         rng.randint(0, eng.config.vocab_size, (4,))
                         .astype(np.int32)])
    r2 = eng.add_request(t2, max_new_tokens=5)
    outs.update(eng.run())
    return outs, outs[r2]


# ---------------------------------------------------------------------------
# resumed-from-host parity + counters
# ---------------------------------------------------------------------------

def test_host_restore_parity_and_counters(params, cfg):
    """A returning session whose pages were LRU-evicted restores from the
    host tier with ONE scatter: tokens byte-identical to both the
    drop-on-evict baseline (full re-prefill) and a direct `generate`, with
    the spill/restore counters moving and zero page leaks."""
    eng = _engine(params, cfg)
    outs, ret = _session_stream(eng)
    base_eng = _engine(params, cfg, kv_tier=False)
    base_outs, base_ret = _session_stream(base_eng)
    for a, b in zip(sorted(outs), sorted(base_outs)):
        assert outs[a].token_ids == base_outs[b].token_ids
    ref = G.generate(params, jnp.asarray(ret.prompt)[None], cfg,
                     max_new_tokens=5)
    np.testing.assert_array_equal(ret.tokens, np.asarray(ref[0]))

    st, base_st = eng.stats(), base_eng.stats()
    assert st["kv_tier"]["enabled"] and not base_st["kv_tier"]["enabled"]
    assert st["kv_tier"]["spills"] > 0
    assert st["kv_tier"]["restores"] >= 1
    assert st["kv_tier"]["restored_tokens"] >= 16     # >= 2 full pages
    assert base_st["kv_tier"]["spills"] == 0
    # the restored tokens were NOT re-prefilled: the tier pass computes less
    assert st["prefilled_tokens"] < base_st["prefilled_tokens"]
    # spill/restore reuse the two swap executables — nothing new compiles
    assert st["swap_executables"] <= 2
    assert st["decode_executables"] + st["verify_executables"] == 1
    eng.cache.check_invariants()
    assert eng.cache.swapped_page_count == 0


def test_restore_from_spill_dir_parity(params, cfg, tmp_path):
    """With a tight host budget and `spill_dir`, over-budget tier content
    cascades to disk and restores from there transparently — same tokens as
    the re-prefill baseline."""
    eng = _engine(params, cfg, swap_pool_pages=6, spill_dir=str(tmp_path))
    outs, ret = _session_stream(eng)
    base_eng = _engine(params, cfg, kv_tier=False)
    base_outs, _ = _session_stream(base_eng)
    for a, b in zip(sorted(outs), sorted(base_outs)):
        assert outs[a].token_ids == base_outs[b].token_ids
    st = eng.stats()
    assert st["kv_tier"]["disk_spills"] > 0
    assert st["kv_tier"]["restores"] >= 1
    assert st["kv_tier"]["pages_host"] <= 6           # budget respected
    eng.cache.check_invariants()


def test_eviction_cascade_to_drop_no_leaks(params, cfg, tmp_path):
    """device -> host -> disk -> drop: with a capped disk level the oldest
    spilled prefixes fall off the end; every level's accounting stays exact
    under check_invariants and nothing leaks."""
    eng = _engine(params, cfg, swap_pool_pages=4, spill_dir=str(tmp_path),
                  spill_disk_pages=3)
    rng = np.random.RandomState(11)
    for _ in range(10):
        eng.add_request(rng.randint(0, cfg.vocab_size, (30,))
                        .astype(np.int32), max_new_tokens=4)
        eng.run()
        eng.cache.check_invariants()
    st = eng.stats()["kv_tier"]
    assert st["pages_host"] <= 4
    assert st["pages_disk"] <= 3
    assert st["disk_spills"] > 0 and st["tier_drops"] > 0
    # drop really deletes the files
    import os
    assert len(os.listdir(str(tmp_path))) == eng.cache.tier_pages_disk
    eng.cache.check_invariants()


def test_no_tier_when_disabled_or_unbudgeted(params, cfg):
    """kv_tier=False, prefix_cache=False, and swap_pool_pages=0 all disable
    tiering cleanly: evictions drop as in PR 10, stats say so."""
    for kw in (dict(kv_tier=False), dict(prefix_cache=False),
               dict(swap_pool_pages=0)):
        eng = _engine(params, cfg, **kw)
        assert not eng.kv_tier
        rng = np.random.RandomState(1)
        for _ in range(4):
            eng.add_request(rng.randint(0, cfg.vocab_size, (30,))
                            .astype(np.int32), max_new_tokens=3)
        eng.run()
        st = eng.stats()["kv_tier"]
        assert st["spills"] == 0 and st["pages_host"] == 0
        eng.cache.check_invariants()


# ---------------------------------------------------------------------------
# rolling-hash partial-page index
# ---------------------------------------------------------------------------

def test_rolling_hash_partial_tail_of_full_page():
    """A prompt sharing only a partial tail of a cached FULL page COW-copies
    the matched fraction — the case the PR-2 exact-content index could never
    hit (it only matched pages registered under exactly that partial
    content)."""
    mgr = PagedKVCache(num_pages=16, page_size=4, num_slots=4,
                       max_pages_per_slot=8)
    tok = np.arange(12, dtype=np.int32)             # 3 full pages
    mgr.allocate_prefixed(0, 12, tok)
    mgr.register_prefix(0, tok, 12)
    # new prompt: first page + HALF the second page, then diverges
    div = np.concatenate([tok[:6], np.asarray([77, 77, 77, 77], np.int32)])
    row, m, cow = mgr.allocate_prefixed(1, 12, div)
    assert m == 6                                   # 4 full + 2 partial
    assert cow is not None and cow[0] == mgr.slot_pages(0)[1]
    # divergent tail beyond the verified prefix does not match
    bad = np.concatenate([tok[:4], np.asarray([9, 9, 9], np.int32)])
    _, m2, cow2 = mgr.allocate_prefixed(2, 8, bad)
    assert m2 == 4 and cow2 is None
    mgr.check_invariants()


def test_rolling_hash_engine_parity(params, cfg):
    """Engine-level: a request sharing a partial tail of a cached page is
    token-identical to `generate` (the COW'd fraction is real KV), and the
    partial_page_hits counter moves."""
    eng = _engine(params, cfg)
    rng = np.random.RandomState(5)
    donor = rng.randint(0, cfg.vocab_size, (24,)).astype(np.int32)
    eng.add_request(donor, max_new_tokens=3)
    eng.run()
    # shares donor's first 12 tokens: page 1 full + half of page 2
    probe = np.concatenate([donor[:12],
                            rng.randint(0, cfg.vocab_size, (6,))
                            .astype(np.int32)])
    rid = eng.add_request(probe, max_new_tokens=5)
    outs = eng.run()
    ref = G.generate(params, jnp.asarray(probe)[None], cfg, max_new_tokens=5)
    np.testing.assert_array_equal(outs[rid].tokens, np.asarray(ref[0]))
    assert outs[rid].cached_tokens == 12
    assert eng.stats()["kv_tier"]["partial_page_hits"] >= 1
    eng.cache.check_invariants()


# ---------------------------------------------------------------------------
# fault degradation: spill -> drop, restore -> re-prefill
# ---------------------------------------------------------------------------

def test_fail_d2h_degrades_spill_to_drop(params, cfg):
    """Every spill d2h copy fails: nodes drop from the index (no restores
    ever), outputs identical to the no-tier baseline, nothing leaks."""
    eng = _engine(params, cfg, fault_plan=FaultPlan(fail_d2h=1000))
    outs, _ = _session_stream(eng)
    base_eng = _engine(params, cfg, kv_tier=False)
    base_outs, _ = _session_stream(base_eng)
    for a, b in zip(sorted(outs), sorted(base_outs)):
        assert outs[a].token_ids == base_outs[b].token_ids
    st = eng.stats()["kv_tier"]
    assert st["spills"] == 0 and st["restores"] == 0
    assert st["pages_host"] == 0 and st["pages_disk"] == 0
    eng.cache.check_invariants()


def test_fail_h2d_degrades_restore_to_reprefill(params, cfg):
    """Spills land, but every restore h2d fails: the matched nodes drop and
    the request re-prefills — same tokens, no partial restore ever visible,
    zero leaks."""
    eng = _engine(params, cfg, fault_plan=FaultPlan(fail_h2d=1000))
    outs, _ = _session_stream(eng)
    base_eng = _engine(params, cfg, kv_tier=False)
    base_outs, _ = _session_stream(base_eng)
    for a, b in zip(sorted(outs), sorted(base_outs)):
        assert outs[a].token_ids == base_outs[b].token_ids
    st = eng.stats()["kv_tier"]
    assert st["spills"] > 0
    assert st["restores"] == 0 and st["restored_tokens"] == 0
    eng.cache.check_invariants()
    assert eng.cache.swapped_page_count == 0


# ---------------------------------------------------------------------------
# unified host pool: room accounting + reclamation for live victims
# ---------------------------------------------------------------------------

def test_host_pool_room_counts_tier_pages(params, cfg):
    """Spilled prefix pages consume the SAME budget as preemption swap
    parking: host_pool_room reflects them, and tier_make_room reclaims
    (drops, with no disk level) room on demand."""
    eng = _engine(params, cfg, swap_pool_pages=8)
    rng = np.random.RandomState(2)
    for _ in range(5):
        eng.add_request(rng.randint(0, cfg.vocab_size, (30,))
                        .astype(np.int32), max_new_tokens=3)
        eng.run()
    mgr = eng.cache
    held = mgr.tier_pages_host
    assert held > 0
    assert mgr.host_pool_room(8) == 8 - held
    freed = mgr.tier_make_room(2)
    assert freed == 2
    assert mgr.host_pool_room(8) == 8 - held + 2
    mgr.check_invariants()


def test_preemption_swap_reclaims_tier_room(params, cfg):
    """preempt="swap" with the host pool full of spilled prefixes: the
    victim still parks — live work evicts cached prefixes from the unified
    pool instead of degrading to recompute."""
    prompts = [np.arange(i * 7, i * 7 + 20, dtype=np.int32) % cfg.vocab_size
               for i in range(6)]
    eng = _engine(params, cfg, num_slots=6, prefill_chunk=8,
                  admission="optimistic", preempt="swap", swap_pool_pages=8)
    for p in prompts:
        eng.add_request(p.astype(np.int32), max_new_tokens=24)
    eng.run()
    st = eng.stats()
    assert st["preemptions"] > 0
    assert st["preempt_swaps"] > 0      # parking never starved by the tier
    eng.cache.check_invariants()
    assert eng.cache.swapped_page_count == 0


# ---------------------------------------------------------------------------
# the multi-turn bench: the ISSUE-15 acceptance bar
# ---------------------------------------------------------------------------

def test_bench_multi_turn_tier_acceptance(params, cfg):
    """CPU-smoke --multi-turn: returning-session prefilled tokens drop
    >= 50% and returning TTFT p50 improves vs --no-kv-tier on the same
    stream, with byte-exact greedy parity and zero new compiled programs
    (decode-side 1, swap bucket <= 2) — and the current-schema trajectory
    row built from the run passes schema + floors."""
    from bench_serve import run_serve_bench
    from tools.check_bench import bench_row, check_floors, validate_row

    kw = dict(config=cfg, params=params, num_requests=12, num_slots=4,
              page_size=8, max_model_len=64, max_new_tokens=6,
              prefill_chunk=8, multi_turn=3, seed=0)
    tier = run_serve_bench(kv_tier=True, **kw)
    base = run_serve_bench(kv_tier=False, **kw)
    assert tier["outputs_digest"] == base["outputs_digest"]
    assert tier["resume_hits"] > 0 and tier["resume_restored_tokens"] > 0
    drop = 1.0 - tier["returning_prefilled_tokens"] / \
        max(base["returning_prefilled_tokens"], 1)
    assert drop >= 0.5, (tier["returning_prefilled_tokens"],
                         base["returning_prefilled_tokens"])
    assert tier["returning_ttft_p50_ms"] < base["returning_ttft_p50_ms"]
    assert tier["decode_executables"] + tier["verify_executables"] == 1
    assert tier["swap_executables"] <= 2

    stats = dict(tier)
    stats["kv_tier_parity"] = \
        tier["outputs_digest"] == base["outputs_digest"]
    stats["returning_prefilled_drop"] = round(drop, 4)
    row = bench_row(stats)
    assert row["schema_version"] == 5
    assert validate_row(row) == []
    assert check_floors(row) == []
    assert row["mode"]["kv_tier"] is True and row["mode"]["multi_turn"] == 3
    assert row["parity"]["kv_tier_parity"] is True


def test_check_bench_v1_rows_still_parse():
    """Old trajectory rows (schema v1) keep validating against the v1 axis
    sets; unknown versions fail loudly."""
    from tools.check_bench import (MODE_AXES_V1, PERF_KEYS_V1, validate_row)
    v1 = {"schema_version": 1, "t": 1.0,
          "mode": {k: None for k in MODE_AXES_V1},
          "perf": {k: None for k in PERF_KEYS_V1},
          "parity": {}}
    v1["perf"]["decode_tokens_per_sec_per_chip"] = 100.0
    assert validate_row(v1) == []
    v9 = dict(v1, schema_version=9)
    assert any("schema_version" in e for e in validate_row(v9))
