"""to_static capture tests (reference: `test/dygraph_to_static/`)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def rnd(*s):
    return np.random.RandomState(3).rand(*s).astype(np.float32)


def test_function_to_static():
    @paddle.jit.to_static
    def f(x, y):
        return paddle.matmul(x, y) + 1.0

    a = paddle.to_tensor(rnd(3, 4))
    b = paddle.to_tensor(rnd(4, 5))
    out = f(a, b)
    np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy() + 1.0, rtol=1e-5)
    # cache: second call same shapes hits the same program
    f(a, b)
    assert len(f.program_cache) == 1
    # new shape -> new specialization
    f(paddle.to_tensor(rnd(2, 4)), b)
    assert len(f.program_cache) == 2


def test_layer_to_static_matches_eager():
    net = nn.Sequential(nn.Linear(4, 16), nn.GELU(), nn.Linear(16, 2))
    x = paddle.to_tensor(rnd(5, 4))
    eager = net(x).numpy()
    paddle.jit.to_static(net)
    static = net(x).numpy()
    np.testing.assert_allclose(static, eager, rtol=1e-5, atol=1e-6)


def test_to_static_training_grads_match_eager():
    def build():
        paddle.seed(42)
        return nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))

    x = paddle.to_tensor(rnd(6, 4))

    net1 = build()
    net1(x).sum().backward()
    eager_grad = net1[0].weight.grad.numpy()

    net2 = build()
    paddle.jit.to_static(net2)
    net2(x).sum().backward()
    static_grad = net2[0].weight.grad.numpy()

    np.testing.assert_allclose(static_grad, eager_grad, rtol=1e-4, atol=1e-6)


def test_buffer_mutation_under_capture():
    bn = nn.BatchNorm1D(4)
    paddle.jit.to_static(bn)
    x = paddle.to_tensor(rnd(8, 4) * 3)
    bn.train()
    before = bn._mean.numpy().copy()
    bn(x)
    after = bn._mean.numpy()
    assert not np.allclose(before, after)  # running stats updated through jit
