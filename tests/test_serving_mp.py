"""Multi-chip (tensor-parallel) serving: mp-sharded LLMEngine vs single-chip.

The mp serving path (PR "Multi-chip serving") is a pure partitioning of the
same computation — Megatron-sharded serving params, page pool sharded on its
KVH axis, paged attention per-chip on the local head slice — so greedy
outputs must be TOKEN-IDENTICAL to single-chip serving on the same request
stream, with every scheduler feature (prefix cache, COW, chunked prefill,
speculative decoding, abort) unchanged.  Runs on 8 forced CPU host devices
(tests/conftest.py).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import gpt as G
from paddle_tpu.inference.engine import LLMEngine
from paddle_tpu.incubate.kernels.paged_attention import (
    paged_attention_decode_mp, paged_attention_xla,
    paged_prefill_attention_mp, paged_prefill_attention_xla)
from paddle_tpu.parallel.hybrid import serving_mesh

TINY = G.gpt_tiny(128)


@pytest.fixture(scope="module")
def params():
    return G.init_params(TINY, jax.random.key(0))


def _stream(seed=7, n=10):
    """Mixed stream: random prompts + a shared prefix (full-page shares, a
    bare-prefix donor, and non-aligned tails so COW fires)."""
    rng = np.random.RandomState(seed)
    shared = rng.randint(0, TINY.vocab_size, (20,)).astype(np.int32)
    prompts = []
    for i in range(n):
        if i % 3 == 0:
            tail = int(rng.randint(0, 8))
            ext = rng.randint(0, TINY.vocab_size, (tail,)).astype(np.int32)
            prompts.append(np.concatenate([shared, ext]) if tail
                           else shared.copy())
        else:
            prompts.append(rng.randint(0, TINY.vocab_size,
                                       (rng.randint(1, 50),)).astype(np.int32))
    return prompts


def _run(params, config, mp, spec_len, prompts, chunk=16, abort_rid=None):
    eng = LLMEngine(params, config, num_slots=4, page_size=8,
                    max_model_len=64, prefill_chunk=chunk, prefix_cache=True,
                    spec_len=spec_len, mp=mp)
    rids = [eng.add_request(p, max_new_tokens=6) for p in prompts]
    if abort_rid is not None:
        for _ in range(3):
            eng.step()
        eng.abort(rids[abort_rid])
    outs = eng.run()
    eng.cache.check_invariants()
    return {r: tuple(o.token_ids) for r, o in outs.items()}, eng.stats()


@pytest.fixture(scope="module")
def single_chip(params):
    out, _ = _run(params, TINY, 1, 0, _stream())
    return out


# ---------------------------------------------------------------------------
# engine token parity: mp vs single chip
# ---------------------------------------------------------------------------

def test_mp2_greedy_token_parity_chunked_prefix(params, single_chip):
    """mp=2, prefix cache on, chunked prefill on, spec off: byte-identical
    greedy tokens, one decode-side program, pool invariants clean."""
    out, st = _run(params, TINY, 2, 0, _stream())
    assert out == single_chip
    assert st["mp"] == 2
    assert st["decode_executables"] + st["verify_executables"] <= 2
    assert st["prefill_executables"] <= 2
    assert st["prefix_hit_requests"] > 0      # the mp run still shares pages


def test_mp2_spec_on_token_parity(params, single_chip):
    """mp=2 with speculative decoding: greedy acceptance stays lossless under
    tensor parallelism (verify + decode partitioned identically)."""
    out, st = _run(params, TINY, 2, 3, _stream())
    assert out == single_chip
    assert st["decode_executables"] + st["verify_executables"] <= 2
    assert st["spec_drafted_tokens"] >= 0     # lane exercised (stream-dep.)


@pytest.mark.slow
def test_mp4_spec_token_parity(params, single_chip):
    """mp=4 (1 kv head per chip): same stream, same tokens."""
    out, st = _run(params, TINY, 4, 3, _stream())
    assert out == single_chip
    assert st["mp"] == 4
    assert st["decode_executables"] + st["verify_executables"] <= 2


@pytest.mark.slow
def test_mp2_bucketed_prefill_parity(params):
    """Legacy bucketed one-shot prefill under mp (head-sharded dense flash
    via shard_map) matches single-chip bucketed serving."""
    base, _ = _run(params, TINY, 1, 0, _stream(seed=9, n=6), chunk=None)
    out, st = _run(params, TINY, 2, 0, _stream(seed=9, n=6), chunk=None)
    assert out == base


@pytest.mark.slow
def test_mp2_llama_gqa_parity():
    """GQA (llama preset, 2 kv heads -> 1 per chip) under mp=2."""
    config = G.llama_tiny(128)
    params = G.init_params(config, jax.random.key(1))
    prompts = [np.random.RandomState(i).randint(0, config.vocab_size,
                                                (1 + 5 * i,)).astype(np.int32)
               for i in range(5)]
    base, _ = _run(params, config, 1, 3, prompts)
    out, _ = _run(params, config, 2, 3, prompts)
    assert out == base


def test_mp2_abort_midrun_keeps_invariants(params):
    """abort() of an in-flight request under mp frees/derefs pages exactly as
    on a single chip (the cache manager is mp-oblivious); the survivors'
    outputs match the single-chip run of the same abort schedule."""
    base, _ = _run(params, TINY, 1, 0, _stream(seed=11, n=8), abort_rid=5)
    out, _ = _run(params, TINY, 2, 0, _stream(seed=11, n=8), abort_rid=5)
    assert out == base


def test_mp_rejects_indivisible_heads(params):
    with pytest.raises(ValueError, match="divide"):
        LLMEngine(params, TINY, num_slots=2, page_size=8, max_model_len=64,
                  mp=3)    # gpt_tiny has 4 heads


# ---------------------------------------------------------------------------
# head-sharded kernel vs oracle (q_len = 1 decode and q_len > 1 verify)
# ---------------------------------------------------------------------------

def _pool_case(rng, kvh):
    B, T, H, hd, page, P, maxp = 3, 5, 4, 64, 8, 9, 4
    q1 = jnp.asarray(rng.randn(B, H, hd), jnp.float32)
    qT = jnp.asarray(rng.randn(B, T, H, hd), jnp.float32)
    k = jnp.asarray(rng.randn(P, page, kvh, hd), jnp.float32)
    v = jnp.asarray(rng.randn(P, page, kvh, hd), jnp.float32)
    tbl = np.zeros((B, maxp), np.int32)
    tbl[0, :3] = [1, 2, 3]
    tbl[1, :2] = [4, 5]
    tbl[2, :4] = [6, 7, 8, 3]
    lengths = jnp.asarray([9, 4, 17], jnp.int32)
    valid = jnp.asarray([5, 1, 3], jnp.int32)
    return q1, qT, k, v, jnp.asarray(tbl), lengths, valid


@pytest.mark.parametrize("kvh", [4, 2], ids=["mha", "gqa"])
def test_sharded_verify_kernel_matches_oracle_qlen_gt1(kvh):
    """The head-sharded Pallas verify/chunk kernel (shard_map over mp=2,
    interpret mode on CPU) returns exactly the unsharded oracle's numbers for
    q_len > 1 — attention never mixes heads, so per-chip slices compose."""
    rng = np.random.RandomState(3)
    _, qT, k, v, tbl, lengths, valid = _pool_case(rng, kvh)
    mesh = serving_mesh(2)
    ref = paged_prefill_attention_xla(qT, k, v, tbl, lengths, valid)
    got = paged_prefill_attention_mp(qT, k, v, tbl, lengths, valid, mesh,
                                     use_pallas=True, interpret=True)
    for b, n in enumerate(np.asarray(valid)):
        np.testing.assert_allclose(np.asarray(got)[b, :n],
                                   np.asarray(ref)[b, :n], atol=2e-5)
    # the sharding-constraint (oracle) route must agree too
    got_xla = jax.jit(lambda *a: paged_prefill_attention_mp(*a, mesh,
                                                            use_pallas=False))(
        qT, k, v, tbl, lengths, valid)
    for b, n in enumerate(np.asarray(valid)):
        np.testing.assert_allclose(np.asarray(got_xla)[b, :n],
                                   np.asarray(ref)[b, :n], atol=2e-5)


@pytest.mark.parametrize("kvh", [4, 2], ids=["mha", "gqa"])
def test_sharded_decode_kernel_matches_oracle(kvh):
    rng = np.random.RandomState(4)
    q1, _, k, v, tbl, lengths, _ = _pool_case(rng, kvh)
    mesh = serving_mesh(2)
    ref = paged_attention_xla(q1, k, v, tbl, lengths)
    got = paged_attention_decode_mp(q1, k, v, tbl, lengths, mesh,
                                    use_pallas=True, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_sharded_kernel_rejects_indivisible_heads():
    rng = np.random.RandomState(5)
    q1, _, k, v, tbl, lengths, _ = _pool_case(rng, 4)
    with pytest.raises(ValueError, match="divisible"):
        paged_attention_decode_mp(q1, k, v, tbl, lengths, serving_mesh(8))
