"""Quantized serving (ISSUE 11): weight-only int8 params + int8 KV page pool.

Covers the tentpole contracts:
- quant/dequant round-trip units (weights per-channel, KV per-token);
- kernel-vs-XLA-oracle parity on int8 pages (same dequant math on both
  routes, so the interpret-mode kernel matches the gather oracle to float
  tolerance);
- engine-level greedy top-1 agreement vs the fp engine across the serving
  modes (chunked+spec+prefix, bucketed, mp2, optimistic+preempt);
- `check_invariants` green on quantized pools, preempted-vs-undisturbed
  BYTE parity within the quantized mode (swap restores bit-exact int8
  pages; recompute re-quantizes deterministically);
- the fp default is byte-identical to a quantization-free engine;
- swap-pool intake admission (the PR-10 follow-on): a request whose worst
  case could never park in the host pool is rejected at `add_request`;
- the tpu_cost quantized account stays budget-clean with the declared
  >= 2x pool shrink.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.inference.engine import LLMEngine
from paddle_tpu.models import gpt as gpt_mod
from paddle_tpu.quantization.serving import (
    dequantize_weight, kv_page_bytes, quantize_serving_params,
    quantize_weight)

AGREEMENT_BAR = 0.85    # greedy top-1 agreement floor vs fp (measured 1.0
                        # on the tiny audit model; the bar leaves room for
                        # near-tie argmax flips on other seeds)


@pytest.fixture(scope="module")
def cfg():
    return gpt_mod.gpt_tiny(64)


@pytest.fixture(scope="module")
def params(cfg):
    return gpt_mod.init_params(cfg, jax.random.key(0))


@pytest.fixture(scope="module")
def prompts(cfg):
    rng = np.random.RandomState(7)
    out = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
           for n in (3, 9, 17, 5)]
    # a shared-prefix pair (not page-aligned) so prefix sharing + COW run
    shared = rng.randint(0, cfg.vocab_size, (13,)).astype(np.int32)
    out.append(shared.copy())
    out.append(np.concatenate(
        [shared, rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32)]))
    return out


def _run(params, cfg, prompts, max_new=8, **kw):
    eng = LLMEngine(params, cfg, page_size=8, max_model_len=64, **kw)
    rids = [eng.add_request(p, max_new_tokens=max_new) for p in prompts]
    outs = eng.run()
    eng.cache.check_invariants()
    assert eng.cache.swapped_page_count == 0
    return [outs[r].token_ids for r in rids], eng


def _agreement(a, b):
    total = sum(max(len(x), len(y)) for x, y in zip(a, b))
    agree = sum(int(u == v) for x, y in zip(a, b) for u, v in zip(x, y))
    return agree / max(total, 1)


# ---------------------------------------------------------------------------
# quant/dequant units
# ---------------------------------------------------------------------------

def test_weight_quant_roundtrip_per_channel():
    rng = np.random.RandomState(0)
    w = (rng.randn(2, 64, 192) * rng.rand(1, 1, 192)).astype(np.float32)
    q, s = quantize_weight(w, channel_axis=(0, 2))
    assert q.dtype == np.int8 and s.dtype == np.float32
    assert s.shape == (2, 1, 192)
    assert np.abs(q).max() <= 127
    # symmetric rounding error is bounded by half a quantization step,
    # per (layer, channel)
    err = np.abs(dequantize_weight(q, s) - w)
    assert (err <= s / 2 + 1e-7).all()


def test_quantize_serving_params_structure(params, cfg):
    qp = quantize_serving_params(params, cfg)
    blocks = qp["blocks"]
    for k in ("qkv_w", "proj_w", "fc1_w", "fc2_w"):
        assert k not in blocks
        assert blocks[k + "_q"].dtype == np.int8
        assert blocks[k + "_scale"].shape == \
            (blocks[k + "_q"].shape[0], 1, blocks[k + "_q"].shape[2])
    assert "wte" not in qp and qp["wte_q"].dtype == np.int8
    assert qp["wte_scale"].shape == (cfg.vocab_size, 1)
    # biases/norms untouched
    assert blocks["ln1_w"] is params["blocks"]["ln1_w"]


def test_kv_quant_roundtrip():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(3, 4, 16).astype(np.float32) * 5.0)
    q, s = gpt_mod._quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (3, 4)
    deq = q.astype(jnp.float32) * s[..., None]
    assert float(jnp.max(jnp.abs(deq - x))) <= float(jnp.max(s)) / 2 + 1e-6


# ---------------------------------------------------------------------------
# kernel vs oracle on int8 pages (same dequant math -> float tolerance)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def int8_pool():
    rng = np.random.RandomState(2)
    P, page, KVH, hd = 10, 8, 2, 64
    kq = jnp.asarray(rng.randint(-127, 128, (P, page, KVH, hd)), jnp.int8)
    vq = jnp.asarray(rng.randint(-127, 128, (P, page, KVH, hd)), jnp.int8)
    ks = jnp.asarray(rng.rand(P, page, KVH).astype(np.float32) * 0.05)
    vs = jnp.asarray(rng.rand(P, page, KVH).astype(np.float32) * 0.05)
    tbl = jnp.asarray(rng.randint(1, P, (3, 4)), jnp.int32)
    return kq, vq, ks, vs, tbl


def test_kernel_oracle_parity_int8_decode(int8_pool):
    from paddle_tpu.incubate.kernels.paged_attention import (
        paged_attention_pallas, paged_attention_xla)
    kq, vq, ks, vs, tbl = int8_pool
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(3, 4, 64).astype(np.float32))
    lens = jnp.asarray(np.array([5, 17, 30], np.int32))
    got = paged_attention_pallas(q, kq, vq, tbl, lens, interpret=True,
                                 kv_scales=(ks, vs))
    want = paged_attention_xla(q, kq, vq, tbl, lens, kv_scales=(ks, vs))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_kernel_oracle_parity_int8_prefill(int8_pool):
    from paddle_tpu.incubate.kernels.paged_attention import (
        paged_prefill_attention_pallas, paged_prefill_attention_xla)
    kq, vq, ks, vs, tbl = int8_pool
    rng = np.random.RandomState(4)
    T = 4
    q = jnp.asarray(rng.randn(3, T, 4, 64).astype(np.float32))
    qo = jnp.asarray(np.array([2, 9, 20], np.int32))
    vl = jnp.asarray(np.array([1, 3, 4], np.int32))
    got = np.asarray(paged_prefill_attention_pallas(
        q, kq, vq, tbl, qo, vl, interpret=True, kv_scales=(ks, vs)))
    want = np.asarray(paged_prefill_attention_xla(
        q, kq, vq, tbl, qo, vl, kv_scales=(ks, vs)))
    for b in range(3):      # rows past valid are padding garbage by contract
        np.testing.assert_allclose(got[b, :int(vl[b])], want[b, :int(vl[b])],
                                   rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# fp default: quantization off changes nothing
# ---------------------------------------------------------------------------

def test_fp_default_byte_identity(params, cfg, prompts):
    default, d_eng = _run(params, cfg, prompts, num_slots=4, prefill_chunk=8,
                          spec_len=2)
    explicit, e_eng = _run(params, cfg, prompts, num_slots=4, prefill_chunk=8,
                           spec_len=2, weight_dtype="bf16", kv_dtype=None)
    assert default == explicit
    assert d_eng.weight_dtype is None and e_eng.kv_dtype is None
    # the fp pool tree is exactly the pre-quantization {k, v} pair
    pool = gpt_mod.init_paged_cache(cfg, 4, 8)
    assert set(pool) == {"k", "v"} and pool["k"].dtype == cfg.dtype
    assert d_eng.kv_pool_bytes() == e_eng.kv_pool_bytes()


def test_quant_dtype_validation(params, cfg):
    with pytest.raises(ValueError, match="kv_dtype"):
        LLMEngine(params, cfg, page_size=8, max_model_len=64,
                  kv_dtype="int4")


# ---------------------------------------------------------------------------
# engine-level greedy top-1 agreement vs fp, across serving modes
# ---------------------------------------------------------------------------

MODES = {
    "chunked_spec_prefix": dict(num_slots=4, prefill_chunk=8, spec_len=2),
    "bucketed": dict(num_slots=4, prefill_chunk=None),
    "mp2": dict(num_slots=4, prefill_chunk=8, spec_len=2, mp=2),
    "preempt": dict(num_slots=6, num_pages=9, prefill_chunk=8,
                    admission="optimistic", preempt="recompute"),
}


@pytest.mark.parametrize("mode", sorted(MODES))
def test_engine_top1_agreement(params, cfg, prompts, mode):
    kw = MODES[mode]
    fp, _ = _run(params, cfg, prompts, max_new=12, **kw)
    q, eng = _run(params, cfg, prompts, max_new=12, weight_dtype="int8",
                  kv_dtype="int8", **kw)
    assert eng.stats()["kv_dtype"] == "int8"
    if mode == "preempt":
        assert eng.stats()["preemptions"] > 0
    assert _agreement(fp, q) >= AGREEMENT_BAR
    # every request still decodes its full budget (quantization must not
    # wedge a slot or truncate a stream)
    assert all(len(t) == 12 for t in q)


# ---------------------------------------------------------------------------
# quantized pools under preemption: byte parity + invariants + swap
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("preempt", ["recompute", "swap"])
def test_quantized_preempt_parity_and_no_leaks(params, cfg, prompts, preempt):
    base, _ = _run(params, cfg, prompts, max_new=12, num_slots=6,
                   prefill_chunk=8, weight_dtype="int8", kv_dtype="int8")
    got, eng = _run(params, cfg, prompts, max_new=12, num_slots=6,
                    num_pages=9, prefill_chunk=8, weight_dtype="int8",
                    kv_dtype="int8", admission="optimistic", preempt=preempt)
    st = eng.stats()
    assert st["preemptions"] > 0
    if preempt == "swap":
        # int8 pages swap as int8: the host pool bound shrinks with the pool
        assert st["preempt_swaps"] > 0
        assert eng.swap_pool_bytes() < \
            (eng.cache.num_pages - 1) * kv_page_bytes(cfg, 8)
    # preempted-vs-undisturbed parity holds WITHIN the quantized mode: swap
    # restores bit-exact int8 pages + scales, recompute re-quantizes the
    # same values deterministically
    assert got == base


def test_quantized_pool_bytes_ratio(params, cfg):
    fp_eng = LLMEngine(params, cfg, page_size=8, max_model_len=64)
    q_eng = LLMEngine(params, cfg, page_size=8, max_model_len=64,
                      kv_dtype="int8")
    ratio = fp_eng.kv_pool_bytes() / q_eng.kv_pool_bytes()
    assert ratio >= 2.0, ratio     # the "~2x smaller, same geometry" bar
    assert q_eng.cache.num_pages == fp_eng.cache.num_pages
    assert kv_page_bytes(cfg, 8) / kv_page_bytes(cfg, 8, "int8") == \
        pytest.approx(ratio)


# ---------------------------------------------------------------------------
# swap-pool intake admission (PR-10 follow-on)
# ---------------------------------------------------------------------------

def test_intake_swap_reject(params, cfg):
    eng = LLMEngine(params, cfg, page_size=8, max_model_len=64, num_slots=2,
                    admission="optimistic", preempt="swap", swap_pool_pages=2)
    # 8 + 32 tokens = 5 pages: fits the device pool, can NEVER park in a
    # 2-page host pool -> rejected at intake, not queued into a thrash loop
    rid = eng.add_request(np.arange(8, dtype=np.int32), max_new_tokens=32)
    out = eng._outputs[rid]
    assert out.finish_reason == "rejected"
    st = eng.stats()
    assert st["intake_swap_rejects"] == 1 and st["rejected_requests"] == 1
    # a parkable footprint is served normally
    rid2 = eng.add_request(np.arange(4, dtype=np.int32), max_new_tokens=8)
    eng.run()
    assert eng._outputs[rid2].finish_reason == "length"
    eng.cache.check_invariants()


def test_intake_gate_scoped_to_swap_mode(params, cfg):
    # recompute mode and zero-size host pools (parking disabled) must keep
    # serving footprints the device pool can hold — no intake gate
    for kw in (dict(admission="optimistic", preempt="recompute"),
               dict(admission="optimistic", preempt="swap",
                    swap_pool_pages=0),
               dict()):
        eng = LLMEngine(params, cfg, page_size=8, max_model_len=64,
                        num_slots=2, **kw)
        rid = eng.add_request(np.arange(8, dtype=np.int32), max_new_tokens=32)
        eng.run()
        assert eng._outputs[rid].finish_reason == "length"
        assert eng.stats()["intake_swap_rejects"] == 0


# ---------------------------------------------------------------------------
# mp layout + CI accounts
# ---------------------------------------------------------------------------

def test_serving_param_specs_quantized(params, cfg):
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel.hybrid import serving_param_specs

    qp = quantize_serving_params(params, cfg)
    specs = serving_param_specs(cfg, qp)
    blocks = specs["blocks"]
    # int8 leaves keep the fp weight's Megatron spec...
    assert blocks["qkv_w_q"] == P(None, None, "mp")
    assert blocks["proj_w_q"] == P(None, "mp", None)
    # ...and scales shard with the weight's CHANNEL (last) dim: split for
    # column-parallel, replicated for row-parallel
    assert blocks["qkv_w_scale"] == P(None, None, "mp")
    assert blocks["fc1_w_scale"] == P(None, None, "mp")
    assert blocks["proj_w_scale"] == P()
    assert blocks["fc2_w_scale"] == P()
    # embedding pair vocab-sharded like the fp wte (scale rows ride the
    # vocab axis: one scale per vocab row)
    assert specs["wte_q"] == P("mp", None)
    assert specs["wte_scale"] == P("mp", None)


def test_cost_checks_quantized_clean():
    from paddle_tpu.analysis.cost_model import run_cost_checks

    reports, findings = run_cost_checks(include_mp=False)
    assert findings == []
    rep = reports[1]
    assert rep["quantized_pool_ratio"] >= 2.0
    assert rep["at_rest_quantized"]["pool_bytes"] < rep["at_rest"]["pool_bytes"]
    # int8 must shrink the TOTAL param account (the replicated remainder is
    # the norm/bias tail plus the row-parallel scales, which int8 slightly
    # grows — the win lives in the vocab-sharded + block columns; same
    # comparison JXP010 enforces)
    q, f = rep["at_rest_quantized"], rep["at_rest"]
    assert q["param_bytes_sharded"] + q["param_bytes_replicated"] < \
        f["param_bytes_sharded"] + f["param_bytes_replicated"]
    assert rep["host_pool_bytes_int8"] < rep["host_pool_bytes"]
    names = [p["name"] for p in rep["programs"]]
    assert "serve.fused_step_int8" in names


def test_bench_quantized_smoke():
    from bench_serve import run_serve_bench

    q = run_serve_bench(num_requests=6, num_slots=3, max_new_tokens=4,
                        prefill_chunk=8, spec_len=2, weight_dtype="int8",
                        kv_dtype="int8")
    fp = run_serve_bench(num_requests=6, num_slots=3, max_new_tokens=4,
                         prefill_chunk=8, spec_len=2)
    assert q["kv_dtype"] == "int8" and q["weight_dtype"] == "int8"
    assert q["kv_pool_bytes"] * 2 <= fp["kv_pool_bytes"]
    agree = sum(int(a == b) for qa, fa in zip(q["output_tokens"],
                                              fp["output_tokens"])
                for a, b in zip(qa, fa))
    total = sum(len(t) for t in fp["output_tokens"])
    assert agree / total >= AGREEMENT_BAR
    # dequant adds no executables: same program counts as the fp engine
    assert q["decode_executables"] == fp["decode_executables"] == 1
    assert q["prefill_executables"] == fp["prefill_executables"]
