"""Autograd engine tests (reference category: eager/backward tests in
`test/legacy_test/`)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer


def t(a, sg=False):
    return paddle.to_tensor(np.asarray(a, np.float32), stop_gradient=sg)


def test_simple_chain():
    x = t([2.0])
    y = x * x + 3.0 * x
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.0])


def test_fanout_accumulation():
    x = t([3.0])
    a = x * 2
    b = x * 5
    (a + b).backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.0])


def test_grad_accumulates_across_backwards():
    x = t([1.0])
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])


def test_stop_gradient():
    x = t([1.0])
    y = t([2.0], sg=True)
    z = x * y
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach():
    x = t([2.0])
    y = x * 3
    d = y.detach()
    assert d.stop_gradient
    z = d * x
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_retain_graph_error():
    x = t([2.0])
    y = x * x
    y.backward(retain_graph=True)
    y.backward()  # allowed with retain_graph first
    with pytest.raises(RuntimeError):
        y.backward()


def test_no_grad():
    x = t([2.0])
    with paddle.no_grad():
        y = x * x
    assert y.stop_gradient


def test_partial_grad():
    x = t([3.0])
    y = t([4.0])
    z = x * y
    gx, = paddle.grad(z, [x])
    np.testing.assert_allclose(gx.numpy(), [4.0])
    assert x.grad is None  # grad() must not write .grad


def test_partial_grad_intermediate():
    x = t([2.0])
    h = x * x
    z = h * 3.0
    gh, = paddle.grad(z, [h])
    np.testing.assert_allclose(gh.numpy(), [3.0])


def test_multi_output_op():
    x = t([[3.0, 1.0, 2.0]])
    vals, idx = paddle.topk(x, k=2)
    vals.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1.0, 0.0, 1.0]])


def test_hook():
    x = t([2.0])
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 2

    x.register_hook(hook)
    (x * 3).backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_backward_nonscalar_with_grad_tensor():
    x = t([1.0, 2.0])
    y = x * x
    y.backward(paddle.to_tensor(np.asarray([1.0, 0.5], np.float32)))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_pylayer():
    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            (x,) = ctx.saved_tensor()
            return grad * 2

    x = t([3.0])
    y = Double.apply(x)
    np.testing.assert_allclose(y.numpy(), [6.0])
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_setitem_grad():
    x = t([1.0, 2.0, 3.0])
    v = t([10.0])
    y = x * 1.0
    y[1] = v
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 0.0, 1.0])
    np.testing.assert_allclose(v.grad.numpy(), [1.0])


def test_indexing_grad():
    x = t([[1.0, 2.0], [3.0, 4.0]])
    y = x[0]
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1.0, 1.0], [0.0, 0.0]])
