"""dy2static AST control-flow conversion (ref jit/dy2static/ast_transformer.py
IfElse/Loop transforms + convert_operators.py): plain Python if/while on
tensor VALUES work under @to_static via lazy AST rewrite + retrace."""
import numpy as np
import pytest

import paddle_tpu as paddle

# module-level functions (AST transform needs retrievable source)


@paddle.jit.to_static
def _branchy(a):
    if a.sum() > 0:
        out = a * 2
    else:
        out = a - 100
    return out


@paddle.jit.to_static
def _collatz(n):
    steps = paddle.to_tensor(np.int32(0))
    while n > 1:
        if (n % 2) == 0:
            n = n // 2
        else:
            n = 3 * n + 1
        steps = steps + 1
    return steps


@paddle.jit.to_static
def _static_flag(a, flag=True):
    if flag:
        return a + 1
    return a - 1


def test_python_if_on_tensor_value():
    pos = paddle.to_tensor(np.ones(3, np.float32))
    neg = paddle.to_tensor(-np.ones(3, np.float32))
    np.testing.assert_allclose(_branchy(pos).numpy(), [2, 2, 2])
    np.testing.assert_allclose(_branchy(neg).numpy(), [-101, -101, -101])


def test_python_while_data_dependent():
    assert int(_collatz(paddle.to_tensor(np.int32(6))).numpy()) == 8
    assert int(_collatz(paddle.to_tensor(np.int32(27))).numpy()) == 111


def test_python_bool_control_flow_untouched():
    pos = paddle.to_tensor(np.ones(3, np.float32))
    np.testing.assert_allclose(_static_flag(pos).numpy(), [2, 2, 2])
    np.testing.assert_allclose(_static_flag(pos, flag=False).numpy(),
                               [0, 0, 0])


def test_transform_is_lazy_and_cached():
    """First call triggers the rewrite; subsequent calls hit the program
    cache (no repeated transform)."""
    from paddle_tpu.jit.program import StaticFunction
    sf = _branchy
    assert isinstance(sf, StaticFunction)
    assert getattr(sf, "_ast_transformed", False)  # set by the earlier tests
    n_progs = len(sf.program_cache)
    _branchy(paddle.to_tensor(np.ones(3, np.float32)))
    assert len(sf.program_cache) == n_progs


def test_convert_ops_eager_semantics():
    from paddle_tpu.jit.dy2static import convert_ifelse, convert_while_loop
    a = paddle.to_tensor(np.float32(5.0))
    out = convert_ifelse(a > 1, lambda x: x * 2, lambda x: x, (a,))
    assert float(out[0].numpy() if isinstance(out, tuple) else out.numpy()) == 10.0
    vals = convert_while_loop(lambda i: i < 3, lambda i: (i + 1,),
                              (paddle.to_tensor(np.int32(0)),))
    assert int(vals[0].numpy()) == 3


@paddle.jit.to_static
def _cond_bound(a, debug=False):
    out = a * 1
    if debug:
        tmp = 1
    if a.sum() > 0:
        res = a * 2
    else:
        res = a * 3
    return out + res


def test_conditionally_bound_names_not_captured():
    pos = paddle.to_tensor(np.ones(2, np.float32))
    np.testing.assert_allclose(_cond_bound(pos).numpy(), [3, 3])


_nested_def_probe = None


@paddle.jit.to_static
def _with_nested_def(a):
    b = a * 0

    def h(x):
        return x + 10

    if a.sum() > 0:
        b = b + 1
    else:
        b = b - 1
    return h(b)


def test_nested_def_scope_preserved():
    pos = paddle.to_tensor(np.ones(2, np.float32))
    np.testing.assert_allclose(_with_nested_def(pos).numpy(), [11, 11])


@paddle.jit.to_static
def _kwonly(x, *, shift=None):
    if x.sum() > 0:
        shift = shift + 1
    else:
        shift = shift - 1
    return shift


def test_kwonly_param_is_defined():
    pos = paddle.to_tensor(np.ones(2, np.float32))
    s = paddle.to_tensor(np.float32(5.0))
    np.testing.assert_allclose(_kwonly(pos, shift=s).numpy(), 6.0)
