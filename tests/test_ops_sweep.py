"""Table-driven OpTest sweep (ref test/legacy_test/ 1330 per-op test files).

Every entry runs through the OpTest harness: eager + to_static capture vs a
numpy oracle (`check_output`), and numeric-vs-analytic gradients
(`check_grad`) for the differentiable ones — the reference's dual-mode +
grad-check contract, one table instead of 1330 files.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

from op_test import check_grad, check_output

rng = np.random.RandomState(7)
POS = rng.rand(3, 4).astype(np.float32) + 0.5        # strictly positive
UNIT = (rng.rand(3, 4).astype(np.float32) * 1.6 - 0.8)  # in (-0.8, 0.8)
ANY = rng.randn(3, 4).astype(np.float32)
ANY2 = rng.randn(3, 4).astype(np.float32)
POSB = rng.rand(3, 4).astype(np.float32) + 0.5
INTS = rng.randint(0, 5, (3, 4)).astype(np.int64)

# (name, paddle_fn, numpy_fn, inputs, check_grad?, tolerance)
UNARY = [
    ("abs", paddle.abs, np.abs, [ANY], True),
    ("acos", paddle.acos, np.arccos, [UNIT], True),
    ("acosh", paddle.acosh, np.arccosh, [POS + 1.0], True),
    ("asin", paddle.asin, np.arcsin, [UNIT], True),
    ("asinh", paddle.asinh, np.arcsinh, [ANY], True),
    ("atan", paddle.atan, np.arctan, [ANY], True),
    ("atanh", paddle.atanh, np.arctanh, [UNIT], True),
    ("ceil", paddle.ceil, np.ceil, [ANY], False),
    ("cos", paddle.cos, np.cos, [ANY], True),
    ("cosh", paddle.cosh, np.cosh, [ANY], True),
    ("erf", paddle.erf, None, [ANY], True),
    ("exp", paddle.exp, np.exp, [ANY], True),
    ("expm1", paddle.expm1, np.expm1, [ANY], True),
    ("floor", paddle.floor, np.floor, [ANY], False),
    ("log", paddle.log, np.log, [POS], True),
    ("log10", paddle.log10, np.log10, [POS], True),
    ("log1p", paddle.log1p, np.log1p, [POS], True),
    ("log2", paddle.log2, np.log2, [POS], True),
    ("reciprocal", paddle.reciprocal, np.reciprocal, [POS], True),
    ("round", paddle.round, np.round, [ANY], False),
    ("rsqrt", paddle.rsqrt, lambda a: 1 / np.sqrt(a), [POS], True),
    ("sigmoid", paddle.sigmoid, lambda a: 1 / (1 + np.exp(-a)), [ANY], True),
    ("sign", paddle.sign, np.sign, [ANY], False),
    ("sin", paddle.sin, np.sin, [ANY], True),
    ("sinh", paddle.sinh, np.sinh, [ANY], True),
    ("sqrt", paddle.sqrt, np.sqrt, [POS], True),
    ("square", paddle.square, np.square, [ANY], True),
    ("tan", paddle.tan, np.tan, [UNIT], True),
    ("tanh", paddle.tanh, np.tanh, [ANY], True),
    ("trunc", paddle.trunc, np.trunc, [ANY], False),
    ("deg2rad", paddle.deg2rad, np.deg2rad, [ANY], True),
    ("rad2deg", paddle.rad2deg, np.rad2deg, [ANY], True),
    ("digamma", paddle.digamma, None, [POS], True),
    ("lgamma", paddle.lgamma, None, [POS], True),
    ("i0", paddle.i0, None, [ANY], True),
    ("frac", paddle.frac, lambda a: a - np.trunc(a), [ANY], True),
    ("logit", paddle.logit, lambda a: np.log(a / (1 - a)),
     [rng.rand(3, 4).astype(np.float32) * 0.8 + 0.1], True),
    ("angle", paddle.angle, np.angle, [ANY], False),
    ("neg", paddle.neg, np.negative, [ANY], True),
]

BINARY = [
    ("add", paddle.add, np.add, [ANY, ANY2], True),
    ("subtract", paddle.subtract, np.subtract, [ANY, ANY2], True),
    ("multiply", paddle.multiply, np.multiply, [ANY, ANY2], True),
    ("divide", paddle.divide, np.divide, [ANY, POSB], True),
    ("maximum", paddle.maximum, np.maximum, [ANY, ANY2], True),
    ("minimum", paddle.minimum, np.minimum, [ANY, ANY2], True),
    ("pow", paddle.pow, np.power, [POS, POSB], True),
    ("fmax", paddle.fmax, np.fmax, [ANY, ANY2], False),
    ("fmin", paddle.fmin, np.fmin, [ANY, ANY2], False),
    ("atan2", paddle.atan2, np.arctan2, [ANY, POSB], True),
    ("hypot", paddle.hypot, np.hypot, [ANY, ANY2], True),
    ("logaddexp", paddle.logaddexp, np.logaddexp, [ANY, ANY2], True),
    ("floor_divide", paddle.floor_divide, np.floor_divide, [POS, POSB], False),
    ("mod", paddle.mod, np.mod, [POS, POSB], False),
    ("copysign", paddle.copysign, np.copysign, [ANY, ANY2], False),
    ("nextafter", paddle.nextafter, np.nextafter, [ANY, ANY2], False),
    ("heaviside", paddle.heaviside, np.heaviside, [ANY, POSB], False),
]

REDUCTION = [
    ("sum", paddle.sum, np.sum, [ANY], True),
    ("mean", paddle.mean, np.mean, [ANY], True),
    ("max", paddle.max, np.max, [ANY], True),
    ("min", paddle.min, np.min, [ANY], True),
    ("prod", paddle.prod, np.prod, [POS], True),
    ("logsumexp", paddle.logsumexp,
     lambda a: np.log(np.sum(np.exp(a))), [ANY], True),
    ("amax", paddle.amax, np.amax, [ANY], False),
    ("amin", paddle.amin, np.amin, [ANY], False),
    ("all", paddle.all, np.all, [ANY > 0], False),
    ("any", paddle.any, np.any, [ANY > 0], False),
    ("count_nonzero", paddle.count_nonzero, np.count_nonzero, [ANY], False),
    ("median", paddle.median, np.median, [ANY], False),
    ("std", paddle.std, lambda a: np.std(a, ddof=1), [ANY], True),
    ("var", paddle.var, lambda a: np.var(a, ddof=1), [ANY], True),
    ("nansum", paddle.nansum, np.nansum, [ANY], True),
    ("nanmean", paddle.nanmean, np.nanmean, [ANY], True),
]

SHAPE = [
    ("transpose", lambda x: paddle.transpose(x, [1, 0]), np.transpose, [ANY],
     True),
    ("reshape", lambda x: paddle.reshape(x, [4, 3]),
     lambda a: np.reshape(a, (4, 3)), [ANY], True),
    ("flatten", paddle.flatten, np.ravel, [ANY], True),
    ("flip", lambda x: paddle.flip(x, 0), lambda a: np.flip(a, 0), [ANY], True),
    ("roll", lambda x: paddle.roll(x, 1), lambda a: np.roll(a, 1), [ANY], True),
    ("tril", paddle.tril, np.tril, [ANY], True),
    ("triu", paddle.triu, np.triu, [ANY], True),
    ("rot90", paddle.rot90, np.rot90, [ANY], False),
    ("cumsum", paddle.cumsum,
     lambda a: np.cumsum(a), [ANY], True),
    ("cumprod", lambda x: paddle.cumprod(x, 0),
     lambda a: np.cumprod(a, 0), [POS], True),
    ("diff", paddle.diff, np.diff, [ANY], True),
    ("kron", paddle.kron, np.kron, [ANY, ANY2], True),
    ("diagonal", paddle.diagonal, np.diagonal, [ANY], True),
    ("trace", paddle.trace, np.trace, [ANY], True),
]

LINALG = [
    ("matmul", paddle.matmul, np.matmul, [ANY, ANY2.T.copy()], True),
    ("dot", paddle.dot, lambda a, b: np.sum(a * b, -1),
     [ANY[0], ANY2[0]], True),
    ("outer", paddle.outer, np.outer, [ANY[0], ANY2[0]], True),
    ("inner", paddle.inner, np.inner, [ANY, ANY2], True),
    ("cross", lambda x, y: paddle.cross(x, y, axis=1),
     lambda a, b: np.cross(a, b, axis=1),
     [ANY[:, :3].copy(), ANY2[:, :3].copy()], True),
    ("bmm", paddle.bmm, np.matmul,
     [rng.randn(2, 3, 4).astype(np.float32),
      rng.randn(2, 4, 5).astype(np.float32)], True),
    ("mv", paddle.mv, lambda a, b: a @ b, [ANY, ANY2[0]], True),
]

ALL_CASES = UNARY + BINARY + REDUCTION + SHAPE + LINALG


@pytest.mark.parametrize("case", ALL_CASES, ids=[c[0] for c in ALL_CASES])
def test_op_dual_mode_and_grad(case):
    name, fn, np_fn, inputs, do_grad = case
    if np_fn is not None:
        check_output(fn, np_fn, inputs, atol=2e-5, rtol=2e-5)
    else:
        # no numpy oracle (scipy-special): eager/static consistency only
        out_e = fn(*[paddle.to_tensor(v) for v in inputs])
        st = paddle.jit.to_static(lambda *ts: fn(*ts))
        out_s = st(*[paddle.to_tensor(v) for v in inputs])
        np.testing.assert_allclose(out_e.numpy(), out_s.numpy(), rtol=1e-6)
    if do_grad:
        check_grad(fn, inputs)
