"""End-to-end "book" test (reference: `test/book/test_recognize_digits.py` — train a
small model to a loss threshold; the canonical framework-works gate)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.io import DataLoader
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.transforms import Compose, Normalize, ToTensor

TRANSFORM = Compose([ToTensor(), Normalize(mean=[0.5], std=[0.5])])


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 128)
        self.fc2 = nn.Linear(128, 64)
        self.fc3 = nn.Linear(64, 10)

    def forward(self, x):
        x = paddle.reshape(x, [x.shape[0], 784])
        x = F.relu(self.fc1(x))
        x = F.relu(self.fc2(x))
        return self.fc3(x)


def test_mnist_mlp_trains_to_threshold():
    paddle.seed(0)
    train_ds = MNIST(mode="train", transform=TRANSFORM)
    loader = DataLoader(train_ds, batch_size=64, shuffle=True, drop_last=True)
    model = MLP()
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    loss_fn = nn.CrossEntropyLoss()

    first_loss = None
    recent = []
    steps = 0
    for epoch in range(4):
        for img, label in loader:
            out = model(img)
            loss = loss_fn(out, paddle.reshape(label, [-1]))
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first_loss is None:
                first_loss = float(loss.numpy())
            recent.append(float(loss.numpy()))
            steps += 1
            if steps >= 180:
                break
        if steps >= 180:
            break
    last_loss = float(np.mean(recent[-10:]))
    assert first_loss > 1.5  # started near log(10)
    assert last_loss < 0.8 * first_loss
    assert last_loss < 1.2

    # eval accuracy above chance by a wide margin
    model.eval()
    test_ds = MNIST(mode="test", transform=TRANSFORM)
    correct = total = 0
    with paddle.no_grad():
        for img, label in DataLoader(test_ds, batch_size=256):
            pred = model(img).numpy().argmax(-1)
            correct += int((pred == label.numpy().reshape(-1)).sum())
            total += pred.shape[0]
    assert correct / total > 0.5


def test_mnist_save_load_inference_roundtrip(tmp_path):
    paddle.seed(1)
    model = MLP()
    model.eval()
    x = paddle.to_tensor(np.random.rand(4, 1, 28, 28).astype(np.float32))
    expect = model(x).numpy()

    from paddle_tpu.static import InputSpec
    path = str(tmp_path / "mnist_model")
    paddle.jit.save(model, path, input_spec=[InputSpec([4, 1, 28, 28], "float32")])
    loaded = paddle.jit.load(path)
    got = loaded(x)
    got_arr = got.numpy() if hasattr(got, "numpy") else got[0].numpy()
    np.testing.assert_allclose(got_arr, expect, rtol=1e-4, atol=1e-5)
