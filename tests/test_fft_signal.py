"""paddle.fft / paddle.signal parity vs numpy (ref python/paddle/fft.py,
signal.py; op tests test/legacy_test/test_fft.py, test_stft_op.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle


@pytest.fixture(scope="module")
def x():
    return np.random.RandomState(0).randn(4, 32).astype(np.float32)


def test_fft_matches_numpy(x):
    for name in ["fft", "ifft", "rfft", "ihfft"]:
        got = getattr(paddle.fft, name)(paddle.to_tensor(x)).numpy()
        exp = getattr(np.fft, name)(x)
        np.testing.assert_allclose(got, exp, atol=1e-4, rtol=1e-4)


def test_fft_inverse_roundtrips(x):
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(paddle.fft.irfft(paddle.fft.rfft(t), n=32).numpy(),
                               x, atol=1e-5)
    np.testing.assert_allclose(paddle.fft.ifft(paddle.fft.fft(t)).numpy().real,
                               x, atol=1e-5)
    np.testing.assert_allclose(
        paddle.fft.ifftn(paddle.fft.fftn(t)).numpy().real, x, atol=1e-5)


def test_fft2_and_shift(x):
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(paddle.fft.fft2(t).numpy(), np.fft.fft2(x),
                               atol=1e-3, rtol=1e-4)
    np.testing.assert_allclose(paddle.fft.fftshift(t).numpy(), np.fft.fftshift(x))
    np.testing.assert_allclose(paddle.fft.fftfreq(8, 0.5).numpy(),
                               np.fft.fftfreq(8, 0.5), atol=1e-6)
    np.testing.assert_allclose(paddle.fft.rfftfreq(8).numpy(), np.fft.rfftfreq(8),
                               atol=1e-6)


def test_fft_norm_modes(x):
    t = paddle.to_tensor(x)
    for norm in ["backward", "ortho", "forward"]:
        np.testing.assert_allclose(paddle.fft.fft(t, norm=norm).numpy(),
                                   np.fft.fft(x, norm=norm), atol=1e-4, rtol=1e-4)
    with pytest.raises(ValueError):
        paddle.fft.fft(t, norm="bogus")


def test_fft_grad():
    x = np.random.RandomState(1).randn(16).astype(np.float32)
    t = paddle.to_tensor(x)
    t.stop_gradient = False
    # sum(irfft(rfft(x))) == sum(x) -> grad == ones
    y = paddle.fft.irfft(paddle.fft.rfft(t), n=16).sum()
    y.backward()
    np.testing.assert_allclose(t.grad.numpy(), np.ones(16), atol=1e-4)


def test_frame_overlap_add_roundtrip():
    x = np.arange(16, dtype=np.float32)
    fr = paddle.signal.frame(paddle.to_tensor(x), 4, 4)  # non-overlapping
    assert fr.shape == [4, 4]
    back = paddle.signal.overlap_add(fr, 4)
    np.testing.assert_allclose(back.numpy(), x)
    # frame values
    np.testing.assert_allclose(fr.numpy()[:, 1], x[4:8])


def test_stft_matches_manual():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 64).astype(np.float32)
    n_fft, hop = 16, 8
    S = paddle.signal.stft(paddle.to_tensor(x), n_fft, hop, center=False).numpy()
    # manual: frames [n_fft, nf] rfft over axis 0
    nf = 1 + (64 - n_fft) // hop
    man = np.stack([np.fft.rfft(x[:, i * hop:i * hop + n_fft], axis=1)
                    for i in range(nf)], axis=-1)
    np.testing.assert_allclose(S, man, atol=1e-3, rtol=1e-3)


def test_stft_istft_roundtrip():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 128).astype(np.float32)
    win = np.hanning(32).astype(np.float32)
    t = paddle.to_tensor(x)
    S = paddle.signal.stft(t, 32, 8, window=paddle.to_tensor(win))
    back = paddle.signal.istft(S, 32, 8, window=paddle.to_tensor(win), length=128)
    np.testing.assert_allclose(back.numpy(), x, atol=1e-3)
