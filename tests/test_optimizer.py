"""Optimizer numerics + LR schedulers (reference: `test/legacy_test/test_adam_op.py`
family)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.optimizer import (SGD, Adam, AdamW, Adagrad, Adadelta, Adamax, Lamb,
                                  Momentum, RMSProp)
from paddle_tpu.optimizer import lr as lr_mod


def quad_problem(opt_cls, steps=50, **kw):
    """Minimize ||x - 3||^2; return final x."""
    x = paddle.to_tensor(np.zeros((4,), np.float32), stop_gradient=False)
    x.persistable = True
    opt = opt_cls(parameters=[x], **kw)
    for _ in range(steps):
        loss = ((x - 3.0) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return x.numpy()


def test_sgd_converges():
    out = quad_problem(SGD, learning_rate=0.1, steps=100)
    np.testing.assert_allclose(out, np.full(4, 3.0), atol=1e-2)


def test_momentum_converges():
    out = quad_problem(Momentum, learning_rate=0.02, momentum=0.9, steps=150)
    np.testing.assert_allclose(out, np.full(4, 3.0), atol=2e-2)


def test_adam_matches_reference_impl():
    # hand-rolled adam reference
    lr, b1, b2, eps = 0.1, 0.9, 0.999, 1e-8
    x = paddle.to_tensor(np.array([1.0, -2.0], np.float32), stop_gradient=False)
    opt = Adam(learning_rate=lr, parameters=[x])
    ref = np.array([1.0, -2.0], np.float64)
    m = np.zeros(2)
    v = np.zeros(2)
    for step in range(1, 6):
        loss = (x * x).sum()
        loss.backward()
        g = 2 * ref
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        mh = m / (1 - b1 ** step)
        vh = v / (1 - b2 ** step)
        ref = ref - lr * mh / (np.sqrt(vh) + eps)
        opt.step()
        opt.clear_grad()
        np.testing.assert_allclose(x.numpy(), ref, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("cls,kw", [
    (Adam, {"learning_rate": 0.1}),
    (AdamW, {"learning_rate": 0.1}),
    (Adamax, {"learning_rate": 0.1}),
    (Adagrad, {"learning_rate": 0.5}),
    (Adadelta, {"learning_rate": 5.0}),
    (RMSProp, {"learning_rate": 0.05}),
    (Lamb, {"learning_rate": 0.05}),
])
def test_optimizers_reduce_loss(cls, kw):
    x = paddle.to_tensor(np.full((4,), 5.0, np.float32), stop_gradient=False)
    opt = cls(parameters=[x], **kw)
    first = None
    for _ in range(30):
        loss = ((x - 3.0) ** 2).sum()
        if first is None:
            first = float(loss.numpy())
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss.numpy()) < first * 0.5


def test_optimizer_state_dict_roundtrip():
    x = paddle.to_tensor(np.ones((3,), np.float32), stop_gradient=False)
    opt = Adam(learning_rate=0.1, parameters=[x])
    (x * x).sum().backward()
    opt.step()
    state = opt.state_dict()
    opt2 = Adam(learning_rate=0.1, parameters=[x])
    opt2.set_state_dict(state)
    assert opt2._global_step == opt._global_step
    m1 = opt._accumulators["moment1"][id(x)]
    m2 = opt2._accumulators["moment1"][id(x)]
    np.testing.assert_allclose(np.asarray(m1), np.asarray(m2))


def test_lr_schedulers():
    s = lr_mod.StepDecay(0.1, step_size=2, gamma=0.5)
    vals = []
    for _ in range(5):
        vals.append(round(s(), 6))
        s.step()
    assert vals == [0.1, 0.1, 0.05, 0.05, 0.025]

    c = lr_mod.CosineAnnealingDecay(1.0, T_max=10)
    assert abs(c() - 1.0) < 1e-9
    for _ in range(10):
        c.step()
    assert abs(c()) < 1e-6

    w = lr_mod.LinearWarmup(0.1, warmup_steps=5, start_lr=0.0, end_lr=0.1)
    assert w() == 0.0
    for _ in range(5):
        w.step()
    assert abs(w() - 0.1) < 1e-9

    n = lr_mod.NoamDecay(d_model=512, warmup_steps=10, learning_rate=1.0)
    lrs = []
    for _ in range(20):
        lrs.append(n())
        n.step()
    assert max(lrs) == lrs[10]  # peak at warmup end (last_epoch == warmup_steps)


def test_scheduler_with_optimizer():
    x = paddle.to_tensor(np.ones((2,), np.float32), stop_gradient=False)
    sched = lr_mod.StepDecay(0.1, step_size=1, gamma=0.1)
    opt = SGD(learning_rate=sched, parameters=[x])
    assert abs(opt.get_lr() - 0.1) < 1e-9
    sched.step()
    assert abs(opt.get_lr() - 0.01) < 1e-9


def test_grad_clip_in_optimizer():
    from paddle_tpu.nn import ClipGradByGlobalNorm
    x = paddle.to_tensor(np.full((4,), 100.0, np.float32), stop_gradient=False)
    opt = SGD(learning_rate=1.0, parameters=[x],
              grad_clip=ClipGradByGlobalNorm(1.0))
    (x * x).sum().backward()
    before = x.numpy().copy()
    opt.step()
    moved = np.linalg.norm(x.numpy() - before)
    np.testing.assert_allclose(moved, 1.0, rtol=1e-4)
