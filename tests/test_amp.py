"""AMP tests (reference: `test/amp/`)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def rnd(*s):
    return np.random.RandomState(5).rand(*s).astype(np.float32)


def test_autocast_white_list_casts_matmul():
    x = paddle.to_tensor(rnd(4, 4))
    y = paddle.to_tensor(rnd(4, 4))
    with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
        out = paddle.matmul(x, y)
    assert out.dtype.name == "bfloat16"
    # black list stays fp32
    with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
        s = paddle.nn.functional.softmax(x)
    assert s.dtype.name == "float32"


def test_autocast_off_outside_context():
    x = paddle.to_tensor(rnd(4, 4))
    out = paddle.matmul(x, x)
    assert out.dtype.name == "float32"


def test_grad_scaler_scales_and_unscales():
    lin = nn.Linear(4, 4)
    scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
    x = paddle.to_tensor(rnd(2, 4))
    loss = lin(x).sum()
    scaler.scale(loss).backward()
    g_scaled = lin.weight.grad.numpy().copy()
    scaler.step(paddle.optimizer.SGD(learning_rate=0.0, parameters=lin.parameters()))
    scaler.update()
    # after unscale_, grads are divided by 128
    np.testing.assert_allclose(lin.weight.grad.numpy(), g_scaled / 128.0, rtol=1e-6)


def test_grad_scaler_skips_on_inf():
    lin = nn.Linear(2, 2)
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=lin.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
    x = paddle.to_tensor(np.array([[np.inf, 1.0]], np.float32))
    loss = lin(x).sum()
    scaler.scale(loss).backward()
    before = lin.weight.numpy().copy()
    scaler.step(opt)
    scaler.update()
    np.testing.assert_allclose(lin.weight.numpy(), before)  # update skipped
    assert scaler.get_loss_scaling() < 4.0  # scale decreased


def test_o2_decorate_casts_params():
    net = nn.Sequential(nn.Linear(4, 4), nn.LayerNorm(4))
    paddle.amp.decorate(net, level="O2", dtype="bfloat16")
    assert net[0].weight.dtype.name == "bfloat16"
    assert net[1].weight.dtype.name == "float32"  # norm excluded
