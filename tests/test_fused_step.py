"""One-dispatch engine step: fused decode+prefill+verify program
(`models.gpt.serve_step_paged`), on-device sampling + acceptance, and
double-buffered scheduling (ref `AnalysisPredictor::ZeroCopyRun` single-graph
step; Sarathi-Serve piggybacking, Agrawal et al. OSDI 2024).

Covers the PR acceptance bars: byte-identical greedy tokens fused vs
`fuse=False` (spec on/off x bucketed/chunked x mp1/mp2, prefix cache + COW
on), sampled-path parity under a fixed PRNG key, the busy-step ONE-dispatch
assertion straight from `step_trace()`, double-buffer ordering (the token for
step n observed during step n+1), a warmed steady-state loop clean under
`jax.transfer_guard("disallow")`, page invariants after aborting a fused
in-flight batch, and the bench-level dispatches_per_step / parity wiring.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import gpt as G
from paddle_tpu.inference.engine import LLMEngine


@pytest.fixture(scope="module")
def tiny():
    cfg = G.gpt_tiny(64)
    return cfg, G.init_params(cfg, jax.random.key(0))


def _mixed_prompts(cfg, seed=0, n_extra=4):
    """Mixed stream: a repetitive prompt (drafts accept), random lengths, and
    a shared-prefix extension pair (full-page share + COW partial page)."""
    rng = np.random.RandomState(seed)
    pat = rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32)
    prompts = [np.tile(pat, 3)]
    prompts += [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
                for n in (5, 9, 17, 30)[:n_extra]]
    base = prompts[-1]
    prompts.append(np.concatenate(
        [base, rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32)]))
    return prompts


# ---------------------------------------------------------------------------
# fused program unit: predictions + on-device accept scan
# ---------------------------------------------------------------------------

def test_serve_step_program_matches_verify_and_host_accept(tiny):
    """serve_step_paged's token buffer is the argmax of verify_step_paged's
    logits, and its on-device accept counts equal the host-side greedy
    longest-prefix scan — the contract the harvest path relies on."""
    cfg, params = tiny
    rng = np.random.RandomState(3)
    B, T, page = 2, 4, 8
    pool = G.init_paged_cache(cfg, num_pages=10, page_size=page)
    table = np.zeros((B, 8), np.int32)
    table[0, :2] = [1, 2]
    table[1, :2] = [3, 4]
    tbl = jnp.asarray(table)
    prompts = rng.randint(0, cfg.vocab_size, (B, 6)).astype(np.int32)
    ids = np.zeros((B, 8), np.int32)
    ids[:, :6] = prompts
    _, pool = G.prefill_chunk_paged(
        params, jnp.asarray(ids), cfg, pool, tbl,
        jnp.zeros((B,), jnp.int32), jnp.full((B,), 6, jnp.int32))
    # slot 0: decode (valid=1); slot 1: a 3-token draft (valid=4)
    tokens = np.zeros((B, T), np.int32)
    tokens[0, 0] = prompts[0, -1]
    tokens[1, :] = rng.randint(0, cfg.vocab_size, (T,))
    tokens[1, 0] = prompts[1, -1]
    qoff = jnp.full((B,), 6, jnp.int32)
    valid = jnp.asarray([1, 4], jnp.int32)
    vlog, vpool = G.verify_step_paged(
        params, jnp.asarray(tokens), pool, tbl, qoff, valid, cfg)
    ref = np.asarray(jnp.argmax(vlog, axis=-1))
    out, accept, _, _ = G.serve_step_paged(
        params, jnp.asarray(tokens), vpool, tbl, qoff, valid, cfg)
    out, accept = np.asarray(out), np.asarray(accept)
    np.testing.assert_array_equal(out, ref)
    # host-side accept scan over the drafted slot
    a = 0
    while a < 3 and tokens[1, 1 + a] == ref[1, a]:
        a += 1
    assert accept[0] == 0 and accept[1] == a


# ---------------------------------------------------------------------------
# engine parity: fused vs --no-fuse, greedy byte-exact
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("spec_len", [0, 4], ids=["nospec", "spec4"])
@pytest.mark.parametrize("chunk", [None, 8], ids=["bucketed", "chunked"])
def test_fused_vs_unfused_greedy_byte_parity(tiny, spec_len, chunk):
    """Acceptance bar: fused and fuse=False emit byte-identical greedy
    tokens (prefix cache + COW on), with decode-side compiled programs
    exactly 1 fused vs <= 2 unfused."""
    cfg, params = tiny
    prompts = _mixed_prompts(cfg)
    outs, stats = {}, {}
    for fuse in (True, False):
        eng = LLMEngine(params, cfg, num_slots=3, page_size=8,
                        max_model_len=64, prefill_chunk=chunk,
                        spec_len=spec_len, fuse=fuse)
        rids = [eng.add_request(p, max_new_tokens=10) for p in prompts]
        res = eng.run()
        outs[fuse] = [list(res[r].tokens) for r in rids]
        stats[fuse] = eng.stats()
        eng.cache.check_invariants()
        assert eng.stats()["pages_in_use"] == 0
    assert outs[True] == outs[False]
    st = stats[True]
    assert st["decode_executables"] + st["verify_executables"] == 1
    if spec_len:
        assert st["verify_steps"] > 0      # drafts rode the fused program
    if chunk is not None:
        assert st["prefill_executables"] == 0  # the chunk rode it too


def test_fused_mp2_parity_and_aot_program_count(tiny):
    """mp=2 tensor-parallel fused serving: byte-identical tokens vs mp=1,
    decode-side exactly ONE AOT-compiled program (exact count, not a
    dispatch-cache size)."""
    cfg, params = tiny
    prompts = _mixed_prompts(cfg)
    outs = {}
    for mp in (1, 2):
        eng = LLMEngine(params, cfg, num_slots=3, page_size=8,
                        max_model_len=64, prefill_chunk=8, spec_len=3,
                        mp=mp if mp > 1 else None)
        rids = [eng.add_request(p, max_new_tokens=8) for p in prompts]
        res = eng.run()
        outs[mp] = [list(res[r].tokens) for r in rids]
        st = eng.stats()
        assert st["decode_executables"] + st["verify_executables"] == 1
        if mp > 1:
            assert eng._decode_fn._cache_size() == 1   # AOT: exact count
    assert outs[1] == outs[2]


def test_fused_sampled_parity_fixed_key(tiny):
    """Sampled path: with a fixed seed the fused on-device pick (shared
    `gpt.sample_token`, one split per decode dispatch) emits exactly the
    unfused engine's tokens in bucketed spec-off mode, where the two PRNG
    streams split in lockstep."""
    cfg, params = tiny
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 12, 20)]
    outs = {}
    for fuse in (True, False):
        eng = LLMEngine(params, cfg, num_slots=3, page_size=8,
                        max_model_len=64, temperature=0.8, seed=42,
                        spec_len=0, fuse=fuse)
        rids = [eng.add_request(p, max_new_tokens=8) for p in prompts]
        res = eng.run()
        outs[fuse] = [list(res[r].token_ids) for r in rids]
    assert outs[True] == outs[False]
    # the same engine still honors the per-request greedy fast path
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    temperature=0.8, seed=42, spec_len=0)
    rg = eng.add_request(prompts[0], max_new_tokens=8, temperature=0.0)
    ref = G.generate(params, jnp.asarray(prompts[0])[None], cfg,
                     max_new_tokens=8)
    np.testing.assert_array_equal(eng.run()[rg].tokens, np.asarray(ref[0]))


# ---------------------------------------------------------------------------
# the one-dispatch claim, asserted from the step trace
# ---------------------------------------------------------------------------

def test_busy_step_dispatches_exactly_one_program(tiny):
    """Acceptance bar: a steady-state busy step — decode + interleaved
    prefill chunk + verify all active — dispatches exactly ONE program, and
    the v2 trace record says so (per-mode slot occupancy included)."""
    cfg, params = tiny
    eng = LLMEngine(params, cfg, num_slots=3, page_size=8, max_model_len=64,
                    prefill_chunk=8, spec_len=3)
    rng = np.random.RandomState(1)
    # repetitive prompt: decoding + drafting while the long prompt chunks
    eng.add_request(np.tile(np.asarray([7, 3, 9], np.int32), 4),
                    max_new_tokens=16)
    for _ in range(3):
        eng.step()
    eng.add_request(rng.randint(0, cfg.vocab_size, (30,)).astype(np.int32),
                    max_new_tokens=4)
    eng.run()
    busy = [r for r in eng.step_trace()
            if r["decode_batch"] > 0 and r["chunk"] and
            r["verify_dispatches"] > 0]
    assert busy, "no decode+chunk+verify step in the trace"
    for r in busy:
        assert r["v"] == 2 and r["fused"]
        assert r["dispatches"] == 1
        assert r["slots"]["chunk"] == 1
        assert r["slots"]["verify"] >= 1
        assert "sync_ms" in r
    # every decode-path step of the whole run was one dispatch
    assert all(r["dispatches"] <= 1 for r in eng.step_trace())


def test_double_buffer_token_lands_in_next_step(tiny):
    """Double-buffer ordering through the injectable clock: the fused
    dispatch of step n returns un-synced and its token is observed during
    step n+1 (the harvest inside step n+1's sample-sync span), while
    double_buffer=False keeps the synchronous schedule."""
    cfg, params = tiny

    class Clk:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            return self.t

    for db, after_step1 in ((True, 1), (False, 2)):
        clk = Clk()
        eng = LLMEngine(params, cfg, num_slots=2, page_size=8,
                        max_model_len=64, double_buffer=db, clock=clk)
        eng.add_request(np.arange(5, dtype=np.int32), max_new_tokens=4)
        clk.t = 1.0
        eng.step()      # admit + prefill (first token) + fused dispatch
        seq = next(iter(eng._running.values()))
        assert len(seq.generated) == after_step1
        trace = eng.step_trace()
        assert trace[-1]["tokens_emitted"] == after_step1 - 1
        clk.t = 2.0
        eng.step()      # db: harvest of step 1's dispatch lands HERE
        assert len(next(iter(eng._running.values())).generated) == \
            after_step1 + 1
        if db:
            assert eng.step_trace()[-1]["tokens_emitted"] == 1
        outs = eng.run()
        assert len(next(iter(outs.values())).token_ids) == 4
    # parity between the two schedules, token for token
    res = {}
    for db in (True, False):
        eng = LLMEngine(params, cfg, num_slots=2, page_size=8,
                        max_model_len=64, spec_len=3, prefill_chunk=8,
                        double_buffer=db)
        rids = [eng.add_request(p, max_new_tokens=8)
                for p in _mixed_prompts(cfg, seed=5, n_extra=2)]
        out = eng.run()
        res[db] = [list(out[r].tokens) for r in rids]
    assert res[True] == res[False]


def test_steady_state_fused_loop_transfer_guard_clean(tiny):
    """The warmed fused+double-buffered loop — harvest fetch included — runs
    under `jax.transfer_guard("disallow")`: every h2d is an explicit staged
    placement and the per-step d2h is the one O(B*K)-int harvest."""
    cfg, params = tiny
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    num_pages=32, prefill_chunk=8, spec_len=3)
    rng = np.random.RandomState(0)
    for n in (5, 20):                   # warm chunk/decode/verify lanes
        eng.add_request(rng.randint(0, cfg.vocab_size, (n,))
                        .astype(np.int32), max_new_tokens=4)
    eng.run()
    eng.warm_decode()
    base = rng.randint(0, cfg.vocab_size, (13,)).astype(np.int32)
    eng.add_request(base, max_new_tokens=1)
    eng.run()                           # donor registers its prompt pages
    rids = [eng.add_request(rng.randint(0, cfg.vocab_size, (n,))
                            .astype(np.int32), max_new_tokens=5)
            for n in (7, 19)]
    rids.append(eng.add_request(np.concatenate([base, base[:4]]),
                                max_new_tokens=3))      # prefix hit + COW
    with jax.transfer_guard("disallow"):
        outs = eng.run()
    assert all(r in outs for r in rids)
    assert eng.stats()["prefix_cached_tokens"] > 0


def test_abort_mid_inflight_fused_batch_keeps_invariants(tiny):
    """check_invariants() after aborting a request whose fused batch is
    still in flight: the harvest-first abort keeps refcounts/partition
    exact, and the freed slot serves the next request with exact parity."""
    cfg, params = tiny
    rng = np.random.RandomState(2)
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    prefill_chunk=8, spec_len=4)
    prompt = np.tile(rng.randint(0, cfg.vocab_size, (3,)).astype(np.int32), 4)
    r1 = eng.add_request(prompt, max_new_tokens=20)
    eng.step()
    eng.step()
    assert eng._inflight is not None    # a fused batch is in flight
    assert eng.abort(r1)
    assert eng._inflight is None        # abort harvested it first
    eng.cache.check_invariants()
    assert eng.cache.pages_in_use() == 0
    assert eng._outputs[r1].finish_reason == "abort"
    nxt = rng.randint(0, cfg.vocab_size, (9,)).astype(np.int32)
    r2 = eng.add_request(nxt, max_new_tokens=6)
    ref = G.generate(params, jnp.asarray(nxt)[None], cfg, max_new_tokens=6)
    np.testing.assert_array_equal(eng.run()[r2].tokens, np.asarray(ref[0]))
    eng.cache.check_invariants()

    # mid-chunk abort: the staged chunk slot resolves through the harvest
    eng2 = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                     prefill_chunk=8, spec_len=4)
    rl = eng2.add_request(rng.randint(0, cfg.vocab_size, (30,))
                          .astype(np.int32), max_new_tokens=4)
    eng2.step()                         # chunk 1 of 4 staged + dispatched
    assert eng2.abort(rl)
    eng2.cache.check_invariants()
    assert eng2.cache.pages_in_use() == 0 and not eng2.has_work


# ---------------------------------------------------------------------------
# bench + CI wiring
# ---------------------------------------------------------------------------

def test_bench_dispatches_per_step_and_fuse_parity():
    """Acceptance bar (CPU smoke): the fused bench run shows
    dispatches_per_step <= 1.1 with byte-identical outputs vs --no-fuse on
    the same stream; the unfused chunked run shows the dispatch overhead the
    fusion removed (> 1 program per busy step)."""
    from bench_serve import run_serve_bench
    kw = dict(num_requests=12, num_slots=2, page_size=8, max_model_len=64,
              max_new_tokens=6, prefill_chunk=16, shared_prefix_frac=0.5,
              spec_len=4, seed=11)
    fused = run_serve_bench(**kw, fuse=True)
    unfused = run_serve_bench(**kw, fuse=False)
    assert fused["fused"] and not unfused["fused"]
    assert fused["dispatches_per_step"] <= 1.1
    assert unfused["dispatches_per_step"] > 1.0
    assert fused["outputs_digest"] == unfused["outputs_digest"]
    assert fused["decode_executables"] + fused["verify_executables"] == 1
    assert fused["prefill_executables"] == 0    # chunk rides the fused batch
    assert fused["host_sync_ms_per_step"] >= 0.0
    assert fused["accepted_per_step"] > 1.0     # spec still pays inside fusion


def test_program_budget_decode_side_one():
    """Satellite (CI wiring): the tightened budget — decode-side <= 1 — is
    declared once in analysis/registry.py and both measurement passes of
    check_program_count enforce it."""
    from paddle_tpu.analysis.registry import (SERVE_PROGRAM_BUDGET,
                                              SERVE_PROGRAM_BUDGET_MP)
    assert SERVE_PROGRAM_BUDGET["decode_side_executables"] == 1
    assert SERVE_PROGRAM_BUDGET_MP["decode_side_executables"] == 1
    import tools.check_program_count as cpc
    assert cpc.BUDGET is SERVE_PROGRAM_BUDGET          # declared ONCE
    assert cpc.BUDGET_MP is SERVE_PROGRAM_BUDGET_MP


def test_fused_jaxpr_audit_host_output_budget():
    """The fused executable's jaxpr passes JXP001-005 — in particular the
    host-visible output is O(B*K) ints — and a logits-returning variant is
    caught by the new JXP005 audit."""
    from paddle_tpu.analysis.jaxpr_checks import audit_jaxpr, serving_targets
    targets = [t for t in serving_targets(1) if "fused_step" in t[0]]
    assert targets, "fused executable missing from the jaxpr target set"
    name, fn, args, kw = targets[0]
    assert kw.get("host_output_budget")
    assert audit_jaxpr(name, fn, args, **kw) == []
