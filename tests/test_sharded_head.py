"""Vocab-sharded serving head: sharded argmax/top-k merge goldens, cross-mp
byte parity, and the ratcheted replicated-bytes account.

The serving layout shards `wte`/`lm_head` (and their int8 twins) along the
vocab axis (`parallel.hybrid.serving_param_specs`), keeps the `[B, T, V/mp]`
logits sharded, and merges the pick on device: `sharded_argmax` reproduces
`jnp.argmax`'s first-occurrence tie-break exactly (local max/argmax ->
pmax -> index-min over the argmax-achieving shards), and `sample_token`'s
top-k path computes the global k-th threshold from a tiled all-gather of the
per-shard top-k.  Because the full-width Gumbel noise is drawn OUTSIDE the
manual region, the sampled pick is bit-identical across mp — so mp1/mp2/mp4
engines must emit BYTE-IDENTICAL tokens, greedy and sampled, fp and int8.

JXP006 (`analysis.cost_model.audit_resources`) enforces the ratcheted
per-buffer replicated ceiling this layout bought (registry:
replicated_bytes_ceiling) — the pos/neg pair here injects budgets around the
measured account so the ratchet cannot silently loosen.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import gpt as G
from paddle_tpu.parallel.hybrid import serving_mesh
from paddle_tpu.inference.engine import LLMEngine


@pytest.fixture(scope="module")
def tiny():
    cfg = G.gpt_tiny(64)
    return cfg, G.init_params(cfg, jax.random.key(0))


def _mixed_prompts(cfg, seed=0):
    """Mixed stream incl. a shared-prefix pair, so prefix cache + COW are on
    the parity path (same shape as the fused-step suite's stream)."""
    rng = np.random.RandomState(seed)
    pat = rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32)
    prompts = [np.tile(pat, 3)]
    prompts += [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
                for n in (5, 9, 17, 30)]
    prompts.append(np.concatenate(
        [prompts[-1], rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32)]))
    return prompts


# ---------------------------------------------------------------------------
# unit goldens: the on-device merge vs the replicated reference
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mp", [2, 4])
@pytest.mark.parametrize("shape", [(3, 64), (2, 3, 64)],
                         ids=["decode2d", "verify3d"])
def test_sharded_argmax_matches_replicated(mp, shape):
    """Golden: the pmax/pmin merge equals `jnp.argmax` on random logits,
    over both logits ranks the fused program produces."""
    logits = jax.random.normal(jax.random.key(5), shape, jnp.float32)
    mesh = serving_mesh(mp)
    out = G.sharded_argmax(logits, mesh)
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(jnp.argmax(logits, axis=-1)))
    assert out.dtype == jnp.int32


@pytest.mark.parametrize("mp", [2, 4])
def test_sharded_argmax_tie_break_first_occurrence(mp):
    """Determinism golden: constructed ties — equal maxima within one shard,
    across shards, and in the last shard only — resolve to the LOWEST global
    index, exactly `jnp.argmax`'s first-occurrence rule.  This is the rule
    that makes mp1/mp2/mp4 greedy streams byte-identical."""
    V = 64
    rows = [
        ([5, 37], 5),       # tie across shards (mp2: shard 0 vs 1) -> first
        ([40, 8], 8),       # later shard listed first -> still global min
        ([10, 12], 10),     # tie inside one shard
        ([63], 63),         # max in the last shard only
        ([0, 32, 48], 0),   # three-way tie spanning shards
    ]
    logits = np.zeros((len(rows), V), np.float32)
    for r, (idxs, _) in enumerate(rows):
        logits[r, idxs] = 1.0
    out = np.asarray(G.sharded_argmax(jnp.asarray(logits), serving_mesh(mp)))
    np.testing.assert_array_equal(out, [want for _, want in rows])
    np.testing.assert_array_equal(
        out, np.asarray(jnp.argmax(jnp.asarray(logits), axis=-1)))


@pytest.mark.parametrize("top_k", [0, 7], ids=["full", "topk7"])
@pytest.mark.parametrize("mp", [2, 4])
def test_sharded_sample_token_matches_replicated(mp, top_k):
    """Golden: `sample_token` under a mesh emits exactly the mp=1 pick for
    the same key — the shared full-width Gumbel draw + the all-gathered
    k-th-value threshold make the sharded pick bit-identical."""
    logits = jax.random.normal(jax.random.key(9), (4, 64), jnp.float32)
    key = jax.random.key(7)
    ref, ref_key = G.sample_token(logits, key, sample=True, temperature=0.8,
                                  top_k=top_k)
    ids, new_key = G.sample_token(logits, key, sample=True, temperature=0.8,
                                  top_k=top_k, mesh=serving_mesh(mp))
    np.testing.assert_array_equal(np.asarray(ids), np.asarray(ref))
    np.testing.assert_array_equal(jax.random.key_data(new_key),
                                  jax.random.key_data(ref_key))


# ---------------------------------------------------------------------------
# engine parity: byte-identical streams across mesh sizes
# ---------------------------------------------------------------------------

def _greedy_tokens(params, cfg, prompts, mp, **kw):
    eng = LLMEngine(params, cfg, num_slots=3, page_size=8, max_model_len=64,
                    mp=mp if mp > 1 else None, **kw)
    rids = [eng.add_request(p, max_new_tokens=8) for p in prompts]
    res = eng.run()
    return [list(res[r].tokens) for r in rids]


def test_greedy_byte_parity_mp124(tiny):
    """Acceptance bar: mp=1/2/4 engines emit BYTE-IDENTICAL greedy tokens in
    the full serving mode (spec + chunked prefill, prefix cache + COW on) —
    the vocab-sharded head and merge change nothing observable."""
    cfg, params = tiny
    prompts = _mixed_prompts(cfg)
    outs = {mp: _greedy_tokens(params, cfg, prompts, mp,
                               prefill_chunk=8, spec_len=3)
            for mp in (1, 2, 4)}
    assert outs[1] == outs[2] == outs[4]


@pytest.mark.slow
@pytest.mark.parametrize("spec_len,chunk",
                         [(0, None), (3, None), (0, 8)],
                         ids=["plain", "spec", "chunked"])
def test_greedy_byte_parity_mp124_mode_matrix(tiny, spec_len, chunk):
    """The remaining serving modes of the 4-mode acceptance matrix (the
    spec+chunk combination runs non-slow above)."""
    cfg, params = tiny
    prompts = _mixed_prompts(cfg)
    outs = {mp: _greedy_tokens(params, cfg, prompts, mp,
                               prefill_chunk=chunk, spec_len=spec_len)
            for mp in (1, 2, 4)}
    assert outs[1] == outs[2] == outs[4]


def test_sampled_fixed_key_parity_mp12(tiny):
    """Sampled path: a fixed seed emits identical token streams on mp=1 and
    mp=2 engines (the PRNG streams split in lockstep; the sharded pick is
    bit-identical per draw), with and without top-k."""
    cfg, params = tiny
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 12)]
    for tk in (0, 7):
        outs = {}
        for mp in (1, 2):
            eng = LLMEngine(params, cfg, num_slots=2, page_size=8,
                            max_model_len=64, temperature=0.8, seed=42,
                            top_k=tk or None, spec_len=0,
                            mp=mp if mp > 1 else None)
            rids = [eng.add_request(p, max_new_tokens=8) for p in prompts]
            res = eng.run()
            outs[mp] = [list(res[r].token_ids) for r in rids]
        assert outs[1] == outs[2], f"sampled divergence at top_k={tk}"


@pytest.mark.slow
def test_sampled_fixed_key_parity_mp4(tiny):
    cfg, params = tiny
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 12)]
    outs = {}
    for mp in (1, 4):
        eng = LLMEngine(params, cfg, num_slots=2, page_size=8,
                        max_model_len=64, temperature=0.8, seed=42,
                        top_k=7, spec_len=0, mp=mp if mp > 1 else None)
        rids = [eng.add_request(p, max_new_tokens=8) for p in prompts]
        res = eng.run()
        outs[mp] = [list(res[r].token_ids) for r in rids]
    assert outs[1] == outs[4]


def test_int8_top1_agreement_mp12(tiny):
    """int8 weights: quantization is applied BEFORE sharding, so the sharded
    int8 head sees the same quantized table per vocab row and the greedy
    (top-1) stream stays byte-identical across mesh sizes."""
    cfg, params = tiny
    prompts = _mixed_prompts(cfg)[:3]
    outs = {mp: _greedy_tokens(params, cfg, prompts, mp,
                               weight_dtype="int8")
            for mp in (1, 2)}
    assert outs[1] == outs[2]


@pytest.mark.slow
def test_int8_top1_agreement_mp4(tiny):
    cfg, params = tiny
    prompts = _mixed_prompts(cfg)[:3]
    outs = {mp: _greedy_tokens(params, cfg, prompts, mp, weight_dtype="int8",
                               prefill_chunk=8, spec_len=2)
            for mp in (1, 4)}
    assert outs[1] == outs[4]


# ---------------------------------------------------------------------------
# JXP006: the ratcheted replicated-bytes ceiling (pos/neg by injection)
# ---------------------------------------------------------------------------

def test_jxp006_ratchet_positive_and_negative(tiny):
    """The measured mp=2 account passes the DECLARED (ratcheted) ceiling and
    a squeezed injected ceiling flags the largest replicated leaf — proving
    the declared number still bites; `wte`/`lm_head` must sit in the sharded
    column, never among the JXP006 offenders."""
    from paddle_tpu.analysis.cost_model import (AtRestAccount, params_at_rest,
                                                audit_resources)
    from paddle_tpu.analysis.registry import SERVE_RESOURCE_BUDGET

    cfg, params = tiny
    at_rest = AtRestAccount(2, params_at_rest(params, cfg, mp=2))
    sharded = {b.name for b in at_rest.buffers if b.sharded}
    assert "wte" in sharded          # tied head: wte doubles as lm_head

    # negative: the declared ratchet holds on the measured account
    _, findings = audit_resources([], at_rest, SERVE_RESOURCE_BUDGET,
                                  compile_collectives=False)
    assert [f for f in findings if f.rule == "JXP006"] == []

    # positive: squeeze the ceiling below the largest replicated leaf —
    # JXP006 must fire and must NOT name a vocab-sharded buffer
    top = max((b for b in at_rest.buffers
               if not b.sharded and not b.name.startswith("pool.")),
              key=lambda b: b.bytes)
    _, findings = audit_resources(
        [], at_rest, {"replicated_bytes_ceiling": top.bytes - 1},
        compile_collectives=False)
    hits = [f for f in findings if f.rule == "JXP006"]
    assert hits and any(f"`{top.name}`" in f.message for f in hits)
    assert not any("wte" in f.message or "lm_head" in f.message
                   for f in hits)

    # mp=1 keeps replication free: the same squeezed ceiling stays silent
    at_rest1 = AtRestAccount(1, params_at_rest(params, cfg, mp=1))
    _, findings = audit_resources(
        [], at_rest1, {"replicated_bytes_ceiling": 1},
        compile_collectives=False)
    assert [f for f in findings if f.rule == "JXP006"] == []
