"""Speculative decoding (Leviathan et al. 2023) in the serving engine:
n-gram self-drafting + single-program multi-token verify over the paged KV
cache.

Covers the PR-3 acceptance bars: n-gram proposer unit behaviour, the verify
lane of the q_offset paged-attention kernel vs its XLA oracle at q_len > 1,
`verify_step_paged` logit parity against chained single-token decode, exact
greedy token parity spec-on vs spec-off at engine level (prefix cache on AND
off, chunked and bucketed prefill), rollback/abort refcount invariants, the
per-request greedy fast path, accepted_per_step > 1 on a repetitive stream,
and the compiled-program bound (decode-side <= 2 = seed + 1).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import gpt as G
from paddle_tpu.inference.engine import LLMEngine
from paddle_tpu.inference.spec import NgramProposer
from paddle_tpu.incubate.kernels.paged_attention import (
    paged_prefill_attention_pallas, paged_verify_attention)


@pytest.fixture(scope="module")
def tiny():
    cfg = G.gpt_tiny(64)
    return cfg, G.init_params(cfg, jax.random.key(0))


# ---------------------------------------------------------------------------
# n-gram proposer (pure host)
# ---------------------------------------------------------------------------

def test_ngram_proposer_matches_most_recent_occurrence():
    p = NgramProposer(max_ngram=3, min_ngram=1)
    #         0  1  2  3  4  5  6  7  8
    ctx = [9, 1, 2, 3, 7, 1, 2, 3, 5, 1, 2, 3]
    # trailing 3-gram (1,2,3) occurred at 1 and 5; most recent is 5 ->
    # continuation [5, 1, 2, 3] follows it
    np.testing.assert_array_equal(p.propose(np.asarray(ctx), 4), [5, 1, 2, 3])
    # max_tokens truncates
    np.testing.assert_array_equal(p.propose(np.asarray(ctx), 2), [5, 1])


def test_ngram_proposer_prefers_longer_ngrams():
    p = NgramProposer(max_ngram=3, min_ngram=1)
    # trailing 2-gram (2,3) matches at 1..2 (-> 8) and the 1-gram 3 matches
    # at 6 (-> 9); the longer match wins
    ctx = [1, 2, 3, 8, 0, 0, 3, 9, 2, 3]
    np.testing.assert_array_equal(p.propose(np.asarray(ctx), 1), [8])
    # min_ngram=3 refuses the short matches entirely
    assert NgramProposer(max_ngram=3, min_ngram=3).propose(
        np.asarray(ctx), 4) is None


def test_ngram_proposer_self_loop_and_edges():
    p = NgramProposer()
    # a looping generation drafts its own loop: every recent hit is truncated
    # by the tail, so the EARLIEST occurrence supplies the longest run
    # (the trailing 3-gram wins at n=3; its earliest occurrence j=0 leaves a
    # 3-token continuation, vs the single token after the most recent hit)
    np.testing.assert_array_equal(p.propose(np.asarray([7] * 6), 4),
                                  [7, 7, 7])
    np.testing.assert_array_equal(p.propose(np.asarray([7, 7, 7]), 4), [7])
    assert p.propose(np.asarray([1, 2, 3, 4]), 4) is None   # no repeat
    assert p.propose(np.asarray([5]), 4) is None            # too short
    assert p.propose(np.asarray([5, 5]), 0) is None         # no budget
    # bounded lookback: a match older than the window is not scanned (the
    # proposer runs on the host every decode iteration — O(window), not
    # O(context)), while an in-window match still hits
    far = np.concatenate([[3, 1, 4], np.arange(10, 30), [3, 1, 4]])
    assert NgramProposer(max_lookback=6).propose(far, 4) is None
    np.testing.assert_array_equal(
        NgramProposer(max_lookback=far.size).propose(far, 2), [10, 11])
    with pytest.raises(ValueError):
        NgramProposer(max_ngram=2, min_ngram=3)
    with pytest.raises(ValueError):
        NgramProposer(max_lookback=1)


# ---------------------------------------------------------------------------
# verify kernel + verify step numerics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kvh", [2, 1], ids=["gqa", "mqa"])
def test_verify_kernel_matches_xla_oracle_qlen_gt1(kvh):
    """The verify lane (q_len > 1 decode: q_offset = lengths, per-slot valid
    counts including the valid=1 no-draft degenerate) agrees with the gather
    oracle, Pallas kernel in interpret mode on CPU."""
    rng = np.random.RandomState(0)
    B, T, H, hd, page, P, mp = 3, 5, 4, 64, 8, 9, 4
    q = jnp.asarray(rng.randn(B, T, H, hd), jnp.float32)
    k = jnp.asarray(rng.randn(P, page, kvh, hd), jnp.float32)
    v = jnp.asarray(rng.randn(P, page, kvh, hd), jnp.float32)
    tbl = np.zeros((B, mp), np.int32)
    tbl[0, :3] = [1, 2, 3]
    tbl[1, :2] = [4, 5]
    tbl[2, :4] = [6, 7, 8, 3]
    lengths = jnp.asarray([9, 4, 17], jnp.int32)     # q_offset = lengths
    valid = jnp.asarray([5, 1, 3], jnp.int32)        # incl. the no-draft edge
    ref = paged_verify_attention(q, k, v, jnp.asarray(tbl), lengths, valid)
    got = paged_prefill_attention_pallas(q, k, v, jnp.asarray(tbl), lengths,
                                         valid, interpret=True)
    for b, n in enumerate(np.asarray(valid)):
        np.testing.assert_allclose(np.asarray(got)[b, :n],
                                   np.asarray(ref)[b, :n], atol=2e-5)


@pytest.mark.parametrize("preset", [G.gpt_tiny, G.llama_tiny],
                         ids=["gpt", "llama"])
def test_verify_step_matches_dense_forward(preset):
    """verify_step_paged scores T positions in one pass with the logits of
    the dense forward (== chained single-token decode, per the existing
    decode-parity tests) — the property greedy acceptance relies on — and a
    valid-masked call (the rollback shape) leaves the accepted prefix intact:
    a later verify over the once-rejected positions still matches."""
    cfg = preset(64)
    params = G.init_params(cfg, jax.random.key(1))
    rng = np.random.RandomState(1)
    toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (1, 13)), jnp.int32)
    dense = np.asarray(G.forward(params, toks, cfg))        # [1, 13, V]
    page, Tp, T = 4, 8, 4
    table = np.zeros((1, 6), np.int32)
    table[0, :4] = [3, 1, 4, 2]
    tbl = jnp.asarray(table)
    ids = np.zeros((1, 8), np.int32)
    ids[0, :Tp] = np.asarray(toks[0, :Tp])
    pool = G.init_paged_cache(cfg, num_pages=10, page_size=page)
    _, pool = G.prefill_chunk_paged(
        params, jnp.asarray(ids), cfg, pool, tbl,
        jnp.asarray([0], jnp.int32), jnp.asarray([Tp], jnp.int32))
    # verify with valid=2: tokens Tp, Tp+1 land, Tp+2.. masked (rollback)
    vlog, pool = G.verify_step_paged(
        params, toks[:, Tp:Tp + T], pool, tbl, jnp.asarray([Tp], jnp.int32),
        jnp.asarray([2], jnp.int32), cfg)
    for t in range(2):
        np.testing.assert_allclose(np.asarray(vlog[:, t]), dense[:, Tp + t],
                                   atol=2e-4, rtol=2e-4)
    # re-verify from position Tp+2 over the once-rejected region (3 real
    # tokens + 1 padded row): the accepted prefix survived the masked call
    vt = np.zeros((1, T), np.int32)
    vt[0, :3] = np.asarray(toks[0, Tp + 2:Tp + 5])
    vlog2, pool = G.verify_step_paged(
        params, jnp.asarray(vt), pool, tbl,
        jnp.asarray([Tp + 2], jnp.int32), jnp.asarray([3], jnp.int32), cfg)
    for t in range(3):
        np.testing.assert_allclose(np.asarray(vlog2[:, t]),
                                   dense[:, Tp + 2 + t],
                                   atol=2e-4, rtol=2e-4)


# ---------------------------------------------------------------------------
# engine-level parity + acceptance + executable bound
# ---------------------------------------------------------------------------

def test_engine_spec_parity_and_program_bound(tiny):
    """Acceptance bar: spec-on emits exactly the spec-off greedy tokens —
    prefix cache on AND off — within <= 2 decode-side programs (seed bound
    was 1; spec adds exactly the verify executable)."""
    cfg, params = tiny
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 9, 17, 3)]
    base = prompts[2]
    prompts.append(np.concatenate(          # shared prefix: COW lane too
        [base, rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32)]))
    outs = {}
    engines = {}
    # one spec-off reference; spec-on with the prefix cache on AND off
    for key, kw in (("off", dict(spec_len=0)),
                    ("spec", dict(spec_len=4)),
                    ("spec-nopfx", dict(spec_len=4, prefix_cache=False))):
        eng = LLMEngine(params, cfg, num_slots=3, page_size=8,
                        max_model_len=64, **kw)
        rids = [eng.add_request(p, max_new_tokens=10) for p in prompts]
        res = eng.run()
        outs[key] = [res[r].tokens for r in rids]
        engines[key] = eng
    for key in ("spec", "spec-nopfx"):
        for a, b in zip(outs["off"], outs[key]):
            np.testing.assert_array_equal(a, b)
        st = engines[key].stats()
        assert st["decode_executables"] + st["verify_executables"] <= 2
        assert st["verify_steps"] > 0 and st["spec_emitted_tokens"] > 0
        assert st["pages_in_use"] == 0
        engines[key].cache.check_invariants()
        # spec strictly reduced decode iterations on this stream
        assert st["decode_iterations"] < \
            engines["off"].stats()["decode_iterations"]


def test_engine_spec_chunked_prefill_parity(tiny):
    """Spec decoding composes with Sarathi chunked prefill: mid-prefill slots
    stay masked out of the verify dispatch and tokens match generate()."""
    cfg, params = tiny
    eng = LLMEngine(params, cfg, num_slots=3, page_size=8, max_model_len=64,
                    prefill_chunk=8, spec_len=3)
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (30, 5, 17)]
    rids = [eng.add_request(p, max_new_tokens=8) for p in prompts]
    outs = eng.run()
    for rid, p in zip(rids, prompts):
        ref = G.generate(params, jnp.asarray(p)[None], cfg, max_new_tokens=8)
        np.testing.assert_array_equal(outs[rid].tokens, np.asarray(ref[0]))
    st = eng.stats()
    assert st["decode_executables"] + st["verify_executables"] <= 2
    assert st["prefill_executables"] <= 2
    assert st["pages_in_use"] == 0


def test_engine_spec_eos_inside_accepted_prefix(tiny):
    """A drafted token equal to EOS truncates the emitted run at the EOS —
    token-for-token what vanilla decode does — and retires the slot."""
    cfg, params = tiny
    prompt = np.zeros((3,), np.int32)
    ref = np.asarray(G.generate(params, jnp.asarray(prompt)[None], cfg,
                                max_new_tokens=10)[0])
    eos = int(ref[6])                   # whatever greedy emits mid-stream
    van = LLMEngine(params, cfg, num_slots=1, page_size=8, max_model_len=64,
                    eos_token_id=eos)
    rv = van.add_request(prompt, max_new_tokens=10)
    spec = LLMEngine(params, cfg, num_slots=1, page_size=8, max_model_len=64,
                     eos_token_id=eos, spec_len=4)
    rs = spec.add_request(prompt, max_new_tokens=10)
    a, b = van.run()[rv], spec.run()[rs]
    np.testing.assert_array_equal(a.tokens, b.tokens)
    assert b.finish_reason == a.finish_reason
    assert spec.cache.pages_in_use() == 0


def test_accepted_per_step_exceeds_one_on_repetitive_stream(tiny):
    """Self-drafting pays off on repetitive continuations: a stream of
    looping/repetitive prompts accepts > 1 token per drafted verify."""
    cfg, params = tiny
    eng = LLMEngine(params, cfg, num_slots=3, page_size=8, max_model_len=64,
                    spec_len=4)
    rng = np.random.RandomState(0)
    pat = rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32)
    prompts = [np.tile(pat, 3)] + \
        [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
         for n in (7, 12, 5)]
    rids = [eng.add_request(p, max_new_tokens=12) for p in prompts]
    outs = eng.run()
    # parity holds regardless: spot-check the tiled prompt against generate
    ref = G.generate(params, jnp.asarray(prompts[0])[None], cfg,
                     max_new_tokens=12)
    np.testing.assert_array_equal(outs[rids[0]].tokens, np.asarray(ref[0]))
    st = eng.stats()
    assert st["spec_accepted_tokens"] > 0
    assert st["accepted_per_step"] > 1.0
    # spec emitted more tokens than it ran decode iterations for
    assert st["decode_tokens"] > st["decode_iterations"]


# ---------------------------------------------------------------------------
# rollback / abort refcount invariants (satellite bugfix)
# ---------------------------------------------------------------------------

def test_spec_rollback_keeps_refcount_invariants(tiny):
    """Every engine step during a spec-heavy run (shared prefixes, draft
    rejections, retirements) preserves the free/LRU/in-use page partition and
    exact refcounts."""
    cfg, params = tiny
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    num_pages=12, spec_len=4)
    rng = np.random.RandomState(3)
    base = rng.randint(0, cfg.vocab_size, (21,)).astype(np.int32)
    ext = np.concatenate([base,
                          rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)])
    for p in (base, ext, base.copy()):
        eng.add_request(p, max_new_tokens=8)
    while eng.has_work:
        eng.step()
        eng.cache.check_invariants()
    st = eng.stats()
    assert st["pages_in_use"] == 0 and st["verify_steps"] > 0
    # drafts were offered and rejections rolled back (not everything accepts)
    assert st["spec_drafted_tokens"] >= st["spec_accepted_tokens"] > 0


def test_abort_mid_verify_and_mid_chunk_prefill(tiny):
    """abort() of a slot that has speculatively-written (rolled-back) KV, of
    a mid-chunk-prefill slot holding shared prefix pages, and of a queued
    request behind another MUST deref pages cleanly.  The queued case used to
    raise: deque.remove's equality scan hit Request.__eq__, whose numpy
    prompt comparison has no scalar truth value."""
    cfg, params = tiny
    rng = np.random.RandomState(2)
    # --- mid-verify: slot has stale rejected-candidate KV above lengths ---
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    spec_len=4)
    prompt = np.tile(rng.randint(0, cfg.vocab_size, (3,)).astype(np.int32), 4)
    r1 = eng.add_request(prompt, max_new_tokens=12)
    while eng.stats()["verify_steps"] < 2:
        eng.step()
    assert eng.abort(r1)
    eng.cache.check_invariants()
    assert eng.cache.pages_in_use() == 0 and not eng.has_work
    assert eng._outputs[r1].finish_reason == "abort"
    # the freed slot serves the next request with exact parity
    nxt = rng.randint(0, cfg.vocab_size, (9,)).astype(np.int32)
    r2 = eng.add_request(nxt, max_new_tokens=6)
    ref = G.generate(params, jnp.asarray(nxt)[None], cfg, max_new_tokens=6)
    np.testing.assert_array_equal(eng.run()[r2].tokens, np.asarray(ref[0]))
    eng.cache.check_invariants()

    # --- mid-chunk-prefill with SHARED prefix pages: deref exactly once ---
    eng2 = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                     prefill_chunk=8, spec_len=4)
    base = rng.randint(0, cfg.vocab_size, (24,)).astype(np.int32)
    rd = eng2.add_request(base, max_new_tokens=4)
    eng2.run()                          # donor registers its prompt pages
    ext = np.concatenate([base, rng.randint(0, cfg.vocab_size,
                                            (20,)).astype(np.int32)])
    rx = eng2.add_request(ext, max_new_tokens=4)
    eng2.step()                         # admitted w/ shared pages, 1 chunk in
    assert rd in eng2._outputs and rx not in eng2._outputs  # rx mid-prefill
    slot = next(iter(eng2._prefilling))
    shared_page = int(eng2.cache.page_table[slot][0])
    assert eng2.cache._ref[shared_page] == 1    # donor retired, ext holds it
    assert eng2.abort(rx)
    eng2.cache.check_invariants()
    assert eng2.cache.pages_in_use() == 0
    assert eng2.cache._ref[shared_page] == 0    # deref'd exactly once

    # --- queued abort behind another queued request (regression) ---
    eng3 = LLMEngine(params, cfg, num_slots=1, page_size=8, max_model_len=64,
                     num_pages=9)
    q0 = eng3.add_request(rng.randint(0, cfg.vocab_size, (5,))
                          .astype(np.int32), max_new_tokens=4)
    qa = eng3.add_request(rng.randint(0, cfg.vocab_size, (6,))
                          .astype(np.int32), max_new_tokens=4)
    qb = eng3.add_request(rng.randint(0, cfg.vocab_size, (7,))
                          .astype(np.int32), max_new_tokens=4)
    assert eng3.abort(qb) and eng3.abort(qa)    # qb sits BEHIND qa
    assert eng3.abort(q0) and not eng3.has_work
    eng3.cache.check_invariants()


# ---------------------------------------------------------------------------
# per-request greedy fast path (satellite)
# ---------------------------------------------------------------------------

def test_greedy_fast_path_in_sampling_engine(tiny):
    """add_request(temperature=0.0) on a sampling engine takes argmax —
    exact parity with greedy generate(), PRNG-independent — and spec-decode
    drafts apply to the greedy request only."""
    cfg, params = tiny
    rng = np.random.RandomState(5)
    p = rng.randint(0, cfg.vocab_size, (7,)).astype(np.int32)
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    temperature=0.8, seed=9, spec_len=4)
    rg = eng.add_request(p, max_new_tokens=10, temperature=0.0)
    rs = eng.add_request(p, max_new_tokens=10)          # sampled lane
    outs = eng.run()
    ref = G.generate(params, jnp.asarray(p)[None], cfg, max_new_tokens=10)
    np.testing.assert_array_equal(outs[rg].tokens, np.asarray(ref[0]))
    st = eng.stats()
    assert st["verify_steps"] > 0                       # greedy slot drafted
    assert st["decode_executables"] == 1                # sampled slot decoded
    with pytest.raises(ValueError, match="per-request temperature"):
        eng.add_request(p, temperature=0.3)             # != engine temp
    with pytest.raises(ValueError, match="must be >= 0"):
        eng.add_request(p, temperature=-0.7)            # typo'd sign

    # a fully greedy engine never consumes its PRNG key
    g = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64)
    with pytest.raises(ValueError, match="cannot serve sampled"):
        g.add_request(p, temperature=0.7)
    k0 = np.asarray(jax.random.key_data(g._key)).copy()
    g.add_request(p, max_new_tokens=5)
    g.run()
    np.testing.assert_array_equal(np.asarray(jax.random.key_data(g._key)), k0)


# ---------------------------------------------------------------------------
# CI wiring: bench smoke + program-count guard (acceptance bar)
# ---------------------------------------------------------------------------

def test_bench_serve_spec_cpu_smoke():
    """Acceptance bar: --spec-len 4 on a repetitive/shared-prefix CPU-smoke
    stream shows accepted_per_step > 1.2 and EXACT greedy token parity with
    --no-spec (byte-identical output digests), within <= 2 decode-side
    compiled programs."""
    from bench_serve import run_serve_bench
    kw = dict(num_requests=12, num_slots=2, page_size=8, max_model_len=64,
              max_new_tokens=6, prefill_chunk=16, shared_prefix_frac=0.5,
              seed=11)
    spec = run_serve_bench(**kw, spec_len=4)
    base = run_serve_bench(**kw, spec_len=0)
    assert spec["outputs_digest"] == base["outputs_digest"]     # exact parity
    assert spec["accepted_per_step"] > 1.2
    assert spec["decode_executables"] + spec["verify_executables"] <= 2
    assert base["verify_steps"] == 0 and base["accepted_per_step"] == 0.0
    # spec needs fewer decode iterations for the same emitted tokens
    assert spec["decode_iters"] < base["decode_iters"]


def test_check_program_count_tool():
    """Satellite (CI wiring): the program-count guard measures within budget
    and fails loudly when the budget is exceeded."""
    import tools.check_program_count as cpc
    got, stats = cpc.measure()
    assert got["decode_side_executables"] <= cpc.BUDGET["decode_side_executables"]
    assert got["total_executables"] <= cpc.BUDGET["total_executables"]
    assert stats["accepted_per_step"] > 1.0
    # per-mesh-config budget: the mp=2 tensor-parallel pass replays the same
    # stream within the mp budget and emits byte-identical greedy tokens
    got_mp, stats_mp = cpc.measure(mp=2)
    assert got_mp["decode_side_executables"] <= \
        cpc.BUDGET_MP["decode_side_executables"]
    assert got_mp["total_executables"] <= cpc.BUDGET_MP["total_executables"]
    assert stats_mp["outputs_digest"] == stats["outputs_digest"]


# ---------------------------------------------------------------------------
# adaptive spec back-off (per-slot)
# ---------------------------------------------------------------------------

class _AlwaysWrongProposer:
    """Drafts a constant token stream the tiny random model never emits, so
    acceptance is exactly 0 on every verify event."""
    max_lookback = 4

    def __init__(self, token):
        self.token = token
        self.calls = 0

    def propose(self, context, max_tokens):
        self.calls += 1
        return np.full((max_tokens,), self.token, np.int32)


def test_adaptive_spec_backoff_stops_dead_drafting(tiny):
    """A slot whose drafts are never accepted stops being proposed for after
    `spec_backoff_window` zero-accept verify events: the proposer is no
    longer scanned for it, drafted-token counters freeze, the back-off shows
    in stats(), and the emitted tokens are STILL exactly the vanilla greedy
    stream (acceptance is lossless either way)."""
    cfg, params = tiny
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)
    W, NEW = 3, 24

    base = LLMEngine(params, cfg, num_slots=1, page_size=8, max_model_len=64,
                     spec_len=0)
    base.add_request(prompt, max_new_tokens=NEW)
    ref = next(iter(base.run().values())).token_ids

    # pick a draft token the greedy stream never contains -> 0% acceptance
    bad = next(t for t in range(cfg.vocab_size) if t not in ref)
    prop = _AlwaysWrongProposer(bad)
    eng = LLMEngine(params, cfg, num_slots=1, page_size=8, max_model_len=64,
                    spec_len=3, draft_proposer=prop, spec_backoff_window=W)
    eng.add_request(prompt, max_new_tokens=NEW)
    out = next(iter(eng.run().values())).token_ids
    st = eng.stats()
    assert out == ref                         # parity regardless of back-off
    assert st["spec_backoffs"] == 1           # the slot backed off once
    # exactly W drafted events of spec_len tokens, then drafting stopped
    assert prop.calls == W
    assert st["spec_drafted_tokens"] == W * 3
    assert st["spec_accepted_tokens"] == 0
    eng.cache.check_invariants()

    # window=0 disables the back-off: the proposer is scanned every iteration
    prop2 = _AlwaysWrongProposer(bad)
    eng2 = LLMEngine(params, cfg, num_slots=1, page_size=8, max_model_len=64,
                     spec_len=3, draft_proposer=prop2, spec_backoff_window=0)
    eng2.add_request(prompt, max_new_tokens=NEW)
    out2 = next(iter(eng2.run().values())).token_ids
    assert out2 == ref
    assert eng2.stats()["spec_backoffs"] == 0
    assert prop2.calls > W


def test_adaptive_spec_backoff_resets_on_acceptance(tiny):
    """Accepted drafts reset the zero-accept streak: an NgramProposer on a
    repetitive greedy stream keeps drafting (no back-off) while emitting the
    exact vanilla tokens."""
    cfg, params = tiny
    prompt = np.asarray([9, 9, 9, 9, 9, 9], np.int32)   # tight loop
    eng = LLMEngine(params, cfg, num_slots=1, page_size=8, max_model_len=64,
                    spec_len=3, spec_backoff_window=2)
    eng.add_request(prompt, max_new_tokens=16)
    eng.run()
    st = eng.stats()
    if st["spec_accepted_tokens"] > 0:        # stream-dependent, usually true
        assert st["spec_backoffs"] == 0
