"""static.nn control flow (data-dependent, under capture), launch auto-tuner,
custom-op registration (ref static/nn/control_flow.py, auto_tuner/tuner.py,
custom_operator.cc)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static import nn as snn


# ---------------------------------------------------------------------------
# control flow
# ---------------------------------------------------------------------------

def test_cond_eager_and_captured():
    x = paddle.to_tensor(np.float32(2.0))
    assert float(snn.cond(x > 1, lambda: x * 10, lambda: x - 1).numpy()) == 20.0

    @paddle.jit.to_static
    def f(a):
        return snn.cond(a.sum() > 0, lambda: a * 2, lambda: a - 100)

    pos = paddle.to_tensor(np.ones(3, np.float32))
    neg = paddle.to_tensor(-np.ones(3, np.float32))
    np.testing.assert_allclose(f(pos).numpy(), [2, 2, 2])
    np.testing.assert_allclose(f(neg).numpy(), [-101, -101, -101])


def test_while_loop_data_dependent_trip_count():
    """Collatz steps: the trip count depends on the VALUE inside one compiled
    program (the dy2static while capability)."""

    @paddle.jit.to_static
    def steps(n):
        i = paddle.to_tensor(np.int32(0))

        def cnd(n, i):
            return n > 1

        def body(n, i):
            n2 = snn.cond((n % 2) == 0, lambda: n // 2, lambda: 3 * n + 1)
            return n2, i + 1

        n, i = snn.while_loop(cnd, body, [n, i])
        return i

    assert int(steps(paddle.to_tensor(np.int32(6))).numpy()) == 8
    assert int(steps(paddle.to_tensor(np.int32(27))).numpy()) == 111


def test_while_loop_eager():
    i = paddle.to_tensor(np.int32(0))
    s = paddle.to_tensor(np.float32(0.0))
    i, s = snn.while_loop(lambda i, s: i < 5,
                          lambda i, s: (i + 1, s + float(i.numpy())), [i, s])
    assert int(i.numpy()) == 5 and float(s.numpy()) == 10.0


def test_case_and_switch_case():
    a = paddle.to_tensor(np.float32(3.0))
    out = snn.case([(a > 5, lambda: a * 0), (a > 1, lambda: a * 2)],
                   default=lambda: a)
    assert float(out.numpy()) == 6.0

    @paddle.jit.to_static
    def g(i, x):
        return snn.switch_case(i, {0: lambda: x, 1: lambda: x * 2},
                               default=lambda: x * 0)

    x = paddle.to_tensor(np.ones(2, np.float32))
    np.testing.assert_allclose(g(paddle.to_tensor(np.int32(1)), x).numpy(),
                               [2, 2])
    np.testing.assert_allclose(g(paddle.to_tensor(np.int32(9)), x).numpy(),
                               [0, 0])


# ---------------------------------------------------------------------------
# auto-tuner
# ---------------------------------------------------------------------------

def test_auto_tuner_candidates_pruned():
    from paddle_tpu.distributed.auto_tuner import generate_candidates
    from paddle_tpu.models.gpt import gpt_tiny
    cfg = gpt_tiny(64)  # heads=4, layers=2
    cands = generate_candidates(4, cfg)
    assert cands
    for c in cands:
        assert c.size == 4
        assert cfg.num_heads % c.mp == 0
        assert cfg.num_layers % c.pp == 0
        if c.pp > 1:
            assert c.micro_batches % c.pp == 0


@pytest.mark.slow      # timed trials compile one program per candidate (~84 s)
def test_auto_tuner_finds_working_config():
    import jax
    from paddle_tpu.distributed.auto_tuner import tune
    from paddle_tpu.models.gpt import gpt_tiny
    cfg = gpt_tiny(64)
    best, results = tune(cfg, devices=jax.devices()[:4], trial_steps=2,
                         seq=64)
    assert best.size == 4
    ok = [r for r in results if r.ok]
    assert ok and max(r.tokens_per_sec for r in ok) > 0
    # the returned best is the argmax
    assert best in [r.cfg for r in ok]


# ---------------------------------------------------------------------------
# custom ops
# ---------------------------------------------------------------------------

def test_register_custom_op_with_gradient():
    import jax.numpy as jnp
    from paddle_tpu.incubate import register_custom_op

    # custom op: y = x^3 with a deliberately scaled custom gradient 6x^2
    op = register_custom_op(
        "cube_scaled_grad",
        forward=lambda x: x ** 3,
        backward=lambda saved, g: (g * 6 * saved[0] ** 2,))
    x = paddle.to_tensor(np.array([2.0], np.float32))
    x.stop_gradient = False
    y = op(x)
    np.testing.assert_allclose(y.numpy(), [8.0])
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [24.0])  # custom rule, not 3x^2


def test_custom_op_from_c_kernel(tmp_path):
    from paddle_tpu.incubate import custom_op_from_c
    from paddle_tpu.io.shm_ring import available
    if not available():
        pytest.skip("no toolchain")
    from paddle_tpu.utils.cpp_extension import load
    src = tmp_path / "relu6c.cc"
    src.write_text(
        '#include <cstdint>\n'
        'extern "C" void relu6c(const float* in, float* out, int64_t n) {\n'
        '  for (int64_t i = 0; i < n; ++i) {\n'
        '    float v = in[i] < 0 ? 0 : in[i];\n'
        '    out[i] = v > 6 ? 6 : v;\n'
        '  }\n'
        '}\n')
    lib = load("relu6c_ext", [str(src)])
    op = custom_op_from_c(lib, "relu6c")
    x = paddle.to_tensor(np.array([-1.0, 3.0, 9.0], np.float32))
    np.testing.assert_allclose(op(x).numpy(), [0.0, 3.0, 6.0])
    # works inside a captured program too (pure_callback under jit)
    st = paddle.jit.to_static(lambda t: op(t) * 2)
    np.testing.assert_allclose(st(x).numpy(), [0.0, 6.0, 12.0])
