"""DataLoader/Dataset tests (reference: `test/legacy_test/test_dataloader_*`)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.io import (BatchSampler, DataLoader, Dataset, DistributedBatchSampler,
                           IterableDataset, TensorDataset)


class SquaresDataset(Dataset):
    def __init__(self, n=20):
        self.n = n

    def __getitem__(self, i):
        return np.float32(i), np.float32(i * i)

    def __len__(self):
        return self.n


def test_basic_batching():
    loader = DataLoader(SquaresDataset(), batch_size=4)
    batches = list(loader)
    assert len(batches) == 5
    x, y = batches[0]
    assert x.shape == [4]
    np.testing.assert_allclose(y.numpy(), [0, 1, 4, 9])


def test_shuffle_and_drop_last():
    loader = DataLoader(SquaresDataset(10), batch_size=3, shuffle=True, drop_last=True)
    batches = list(loader)
    assert len(batches) == 3
    seen = np.concatenate([b[0].numpy() for b in batches])
    assert len(set(seen.tolist())) == 9


def test_iterable_dataset():
    class Stream(IterableDataset):
        def __iter__(self):
            for i in range(7):
                yield np.float32(i)

    loader = DataLoader(Stream(), batch_size=2)
    batches = list(loader)
    assert len(batches) == 4
    assert batches[-1].shape == [1]


def test_worker_prefetch_path():
    loader = DataLoader(SquaresDataset(50), batch_size=5, num_workers=2)
    batches = list(loader)
    assert len(batches) == 10


def test_tensor_dataset():
    xs = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(6, 2))
    ys = paddle.to_tensor(np.arange(6, dtype=np.int64))
    ds = TensorDataset([xs, ys])
    x0, y0 = ds[2]
    np.testing.assert_allclose(x0.numpy(), [4, 5])


def test_distributed_batch_sampler_shards():
    ds = SquaresDataset(20)
    s0 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=0)
    s1 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=1)
    idx0 = [i for b in s0 for i in b]
    idx1 = [i for b in s1 for i in b]
    assert len(idx0) == len(idx1) == 10
    assert set(idx0).isdisjoint(set(idx1))
