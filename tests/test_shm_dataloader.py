"""Native C++ shm-ring + multiprocess DataLoader + cpp_extension JIT builder
(ref mmap_allocator/blocking_queue, io/reader.py multiprocess path,
utils/cpp_extension)."""
import multiprocessing as mp
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io.shm_ring import ShmRing, available

pytestmark = pytest.mark.skipif(not available(),
                                reason="g++/shm unavailable")


def test_ring_roundtrip_objects():
    ring = ShmRing(f"t_obj_{os.getpid()}", capacity=1 << 20)
    try:
        ring.put({"a": np.arange(5), "b": "x"})
        out = ring.get(timeout_ms=1000)
        np.testing.assert_array_equal(out["a"], np.arange(5))
        assert out["b"] == "x"
    finally:
        ring.free()


def test_ring_cross_process_order_and_wrap():
    ring = ShmRing(f"t_xp_{os.getpid()}", capacity=1 << 16)

    def producer(name):
        r = ShmRing(name, create=False)
        for i in range(40):
            r.push_bytes(bytes([i]) * 30000)  # forces wraparound + blocking
        r.close_producer()

    p = mp.get_context("fork").Process(target=producer, args=(ring.name,))
    p.start()
    n = 0
    try:
        while True:
            b = ring.pop_bytes(timeout_ms=10000)
            assert b is not None and len(b) == 30000 and b[0] == n
            n += 1
    except EOFError:
        pass
    p.join()
    ring.free()
    assert n == 40


def test_ring_timeout_and_oversize():
    ring = ShmRing(f"t_to_{os.getpid()}", capacity=1 << 12)
    try:
        assert ring.pop_bytes(timeout_ms=50) is None  # timeout, not hang
        with pytest.raises(ValueError):
            ring.push_bytes(b"x" * (1 << 13))
    finally:
        ring.free()


class _SquareDataset(paddle.io.Dataset):
    def __len__(self):
        return 37

    def __getitem__(self, i):
        return np.full((8,), i, np.float32), np.int64(i * i)


def test_multiprocess_dataloader_matches_sync():
    ds = _SquareDataset()
    sync = paddle.io.DataLoader(ds, batch_size=4, num_workers=0)
    mpdl = paddle.io.DataLoader(ds, batch_size=4, num_workers=2,
                                use_shared_memory=True)
    got_s = [(x.numpy(), y.numpy()) for x, y in sync]
    got_m = [(x.numpy(), y.numpy()) for x, y in mpdl]
    assert len(got_s) == len(got_m) == 10
    for (xs, ys), (xm, ym) in zip(got_s, got_m):
        np.testing.assert_array_equal(xs, xm)
        np.testing.assert_array_equal(ys, ym)


class _BadDataset(paddle.io.Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at 5")
        return np.zeros(2, np.float32)


def test_multiprocess_dataloader_worker_error_surfaces():
    dl = paddle.io.DataLoader(_BadDataset(), batch_size=2, num_workers=2,
                              use_shared_memory=True)
    with pytest.raises(RuntimeError, match="boom at 5"):
        for _ in dl:
            pass


def test_unpicklable_dataset_falls_back_to_threaded():
    class Local(paddle.io.Dataset):  # local class: not picklable for spawn
        def __len__(self):
            return 6

        def __getitem__(self, i):
            return np.full((2,), i, np.float32)

    dl = paddle.io.DataLoader(Local(), batch_size=2, num_workers=2,
                              use_shared_memory=True)
    got = [x.numpy() for x in dl]
    assert len(got) == 3 and got[2][1][0] == 5.0


def test_cpp_extension_load_builds_and_calls():
    import ctypes
    from paddle_tpu.utils.cpp_extension import load
    src = os.path.join(os.path.dirname(__file__), "_ext_src.cc")
    with open(src, "w") as f:
        f.write('extern "C" long triple(long x) { return 3 * x; }\n')
    try:
        lib = load("test_triple", [src])
        lib.triple.restype = ctypes.c_long
        lib.triple.argtypes = [ctypes.c_long]
        assert lib.triple(14) == 42
    finally:
        os.remove(src)
