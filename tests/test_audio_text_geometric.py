"""paddle.audio / paddle.text / paddle.geometric + new vision families
(ref python/paddle/{audio,text,geometric}/, vision/models/)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_mel_scale_roundtrip():
    AF = paddle.audio.functional
    for htk in (False, True):
        hz = AF.mel_to_hz(AF.hz_to_mel(440.0, htk), htk)
        np.testing.assert_allclose(hz, 440.0, rtol=1e-5)
        freqs = np.array([100.0, 1000.0, 4000.0], np.float32)
        back = AF.mel_to_hz(AF.hz_to_mel(paddle.to_tensor(freqs), htk), htk)
        np.testing.assert_allclose(back.numpy(), freqs, rtol=1e-4)


def test_fbank_matrix_properties():
    AF = paddle.audio.functional
    fb = AF.compute_fbank_matrix(16000, 512, n_mels=40).numpy()
    assert fb.shape == (40, 257)
    assert (fb >= 0).all() and fb.sum() > 0


def test_spectrogram_matches_manual():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 2048).astype(np.float32)
    spec = paddle.audio.features.Spectrogram(n_fft=256, hop_length=128)(
        paddle.to_tensor(x)).numpy()
    assert spec.shape[1] == 129  # n_fft//2 + 1
    assert (spec >= 0).all()
    # Parseval-flavored sanity: energy concentrated where signal is
    x2 = np.zeros((1, 2048), np.float32)
    spec0 = paddle.audio.features.Spectrogram(n_fft=256)(
        paddle.to_tensor(x2)).numpy()
    assert spec0.max() < 1e-10


def test_mfcc_pipeline_shapes():
    rng = np.random.RandomState(0)
    x = rng.randn(3, 4096).astype(np.float32)
    mfcc = paddle.audio.features.MFCC(sr=16000, n_mfcc=13, n_fft=512,
                                      n_mels=40)(paddle.to_tensor(x))
    assert mfcc.shape[0] == 3 and mfcc.shape[1] == 13
    assert np.isfinite(mfcc.numpy()).all()


def test_viterbi_decode_against_bruteforce():
    rng = np.random.RandomState(0)
    B, T, N = 2, 5, 3
    emis = rng.randn(B, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    lens = np.array([5, 3], np.int64)
    scores, paths = paddle.text.viterbi_decode(
        paddle.to_tensor(emis), paddle.to_tensor(trans),
        paddle.to_tensor(lens), include_bos_eos_tag=False)
    import itertools
    for b in range(B):
        L = lens[b]
        best, best_path = -1e30, None
        for p in itertools.product(range(N), repeat=int(L)):
            s = emis[b, 0, p[0]]
            for t in range(1, L):
                s += trans[p[t - 1], p[t]] + emis[b, t, p[t]]
            if s > best:
                best, best_path = s, p
        np.testing.assert_allclose(float(scores.numpy()[b]), best, rtol=1e-5)
        np.testing.assert_array_equal(paths.numpy()[b][:L], best_path)


def test_text_datasets_raise_clearly():
    with pytest.raises(RuntimeError, match="no network egress"):
        paddle.text.Imdb()


def test_geometric_message_passing():
    G = paddle.geometric
    x = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]], np.float32))
    src = paddle.to_tensor(np.array([0, 1, 2], np.int32))
    dst = paddle.to_tensor(np.array([1, 1, 0], np.int32))
    out = G.send_u_recv(x, src, dst, "sum")
    np.testing.assert_allclose(out.numpy(), [[5, 6], [4, 6], [0, 0]])
    e = paddle.to_tensor(np.full((3, 2), 10.0, np.float32))
    out2 = G.send_ue_recv(x, e, src, dst, "add", "max")
    np.testing.assert_allclose(out2.numpy(), [[15, 16], [13, 14], [0, 0]])
    msgs = G.send_uv(x, x, src, dst, "mul")
    np.testing.assert_allclose(msgs.numpy(), [[3, 8], [9, 16], [5, 12]])


def test_geometric_sampling_and_reindex():
    G = paddle.geometric
    # CSC: node 0 <- {1, 2}, node 1 <- {2}, node 2 <- {}
    row = paddle.to_tensor(np.array([1, 2, 2], np.int64))
    colptr = paddle.to_tensor(np.array([0, 2, 3, 3], np.int64))
    nodes = paddle.to_tensor(np.array([0, 1], np.int64))
    neigh, cnt = G.sample_neighbors(row, colptr, nodes, sample_size=-1)
    np.testing.assert_array_equal(cnt.numpy(), [2, 1])
    np.testing.assert_array_equal(neigh.numpy(), [1, 2, 2])
    s, d, out_nodes = G.reindex_graph(nodes, neigh, cnt)
    np.testing.assert_array_equal(out_nodes.numpy(), [0, 1, 2])
    np.testing.assert_array_equal(s.numpy(), [1, 2, 2])
    np.testing.assert_array_equal(d.numpy(), [0, 0, 1])


@pytest.mark.slow      # builds + forwards 13 model families (~80 s compile)
def test_vision_families_complete():
    from paddle_tpu.vision import models as M
    fams = ["ResNet", "VGG", "LeNet", "AlexNet", "MobileNetV1", "MobileNetV2",
            "MobileNetV3Large", "MobileNetV3Small", "SqueezeNet", "DenseNet",
            "GoogLeNet", "InceptionV3", "ShuffleNetV2"]
    for f in fams:
        assert hasattr(M, f), f
    # constructors + forward on tiny inputs for the new compact families
    x = paddle.to_tensor(np.random.RandomState(0).randn(1, 3, 64, 64)
                         .astype(np.float32))
    for make in (lambda: M.squeezenet1_1(num_classes=7),
                 lambda: M.mobilenet_v3_small(num_classes=7),
                 lambda: M.shufflenet_v2_x0_5(num_classes=7)):
        m = make()
        m.eval()
        assert list(m(x).shape) == [1, 7]
