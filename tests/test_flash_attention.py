"""Flash attention + loss chunking tests.

The Pallas kernels themselves only compile on real TPU (Mosaic); under the CPU
conftest these tests cover the XLA fallback path and the chunked-CE parity.  The
TPU-gated test mirrors what /tmp-drive scripts exercise on hardware.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.incubate.kernels.flash_attention import (
    attention_xla, flash_attention_fused, _on_tpu)
from paddle_tpu.models.gpt import GPTConfig, init_params, loss_fn


def test_fused_entry_fallback_matches_xla_on_cpu():
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (2, 128, 2, 64))
    k = jax.random.normal(ks[1], (2, 128, 2, 64))
    v = jax.random.normal(ks[2], (2, 128, 2, 64))
    out = flash_attention_fused(q, k, v, causal=True)
    ref = attention_xla(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_loss_chunk_parity():
    # chunked CE must match the unchunked loss exactly (same f32 math)
    config = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
                       max_seq_len=256)
    params = init_params(config, jax.random.key(0))
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, 512, (2, 256)), jnp.int32)
    lab = jnp.asarray(np.roll(np.asarray(tok), -1, 1), jnp.int32)
    lab = lab.at[:, -8:].set(-100)  # exercise ignore-index masking across chunks
    full = loss_fn(params, tok, lab, config, loss_chunk=None)
    chunked = loss_fn(params, tok, lab, config, loss_chunk=64)
    np.testing.assert_allclose(float(full), float(chunked), rtol=1e-5)
    # grads agree too
    gf = jax.grad(lambda p: loss_fn(p, tok, lab, config, loss_chunk=None))(params)
    gc = jax.grad(lambda p: loss_fn(p, tok, lab, config, loss_chunk=64))(params)
    for a, b in zip(jax.tree_util.tree_leaves(gf), jax.tree_util.tree_leaves(gc)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_remat_policy_matches_plain_loss():
    config = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
                       max_seq_len=256)
    params = init_params(config, jax.random.key(1))
    rng = np.random.RandomState(1)
    tok = jnp.asarray(rng.randint(0, 512, (2, 256)), jnp.int32)
    lab = jnp.asarray(np.roll(np.asarray(tok), -1, 1), jnp.int32)
    l0 = loss_fn(params, tok, lab, config, remat=False)
    l1 = loss_fn(params, tok, lab, config, remat=True)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-5)
    g0 = jax.grad(lambda p: loss_fn(p, tok, lab, config, remat=False))(params)
    g1 = jax.grad(lambda p: loss_fn(p, tok, lab, config, remat=True))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.skipif(not _on_tpu(), reason="Pallas kernels require TPU (Mosaic)")
def test_pallas_flash_fwd_bwd_vs_xla_on_tpu():
    from paddle_tpu.incubate.kernels.flash_attention import _flash_attention_core
    for causal in (True, False):
        ks = jax.random.split(jax.random.key(7), 4)
        q = jax.random.normal(ks[0], (2, 512, 4, 64), jnp.bfloat16)
        k = jax.random.normal(ks[1], (2, 512, 4, 64), jnp.bfloat16)
        v = jax.random.normal(ks[2], (2, 512, 4, 64), jnp.bfloat16)
        g = jax.random.normal(ks[3], (2, 512, 4, 64), jnp.bfloat16)
        scale = 1.0 / 8.0
        out_p, vjp_p = jax.vjp(lambda a, b, c: _flash_attention_core(a, b, c, causal, scale), q, k, v)
        out_x, vjp_x = jax.vjp(lambda a, b, c: attention_xla(a, b, c, None, causal, scale), q, k, v)
        np.testing.assert_allclose(np.asarray(out_p, np.float32),
                                   np.asarray(out_x, np.float32), atol=3e-2, rtol=3e-2)
        for a, b in zip(vjp_p(g), vjp_x(g)):
            a32, b32 = np.asarray(a, np.float32), np.asarray(b, np.float32)
            err = np.abs(a32 - b32).max() / max(np.abs(b32).max(), 1e-6)
            assert err < 6e-2
