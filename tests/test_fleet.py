"""Serving front door + dp engine fleet (router/frontend PR).

Covers the routing plane three ways:

- pure units: `rank_replicas` scoring over `ReplicaView` fakes (affinity
  vs sticky vs load ordering, victim-aware pre-filter, overloaded
  exclusion), no engines involved;
- fleet integration: sticky-session routing with the tier-probe override,
  shed path when every replica is unroutable, abort freeing KV pages,
  byte-exact fleet-vs-single-engine parity on a multi-turn session
  stream, executable adoption, and `create_predictor` fleet routing;
- HTTP: the front door on a real loopback socket — non-stream and SSE
  streaming round-trips, validation errors, rate-limit 429, the obs
  routes through the one door, and client-disconnect -> abort.
"""
from __future__ import annotations

import http.client
import json
import socket
import time

import numpy as np
import pytest

import jax

from paddle_tpu.inference.engine import LLMEngine
from paddle_tpu.inference.router import (EngineFleet, FleetHandle,
                                         FleetOverloaded, ReplicaView,
                                         rank_replicas)
from paddle_tpu.models import gpt as G

EKW = dict(num_slots=2, page_size=8, max_model_len=64, prefill_chunk=16,
           seed=0)


@pytest.fixture(scope="module")
def cfg():
    return G.gpt_tiny(64)


@pytest.fixture(scope="module")
def params(cfg):
    return G.init_params(cfg, jax.random.key(0))


# ---------------------------------------------------------------------------
# rank_replicas units (pure — no engines)
# ---------------------------------------------------------------------------

def _v(label, **kw):
    return ReplicaView(label=label, **kw)


def test_affinity_prefers_longest_cached_prefix():
    views = [_v("engine0", matched_tokens=8),
             _v("engine1", matched_tokens=24),
             _v("engine2", matched_tokens=0)]
    assert rank_replicas(views).label == "engine1"


def test_sticky_wins_ties_but_strictly_more_cache_overrides():
    # equal match: the session's last replica wins the tie
    tie = [_v("engine0", matched_tokens=16),
           _v("engine1", matched_tokens=16, sticky=True)]
    assert rank_replicas(tie).label == "engine1"
    # a replica whose cache/tier holds strictly MORE of the conversation
    # beats stickiness — after an eviction/respill the pages decide
    probe = [_v("engine0", matched_tokens=40),
             _v("engine1", matched_tokens=16, sticky=True)]
    assert rank_replicas(probe).label == "engine0"


def test_affinity_load_tiebreak_depth_then_throughput():
    views = [_v("engine0", depth=3, tokens_per_sec=50.0),
             _v("engine1", depth=1, tokens_per_sec=10.0)]
    assert rank_replicas(views).label == "engine1"
    views = [_v("engine0", depth=2, tokens_per_sec=50.0),
             _v("engine1", depth=2, tokens_per_sec=10.0)]
    assert rank_replicas(views).label == "engine0"


def test_overloaded_and_error_replicas_excluded():
    views = [_v("engine0", state="overloaded", matched_tokens=99),
             _v("engine1", state="error", matched_tokens=99),
             _v("engine2", matched_tokens=0)]
    assert rank_replicas(views).label == "engine2"
    views = [_v("engine0", state="overloaded"), _v("engine1", state="error")]
    assert rank_replicas(views) is None


def test_victim_aware_prefilter_for_low_priority():
    hot = _v("engine0", matched_tokens=30, pool_pressure=0.95)
    churny = _v("engine1", matched_tokens=30, preemptions_per_sec=2.0)
    calm = _v("engine2", matched_tokens=0, pool_pressure=0.1)
    # priority >= 0: cache affinity wins, pressure is not a veto
    assert rank_replicas([hot, churny, calm], priority=0).label == "engine0"
    # priority < 0: the preemption victims go to the calm replica
    assert rank_replicas([hot, churny, calm], priority=-1).label == "engine2"
    # ...unless nowhere is calm — then affinity ordering still applies
    assert rank_replicas([hot, churny], priority=-1).label == "engine0"


def test_least_loaded_and_policy_errors():
    views = [_v("engine0", depth=2), _v("engine1", depth=0)]
    assert rank_replicas(views, policy="least_loaded").label == "engine1"
    with pytest.raises(ValueError):
        rank_replicas(views, policy="round_robin")  # needs fleet state
    with pytest.raises(ValueError):
        rank_replicas(views, policy="nope")


def test_fleet_handle_roundtrip():
    h = FleetHandle(label="engine1", rid=7, session="s0")
    assert str(h) == "engine1/7"
    assert FleetHandle.parse("engine1/7") == FleetHandle("engine1", 7)


# ---------------------------------------------------------------------------
# fleet integration (real engines)
# ---------------------------------------------------------------------------

def _sessions(cfg, n=3, seed=7):
    rng = np.random.RandomState(seed)
    first = {f"s{i}": rng.randint(0, cfg.vocab_size, (10,)).astype(np.int32)
             for i in range(n)}
    chunk = {k: rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32)
             for k in first}
    return first, chunk


def _run_two_turns(fleet, first, chunk):
    outs = {}
    for k, p in first.items():
        outs[(k, 1)] = fleet.result(
            fleet.submit(p, session=k, max_new_tokens=5), timeout=120.0)
    for k, p in first.items():
        conv = np.concatenate([p, np.asarray(outs[(k, 1)].token_ids,
                                             np.int32), chunk[k]])
        outs[(k, 2)] = fleet.result(
            fleet.submit(conv, session=k, max_new_tokens=5), timeout=120.0)
    assert all(o is not None for o in outs.values())
    return outs


def test_fleet_parity_and_affinity_vs_round_robin(params, cfg):
    """Byte-exact parity single vs 2-replica (both routers) on the same
    session stream; affinity's returning turns hit the cache (finish-time
    registration included: cached >= the whole turn-1 conversation KV),
    round-robin's shifted assignment hits nothing; replicas adopt the
    leader's executables."""
    first, chunk = _sessions(cfg)

    def run(replicas, router):
        fleet = EngineFleet(params, cfg, replicas=replicas, router=router,
                            engine_kwargs=EKW)
        assert fleet.shared_executables()
        with fleet:
            outs = _run_two_turns(fleet, first, chunk)
            fleet.check_invariants()
        digest = {k: list(map(int, o.token_ids)) for k, o in outs.items()}
        cached = {k: int(o.cached_tokens) for k, o in outs.items()}
        return digest, cached

    d1, _ = run(1, "affinity")
    d2, c2 = run(2, "affinity")
    d3, c3 = run(2, "round_robin")
    assert d1 == d2 == d3
    for k in first:
        # sticky affinity: turn 2 reuses the ENTIRE turn-1 KV — prompt
        # pages plus the generated pages finish-time registration published
        # (kvlen = 10 prompt + 5 generated - 1; the final sampled token's
        # KV never lands, so 14 is full reuse, not a partial hit)
        assert c2[(k, 2)] == 14, c2
    # 3 sessions over 2 replicas: round-robin's turn-2 assignment shifts
    # off the turn-1 replica for every session — zero cache reuse
    assert all(c3[(k, 2)] == 0 for k in first), c3


def test_finish_time_registration_stops_reprefill(params, cfg):
    """Satellite: a returning session's last REPLY must not re-prefill —
    finish-time registration upgrades the prompt-time partial node to
    cover the generated pages (engine.cache.register_prefix upgrade mode),
    so turn-2 cached_tokens reaches the full turn-1 kvlen instead of
    stopping at the prompt pages."""
    eng = LLMEngine(params, cfg, **EKW)
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, cfg.vocab_size, (10,)).astype(np.int32)
    rid = eng.add_request(prompt, max_new_tokens=5)
    out = eng.result(rid)
    kvlen = prompt.size + len(out.token_ids) - 1
    probe = eng.probe_affinity(np.concatenate(
        [prompt, np.asarray(out.token_ids, np.int32)]))
    assert probe["cached_tokens"] == kvlen, probe
    conv = np.concatenate([prompt, np.asarray(out.token_ids, np.int32),
                           rng.randint(0, cfg.vocab_size,
                                       (4,)).astype(np.int32)])
    out2 = eng.result(eng.add_request(conv, max_new_tokens=4))
    # without finish-time registration this stopped at the prompt's pages
    # (page 8 + rolling-hash partial 2 = 10); with it, the reply rides too
    assert out2.cached_tokens == kvlen, out2.cached_tokens
    eng.cache.check_invariants()


def test_shed_when_all_replicas_overloaded(params, cfg):
    fleet = EngineFleet(params, cfg, replicas=2, engine_kwargs=EKW,
                        shed_retry_after_s=2.5)
    bad = {"state": "overloaded", "code": 2, "reasons": [], "signals": {},
           "burn_rates": {}}
    originals = {l: e.health for l, e in fleet.engines.items()}
    try:
        # one overloaded member: traffic still routes, to the healthy one
        fleet.engines["engine0"].health = lambda: bad
        assert fleet.select(np.arange(4, dtype=np.int32)) == "engine1"
        # every member overloaded: shed with the retry-after hint
        fleet.engines["engine1"].health = lambda: bad
        with pytest.raises(FleetOverloaded) as ei:
            fleet.submit(np.arange(4, dtype=np.int32), max_new_tokens=2)
        assert ei.value.retry_after_s == 2.5
        assert fleet.stats()["shed"] == 1
    finally:
        for l, h in originals.items():
            fleet.engines[l].health = h


def test_abort_frees_pages_and_invariants(params, cfg):
    fleet = EngineFleet(params, cfg, replicas=2, engine_kwargs=EKW)
    with fleet:
        h = fleet.submit(np.arange(20, dtype=np.int32) % cfg.vocab_size,
                         max_new_tokens=40)
        # let it get in flight, then abort mid-generation
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            p = fleet.progress(h)
            if p["finished"] or p["token_ids"]:
                break
            time.sleep(0.01)
        fleet.abort(h)
        out = fleet.result(h, timeout=60.0)
        assert out is not None and out.finish_reason == "abort"
        assert fleet.drain(timeout=60.0)
        fleet.check_invariants()
        eng = fleet.engines[h.label]
        assert eng.stats()["aborted_requests"] == 1
    # the aborted request released its slot: nothing live remains anywhere
    for e in fleet.engines.values():
        st = e.stats()
        assert st["running"] == 0 and st["prefilling"] == 0
        assert st["queued"] == 0


def test_create_predictor_routes_to_engine_and_fleet(params, cfg):
    import paddle_tpu.inference as pinf

    # duck-typed model config + params -> LLMEngine behind the ONE door
    eng = pinf.create_predictor(cfg, params=params, **EKW)
    assert isinstance(eng, LLMEngine)
    out = eng.result(eng.add_request(np.arange(6, dtype=np.int32),
                                     max_new_tokens=3))
    assert len(out.token_ids) == 3
    # Config.enable_llm_engine with replicas > 1 -> EngineFleet
    config = pinf.Config().enable_llm_engine(cfg, params, replicas=2,
                                             **EKW)
    fleet = pinf.create_predictor(config)
    assert isinstance(fleet, EngineFleet)
    assert fleet.shared_executables()
    with fleet:
        h = fleet.submit(np.arange(6, dtype=np.int32), max_new_tokens=3)
        fout = fleet.result(h, timeout=120.0)
    assert list(fout.token_ids) == list(out.token_ids)
    # a broken kind still fails loudly
    with pytest.raises(TypeError):
        pinf.create_predictor(object())


# ---------------------------------------------------------------------------
# the HTTP front door (real loopback socket)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def door(params, cfg):
    from paddle_tpu.inference.frontend import ServingFrontend
    fleet = EngineFleet(params, cfg, replicas=2, engine_kwargs=EKW).start()
    fe = ServingFrontend(fleet, rate_limit_rps=200.0,
                         rate_limit_burst=50).start()
    yield fe
    fe.close()
    fleet.stop()


def _post(door, path, payload, read=True):
    conn = http.client.HTTPConnection("127.0.0.1", door.port, timeout=60)
    conn.request("POST", path, json.dumps(payload),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    if not read:
        return conn, resp
    body = resp.read()
    conn.close()
    return resp, body


def test_http_completion_roundtrip(door, params, cfg):
    prompt = [int(x) for x in np.arange(8)]
    resp, body = _post(door, "/v1/completions",
                       {"prompt": prompt, "max_tokens": 4, "session": "h0"})
    assert resp.status == 200, body
    out = json.loads(body)
    assert out["object"] == "text_completion"
    toks = out["choices"][0]["token_ids"]
    assert len(toks) == 4
    assert out["usage"]["completion_tokens"] == 4
    # parity with a direct single-engine run of the same prompt
    eng = LLMEngine(params, cfg, **EKW)
    ref = eng.result(eng.add_request(np.asarray(prompt, np.int32),
                                     max_new_tokens=4))
    assert toks == [int(x) for x in ref.token_ids]


def test_http_chat_stream_sse(door):
    conn, resp = _post(door, "/v1/chat/completions",
                       {"messages": [{"role": "user",
                                      "content": [1, 2, 3, 4, 5]}],
                        "max_tokens": 4, "stream": True}, read=False)
    assert resp.status == 200
    assert resp.getheader("Content-Type").startswith("text/event-stream")
    raw = resp.read().decode("utf-8")
    conn.close()
    frames = [json.loads(x[len("data: "):])
              for x in raw.strip().split("\n\n")
              if x.startswith("data: ") and x != "data: [DONE]"]
    assert raw.strip().endswith("data: [DONE]")
    streamed = []
    for f in frames[:-1]:
        streamed += f["choices"][0]["delta"]["token_ids"]
    assert len(streamed) == 4
    final = frames[-1]["choices"][0]
    assert final["finish_reason"] in ("stop", "length")
    assert final["message"]["token_ids"] == streamed


def test_http_validation_and_rate_limit(door):
    from paddle_tpu.inference.frontend import ServingFrontend

    resp, body = _post(door, "/v1/completions", {"prompt": "not tokens"})
    assert resp.status == 400
    assert "token ids" in json.loads(body)["error"]
    resp, _ = _post(door, "/v1/completions", {})
    assert resp.status == 400
    resp, body = _post(door, "/v1/completions",
                       {"prompt": [1, 2], "priority_class": "warp-speed"})
    assert resp.status == 400
    assert "priority_class" in json.loads(body)["error"]
    # a second door on the SAME fleet with a near-zero refill: burst 1 means
    # exactly one admit per tenant, then deterministic 429 + Retry-After
    fe2 = ServingFrontend(door.fleet, rate_limit_rps=0.001,
                          rate_limit_burst=1.0).start()
    try:
        resp, _ = _post(fe2, "/v1/completions",
                        {"prompt": [1, 2, 3], "max_tokens": 2})
        assert resp.status == 200
        resp, body = _post(fe2, "/v1/completions",
                           {"prompt": [1, 2, 3], "max_tokens": 2})
        assert resp.status == 429, body
        assert int(resp.getheader("Retry-After")) >= 1
        assert "rate-limited" in json.loads(body)["error"]
        # ...per tenant: a different X-Tenant still has its own bucket
        conn = http.client.HTTPConnection("127.0.0.1", fe2.port, timeout=60)
        conn.request("POST", "/v1/completions",
                     json.dumps({"prompt": [1, 2, 3], "max_tokens": 2}),
                     {"Content-Type": "application/json",
                      "X-Tenant": "other"})
        r = conn.getresponse()
        r.read()
        assert r.status == 200
        conn.close()
    finally:
        fe2.close()


def test_http_obs_routes_one_door(door):
    for path, want in (("/healthz", 200), ("/stats", 200), ("/metrics", 200)):
        conn = http.client.HTTPConnection("127.0.0.1", door.port, timeout=30)
        conn.request("GET", path)
        r = conn.getresponse()
        body = r.read()
        conn.close()
        assert r.status == want, (path, r.status, body[:200])
    # fleet exposition through the door: per-engine series present
    conn = http.client.HTTPConnection("127.0.0.1", door.port, timeout=30)
    conn.request("GET", "/metrics")
    text = conn.getresponse().read().decode("utf-8")
    conn.close()
    assert 'engine="engine0"' in text and 'engine="engine1"' in text
    assert "llm_fleet_" in text
    # unknown route: 404 advertising BOTH planes
    conn = http.client.HTTPConnection("127.0.0.1", door.port, timeout=30)
    conn.request("GET", "/nope")
    r = conn.getresponse()
    routes = json.loads(r.read())["routes"]
    conn.close()
    assert r.status == 404
    assert "/metrics" in routes and "POST /v1/completions" in routes


def test_http_disconnect_aborts_and_frees_pages(door):
    """A dropped client connection must abort the in-flight request so its
    KV pages free — dead streams cannot pin pool capacity."""
    fleet = door.fleet
    before = {l: e.stats()["aborted_requests"]
              for l, e in fleet.engines.items()}
    payload = json.dumps({"prompt": [9, 8, 7, 6, 5, 4, 3, 2],
                          "max_tokens": 48, "stream": True}).encode("utf-8")
    # raw socket: http.client hands Connection:close sockets to the
    # response object, so a clean shutdown needs the fd directly
    sock = socket.create_connection(("127.0.0.1", door.port), timeout=60)
    sock.sendall(b"POST /v1/completions HTTP/1.1\r\n"
                 b"Host: door\r\nContent-Type: application/json\r\n"
                 b"Content-Length: " + str(len(payload)).encode() +
                 b"\r\n\r\n" + payload)
    first = sock.recv(64)
    assert first.startswith(b"HTTP/1.1 200"), first
    # hard client hangup mid-stream
    sock.shutdown(socket.SHUT_RDWR)
    sock.close()
    deadline = time.monotonic() + 60.0
    aborted = False
    while time.monotonic() < deadline and not aborted:
        aborted = any(e.stats()["aborted_requests"] > before[l]
                      for l, e in fleet.engines.items())
        time.sleep(0.05)
    assert aborted, "disconnect never aborted the in-flight request"
    assert fleet.drain(timeout=60.0)
    fleet.check_invariants()
    for eng in fleet.engines.values():
        st = eng.stats()
        assert st["running"] == 0 and st["prefilling"] == 0
