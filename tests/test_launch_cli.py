"""Launch CLI tests (reference: `test/legacy_test/test_launch_coverage.py` pattern —
spawn local trainers with injected cluster env)."""
import os
import subprocess
import sys


def test_launch_sets_cluster_env(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(
        "import os\n"
        "print('RANK=' + os.environ['PADDLE_TRAINER_ID'],"
        " 'WORLD=' + os.environ['PADDLE_TRAINERS_NUM'])\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) \
        + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "log"), str(script)],
        env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    logs = sorted((tmp_path / "log").glob("workerlog.*"))
    assert len(logs) == 2
    contents = "".join(p.read_text() for p in logs)
    assert "RANK=0 WORLD=2" in contents
    assert "RANK=1 WORLD=2" in contents


def test_launch_propagates_failure(tmp_path):
    script = tmp_path / "fail.py"
    script.write_text("import sys; sys.exit(7)\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) \
        + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--log_dir", str(tmp_path / "log"), str(script)],
        env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 7
