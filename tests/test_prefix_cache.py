"""Prefix-cached, chunk-scheduled serving: copy-on-write page sharing
(ref vLLM, Kwon et al. SOSP 2023) + Sarathi-style chunked prefill (Agrawal et
al. OSDI 2024) in the continuous-batching engine.

Covers the PR-2 acceptance bars: refcount/COW/LRU edge cases in
`PagedKVCache`, chunked-prefill vs one-shot logit parity, the q_offset lane
of the paged prefill attention kernel vs its XLA oracle, engine-level token
parity of prefix-cached / chunk-prefilled generation against `generate`,
`LLMEngine.abort`, and the CPU-smoke bench bound (hit rate > 0, prefilled
tokens drop vs the no-cache baseline, <= 2 prefill executables chunked).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import gpt as G
from paddle_tpu.inference.cache import PagedKVCache
from paddle_tpu.inference.engine import LLMEngine
from paddle_tpu.incubate.kernels.paged_attention import (
    paged_prefill_attention_pallas, paged_prefill_attention_xla)


PRESETS = [G.gpt_tiny, G.llama_tiny]
IDS = ["gpt", "llama"]


# ---------------------------------------------------------------------------
# PagedKVCache: refcounts, prefix index, COW, LRU eviction (pure host)
# ---------------------------------------------------------------------------

def test_cache_shared_page_freed_only_at_refcount_zero():
    mgr = PagedKVCache(num_pages=16, page_size=4, num_slots=4,
                       max_pages_per_slot=8)
    tok = np.arange(10, dtype=np.int32)         # 2 full pages + 2-token tail
    row0, m0, cow0 = mgr.allocate_prefixed(0, 12, tok)
    assert m0 == 0 and cow0 is None             # cold cache
    mgr.register_prefix(0, tok, 10)
    row1, m1, cow1 = mgr.allocate_prefixed(1, 12, tok)
    # page-aligned match capped below len(tokens): 2 full pages; the 2-token
    # partial cannot match (only j <= lp - base - 1 = 1 is probed, and the
    # rolling-hash partial index only matches tails >= _MIN_PARTIAL = 2 —
    # a 1-token hit would cost a COW copy to save one prefill token)
    assert m1 == 8 and cow1 is None
    np.testing.assert_array_equal(row1[:2], row0[:2])   # physically shared
    assert row1[2] != row0[2]
    assert mgr._ref[row0[0]] == 2
    free_before = mgr.num_free_pages
    mgr.release(0)
    # shared pages survive slot 0's retirement; only its private page parks
    assert mgr._ref[row1[0]] == 1
    assert mgr.num_free_pages == free_before    # page 2 registered -> LRU
    assert mgr.num_evictable_pages == 1
    mgr.release(1)
    assert mgr.pages_in_use() == 0
    # slot 0's registered chain (2 full + 1 partial) is evictable; slot 1's
    # private reservation-tail page was never registered -> straight to free
    assert mgr.num_evictable_pages == 3


def test_cache_partial_page_copy_on_write_match():
    mgr = PagedKVCache(num_pages=16, page_size=4, num_slots=4,
                       max_pages_per_slot=8)
    tok = np.arange(10, dtype=np.int32)
    row0, _, _ = mgr.allocate_prefixed(0, 12, tok)
    mgr.register_prefix(0, tok, 10)
    ext = np.concatenate([tok, np.asarray([99, 98, 97], np.int32)])  # 13 toks
    row1, m1, cow1 = mgr.allocate_prefixed(1, 16, ext)
    # 2 full pages shared + the 2-token partial page matched via COW
    assert m1 == 10
    assert cow1 is not None
    src, dst = cow1
    assert src == row0[2] and dst == row1[2]    # copy into slot 1's own page
    assert mgr._ref[src] == 1                   # COW does NOT ref the source
    assert mgr._ref[dst] == 1
    # divergent partial content does not match
    div = np.concatenate([tok[:8], np.asarray([7, 7, 7], np.int32)])
    row2, m2, cow2 = mgr.allocate_prefixed(2, 12, div)
    assert m2 == 8 and cow2 is None


def test_cache_lru_eviction_under_pressure():
    mgr = PagedKVCache(num_pages=8, page_size=4, num_slots=2,
                       max_pages_per_slot=8)          # 7 real pages
    a = np.arange(8, dtype=np.int32)
    b = np.arange(100, 108, dtype=np.int32)
    for slot, tok in ((0, a), (1, b)):
        mgr.allocate_prefixed(slot, 12, tok)          # 3 pages each
        mgr.register_prefix(slot, tok, 8)
        mgr.release(slot)
    # each slot frees its unregistered reservation-tail page; the 2 full
    # prompt pages per chain park in the LRU
    assert mgr.num_free_pages == 3 and mgr.num_evictable_pages == 4
    # 6 fresh pages only fit by evicting cached prefixes, oldest (a) first
    c = np.arange(200, 224, dtype=np.int32)
    row, m, _ = mgr.allocate_prefixed(0, 24, c)
    assert m == 0 and mgr.prefix_evictions == 3
    # chain a was evicted: no match for it anymore
    mgr.release(0)
    _, m2, _ = mgr.allocate_prefixed(0, 12, a)
    assert m2 == 0
    mgr.release(0)


def test_cache_match_revives_evictable_page():
    mgr = PagedKVCache(num_pages=8, page_size=4, num_slots=2,
                       max_pages_per_slot=8)
    tok = np.arange(8, dtype=np.int32)
    mgr.allocate_prefixed(0, 8, tok)
    mgr.register_prefix(0, tok, 8)
    mgr.release(0)
    assert mgr.num_evictable_pages == 2
    ext = np.concatenate([tok, np.asarray([5], np.int32)])
    row, m, cow = mgr.allocate_prefixed(1, 12, ext)
    assert m == 8 and cow is None
    assert mgr.num_evictable_pages == 0          # revived out of the LRU
    assert mgr._ref[row[0]] == 1


# ---------------------------------------------------------------------------
# chunked prefill numerics: q_offset kernel lane + logit parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kvh", [2, 1], ids=["gqa", "mqa"])
def test_paged_prefill_attention_pallas_matches_xla_oracle(kvh):
    """The Pallas chunked-prefill kernel (interpret mode on CPU) agrees with
    the gather oracle, including the causal-at-q_offset mask, GQA/MQA
    grouping, and padded chunk rows (compared only where valid)."""
    rng = np.random.RandomState(0)
    B, T, H, hd, page, P, mp = 2, 8, 4, 64, 8, 9, 4
    q = jnp.asarray(rng.randn(B, T, H, hd), jnp.float32)
    k = jnp.asarray(rng.randn(P, page, kvh, hd), jnp.float32)
    v = jnp.asarray(rng.randn(P, page, kvh, hd), jnp.float32)
    tbl = np.zeros((B, mp), np.int32)
    tbl[0, :3] = [1, 2, 3]
    tbl[1, :4] = [4, 5, 6, 7]
    qoff = jnp.asarray([10, 17], jnp.int32)
    valid = jnp.asarray([8, 5], jnp.int32)
    ref = paged_prefill_attention_xla(q, k, v, jnp.asarray(tbl), qoff, valid)
    got = paged_prefill_attention_pallas(q, k, v, jnp.asarray(tbl), qoff,
                                         valid, interpret=True)
    for b, n in enumerate(np.asarray(valid)):
        np.testing.assert_allclose(np.asarray(got)[b, :n],
                                   np.asarray(ref)[b, :n], atol=2e-5)


@pytest.mark.parametrize("preset", PRESETS, ids=IDS)
def test_chunked_prefill_matches_one_shot_logits(preset):
    """prefill_chunk_paged chunks (q_offset 0, 6, 12) reproduce the one-shot
    dense-forward logits through the page-table indirection, and decode
    continues correctly from the chunk-written pages."""
    cfg = preset(64)
    params = G.init_params(cfg, jax.random.key(0))
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (1, 15)), jnp.int32)
    dense = G.forward(params, toks, cfg)
    page, Tp, C = 4, 13, 6
    pool = G.init_paged_cache(cfg, num_pages=10, page_size=page)
    table = np.zeros((1, 6), np.int32)
    table[0, :5] = [3, 1, 4, 2, 5]              # deliberately non-contiguous
    tbl = jnp.asarray(table)
    filled = 0
    while filled < Tp:
        n = min(C, Tp - filled)
        ids = np.zeros((1, C), np.int32)
        ids[0, :n] = np.asarray(toks[0, filled:filled + n])
        logits, pool = G.prefill_chunk_paged(
            params, jnp.asarray(ids), cfg, pool, tbl,
            jnp.asarray([filled], jnp.int32), jnp.asarray([n], jnp.int32))
        filled += n
    np.testing.assert_allclose(np.asarray(logits),
                               np.asarray(dense[:, Tp - 1]),
                               atol=2e-4, rtol=2e-4)
    for pos in range(Tp, 15):
        logits, pool = G.decode_step_paged(
            params, toks[:, pos], pool, tbl, jnp.asarray([pos], jnp.int32),
            cfg)
        if pos < 14:
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(dense[:, pos]),
                                       atol=2e-4, rtol=2e-4)


# ---------------------------------------------------------------------------
# engine-level parity: prefix cache + chunked prefill vs generate()
# ---------------------------------------------------------------------------

def test_engine_prefix_cached_matches_uncached_generation():
    """Greedy token parity with `generate` while the scheduler shares pages:
    B extends A (full-page share + partial-page COW off a live donor), C
    repeats A (full-page share only).  Every cached request reports its
    cached_tokens and the pool fully recycles."""
    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(0))
    rng = np.random.RandomState(3)
    base = rng.randint(0, cfg.vocab_size, (21,)).astype(np.int32)
    ext = np.concatenate([base,
                          rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)])
    eng = LLMEngine(params, cfg, num_slots=3, page_size=8, max_model_len=64)
    rids = [eng.add_request(p, max_new_tokens=5) for p in (base, ext,
                                                           base.copy())]
    outs = eng.run()
    for rid, p in zip(rids, (base, ext, base)):
        ref = G.generate(params, jnp.asarray(p)[None], cfg, max_new_tokens=5)
        np.testing.assert_array_equal(outs[rid].tokens, np.asarray(ref[0]))
    # base: 21 = 2 full pages + 5-token partial; ext COWs the partial
    assert outs[rids[0]].cached_tokens == 0
    assert outs[rids[1]].cached_tokens == 21
    # C's partial tail hits the rolling-hash index at j = lp - 16 - 1 = 4
    # (a prefix of the 5-token partial node; the PR-2 exact-content index
    # stopped at the 2 full pages = 16 here)
    assert outs[rids[2]].cached_tokens == 20
    st = eng.stats()
    assert st["cow_page_copies"] == 2   # B's partial COW + C's rolling-hash hit
    assert st["prefix_hit_requests"] == 2
    assert st["pages_in_use"] == 0
    assert all(outs[r].ttft_s is not None and outs[r].ttft_s > 0 for r in rids)


def test_engine_chunked_prefill_matches_generate():
    """Chunked mode (8-token chunks, prefix cache off to isolate chunking):
    mixed-length prompts — including one long enough to interleave its chunks
    with other slots' decode steps — are token-identical to `generate`, with
    at most 2 prefill executables (acceptance bar; this engine needs 1)."""
    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(0))
    eng = LLMEngine(params, cfg, num_slots=3, page_size=8, max_model_len=64,
                    prefill_chunk=8, prefix_cache=False)
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (30, 5, 17, 3, 9)]
    rids = [eng.add_request(p, max_new_tokens=6) for p in prompts]
    outs = eng.run()
    for rid, p in zip(rids, prompts):
        ref = G.generate(params, jnp.asarray(p)[None], cfg, max_new_tokens=6)
        np.testing.assert_array_equal(outs[rid].tokens, np.asarray(ref[0]))
    st = eng.stats()
    assert st["decode_executables"] == 1
    assert st["prefill_executables"] <= 2
    assert st["prefill_chunks"] == sum(-(-p.size // 8) for p in prompts)
    assert st["pages_in_use"] == 0


@pytest.mark.slow
def test_engine_chunked_plus_prefix_parity():
    """Both tentpole features together: chunked prefill over a prefix-cached
    tail (q_offset starts mid-page after a COW) stays token-identical."""
    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(0))
    rng = np.random.RandomState(5)
    base = rng.randint(0, cfg.vocab_size, (21,)).astype(np.int32)
    ext = np.concatenate([base, rng.randint(0, cfg.vocab_size,
                                            (20,)).astype(np.int32)])
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    prefill_chunk=8)
    ra = eng.add_request(base, max_new_tokens=4)
    eng.run()                       # donor completes, registers its pages
    rb = eng.add_request(ext, max_new_tokens=4)
    outs = eng.run()
    for rid, p in ((ra, base), (rb, ext)):
        ref = G.generate(params, jnp.asarray(p)[None], cfg, max_new_tokens=4)
        np.testing.assert_array_equal(outs[rid].tokens, np.asarray(ref[0]))
    assert outs[rb].cached_tokens == 21         # 16 shared + 5 COW
    st = eng.stats()
    assert st["cow_page_copies"] == 1
    assert st["prefill_executables"] <= 2


def test_engine_abort_frees_pages_immediately():
    """abort() cancels queued, mid-prefill and decoding requests, derefs
    their pages at once, and the slot serves the next request correctly."""
    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(0))
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (30, 17, 5)]
    eng = LLMEngine(params, cfg, num_slots=1, page_size=8, max_model_len=64,
                    num_pages=12, prefill_chunk=8, prefix_cache=False)
    r1 = eng.add_request(prompts[0], max_new_tokens=8)
    r2 = eng.add_request(prompts[1], max_new_tokens=8)
    eng.step()                                  # r1 mid-prefill, r2 queued
    assert eng.cache.pages_in_use() > 0
    assert eng.abort(r1) and eng.abort(r2)
    assert not eng.abort(999)                   # unknown id
    assert eng.cache.pages_in_use() == 0 and not eng.has_work
    assert eng._outputs[r1].finish_reason == "abort"
    assert eng._outputs[r2].finish_reason == "abort"
    # aborting a DECODING request frees mid-generation
    r3 = eng.add_request(prompts[0], max_new_tokens=8)
    while not eng._running:
        eng.step()
    eng.step()
    assert eng.abort(r3)
    assert eng.cache.pages_in_use() == 0
    assert len(eng._outputs[r3].token_ids) >= 1  # partial progress reported
    # the freed slot still serves correctly
    r4 = eng.add_request(prompts[2], max_new_tokens=4)
    out = eng.run()[r4]
    ref = G.generate(params, jnp.asarray(prompts[2])[None], cfg,
                     max_new_tokens=4)
    np.testing.assert_array_equal(out.tokens, np.asarray(ref[0]))
    assert not eng.abort(r4)                    # already finished


# ---------------------------------------------------------------------------
# CI wiring: deterministic CPU smoke with a shared prefix
# ---------------------------------------------------------------------------

def test_bench_serve_shared_prefix_cpu_smoke():
    """Acceptance bar: with --shared-prefix-frac 0.5 on the CPU-smoke config,
    hit rate > 0 and prefilled tokens DROP vs the no-cache baseline on the
    same workload, within <= 2 prefill executables (chunked) and <= 4
    compiled programs total."""
    from bench_serve import run_serve_bench
    kw = dict(num_requests=10, num_slots=2, page_size=8, max_model_len=64,
              max_new_tokens=4, prefill_chunk=16, shared_prefix_frac=0.5,
              seed=11)
    stats = run_serve_bench(**kw, prefix_cache=True)
    base = run_serve_bench(**kw, prefix_cache=False)
    assert stats["requests"] == 10
    assert stats["prefix_hit_rate"] > 0
    assert stats["prefix_cached_tokens"] > 0
    # identical workload (same seed): the cache strictly reduces prefill work
    assert stats["prefilled_tokens"] < base["prefilled_tokens"]
    assert base["prefix_hit_rate"] == 0
    assert stats["prefill_executables"] <= 2
    assert (stats["decode_executables"] + stats["prefill_executables"] +
            stats["copy_executables"]) <= 4
    assert stats["ttft_p99_ms"] >= stats["ttft_p50_ms"] > 0
