"""Breadth additions: in-place variants + new ops + new losses + segment ops +
distribution additions (ref tensor_method_func list, nn/functional/loss.py,
incubate, distribution).  Ops route through the OpTest harness where they are
differentiable (dual-mode + numeric-grad parity)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate as incubate
import paddle_tpu.nn.functional as F

from op_test import check_grad, check_output


# ---------------------------------------------------------------------------
# in-place variants + version counter
# ---------------------------------------------------------------------------

def test_inplace_value_and_identity():
    t = paddle.to_tensor(np.array([1.0, 4.0], np.float32))
    r = t.sqrt_()
    assert r is t
    np.testing.assert_allclose(t.numpy(), [1.0, 2.0])
    paddle.exp_(t)
    np.testing.assert_allclose(t.numpy(), np.exp([1.0, 2.0]), rtol=1e-6)


def test_inplace_grad_flows():
    p = paddle.to_tensor(np.array([2.0], np.float32))
    p.stop_gradient = False
    q = p * 3
    q.exp_()
    q.backward()
    np.testing.assert_allclose(p.grad.numpy(), 3 * np.exp(6.0), rtol=1e-5)


def test_inplace_stale_read_raises():
    p = paddle.to_tensor(np.array([2.0], np.float32))
    p.stop_gradient = False
    q = p * 3
    r = q.sin()
    q.exp_()  # r's recorded input modified in place
    with pytest.raises(RuntimeError, match="inplace"):
        r.backward()


def test_inplace_logic_and_clip():
    t = paddle.to_tensor(np.array([0.5, 3.0], np.float32))
    t.clip_(0.0, 1.0)
    np.testing.assert_allclose(t.numpy(), [0.5, 1.0])
    a = paddle.to_tensor(np.array([1.0, 5.0], np.float32))
    a.greater_than_(paddle.to_tensor(np.array([2.0, 2.0], np.float32)))
    np.testing.assert_array_equal(a.numpy(), [False, True])


# ---------------------------------------------------------------------------
# new math / manipulation / linalg ops
# ---------------------------------------------------------------------------

def test_new_math_ops_against_numpy():
    rng = np.random.RandomState(0)
    x = rng.rand(3, 4).astype(np.float32) * 0.8 + 0.1
    check_output(paddle.logit, lambda a: np.log(a / (1 - a)), [x])
    check_grad(paddle.logit, [x])
    y = rng.randn(3, 5).astype(np.float32)
    check_output(lambda t: paddle.trapezoid(t, dx=0.5),
                 lambda a: np.trapezoid(a, dx=0.5, axis=-1)
                 if hasattr(np, "trapezoid") else np.trapz(a, dx=0.5, axis=-1), [y])
    ct = paddle.cumulative_trapezoid(paddle.to_tensor(y), dx=0.5)
    assert ct.shape == [3, 4]
    np.testing.assert_allclose(ct.numpy()[:, -1],
                               (np.trapezoid if hasattr(np, "trapezoid")
                                else np.trapz)(y, dx=0.5, axis=-1), rtol=1e-5)


def test_frexp_vander_addn():
    x = np.array([0.0, 4.0, -3.5, 0.1], np.float32)
    m, e = paddle.frexp(paddle.to_tensor(x))
    nm, ne = np.frexp(x)
    np.testing.assert_allclose(m.numpy(), nm, rtol=1e-6)
    np.testing.assert_allclose(e.numpy(), ne)
    v = np.array([1.0, 2.0, 3.0], np.float32)
    np.testing.assert_allclose(paddle.vander(paddle.to_tensor(v)).numpy(),
                               np.vander(v), rtol=1e-5)
    np.testing.assert_allclose(
        paddle.vander(paddle.to_tensor(v), n=2, increasing=True).numpy(),
        np.vander(v, 2, increasing=True), rtol=1e-6)
    ts = [paddle.to_tensor(np.full((2, 2), float(i), np.float32)) for i in range(3)]
    np.testing.assert_allclose(paddle.add_n(ts).numpy(), np.full((2, 2), 3.0))


def test_renorm():
    x = np.array([[3.0, 4.0], [0.3, 0.4]], np.float32)  # row norms 5, 0.5
    out = paddle.renorm(paddle.to_tensor(x), p=2.0, axis=0, max_norm=1.0).numpy()
    np.testing.assert_allclose(np.linalg.norm(out[0]), 1.0, rtol=1e-4)
    np.testing.assert_allclose(out[1], x[1], rtol=1e-5)  # under the cap: untouched


def test_unflatten_unfold_vsplit_reverse():
    x = np.arange(24, dtype=np.float32).reshape(2, 12)
    u = paddle.unflatten(paddle.to_tensor(x), 1, [3, 4])
    np.testing.assert_allclose(u.numpy(), x.reshape(2, 3, 4))
    w = paddle.unfold(paddle.to_tensor(np.arange(8, dtype=np.float32)), 0, 4, 2)
    np.testing.assert_allclose(w.numpy(), [[0, 1, 2, 3], [2, 3, 4, 5], [4, 5, 6, 7]])
    parts = paddle.vsplit(paddle.to_tensor(x.reshape(4, 6)), 2)
    assert len(parts) == 2 and parts[0].shape == [2, 6]
    r = paddle.reverse(paddle.to_tensor(x), axis=1)
    np.testing.assert_allclose(r.numpy(), x[:, ::-1])


def test_tensordot_and_lu_unpack():
    rng = np.random.RandomState(0)
    a = rng.randn(3, 4, 5).astype(np.float32)
    b = rng.randn(4, 5, 6).astype(np.float32)
    out = paddle.tensordot(paddle.to_tensor(a), paddle.to_tensor(b), axes=2)
    np.testing.assert_allclose(out.numpy(), np.tensordot(a, b, axes=2),
                               rtol=1e-3, atol=1e-3)
    m = rng.randn(4, 4).astype(np.float32)
    lu, piv = paddle.linalg.lu(paddle.to_tensor(m))
    P, L, U = paddle.linalg.lu_unpack(lu, piv)
    np.testing.assert_allclose(P.numpy() @ L.numpy() @ U.numpy(), m,
                               atol=1e-4, rtol=1e-4)


def test_pca_lowrank():
    rng = np.random.RandomState(0)
    x = rng.randn(20, 5).astype(np.float32)
    U, S, V = paddle.linalg.pca_lowrank(paddle.to_tensor(x), q=3)
    assert U.shape == [20, 3] and S.shape == [3] and V.shape == [5, 3]
    # reconstruction with top-3 components approximates the centered matrix
    xc = x - x.mean(0)
    rec = U.numpy() @ np.diag(S.numpy()) @ V.numpy().T
    full_err = np.linalg.norm(xc - rec)
    assert full_err < np.linalg.norm(xc)


# ---------------------------------------------------------------------------
# new losses
# ---------------------------------------------------------------------------

def test_gaussian_nll_loss():
    rng = np.random.RandomState(0)
    mu = rng.randn(6).astype(np.float32)
    y = rng.randn(6).astype(np.float32)
    var = (rng.rand(6).astype(np.float32) + 0.5)
    got = F.gaussian_nll_loss(paddle.to_tensor(mu), paddle.to_tensor(y),
                              paddle.to_tensor(var)).numpy()
    exp = np.mean(0.5 * (np.log(var) + (y - mu) ** 2 / var))
    np.testing.assert_allclose(got, exp, rtol=1e-5)


def test_multi_margin_loss():
    x = np.array([[0.1, 0.2, 0.7], [0.4, 0.4, 0.2]], np.float32)
    y = np.array([2, 0], np.int64)
    got = F.multi_margin_loss(paddle.to_tensor(x), paddle.to_tensor(y)).numpy()
    exp = []
    for i in range(2):
        m = np.maximum(0, 1.0 - x[i, y[i]] + x[i])
        m[y[i]] = 0
        exp.append(m.sum() / 3)
    np.testing.assert_allclose(got, np.mean(exp), rtol=1e-5)


def test_triplet_margin_with_distance_loss():
    rng = np.random.RandomState(0)
    a, p, n = (rng.randn(4, 8).astype(np.float32) for _ in range(3))
    got = F.triplet_margin_with_distance_loss(
        paddle.to_tensor(a), paddle.to_tensor(p), paddle.to_tensor(n)).numpy()
    dp = np.sqrt(((a - p) ** 2).sum(-1) + 1e-12)
    dn = np.sqrt(((a - n) ** 2).sum(-1) + 1e-12)
    np.testing.assert_allclose(got, np.mean(np.maximum(dp - dn + 1.0, 0)),
                               rtol=1e-4)


def test_margin_cross_entropy_reduces_to_ce():
    rng = np.random.RandomState(0)
    # cosine logits in [-1, 1]
    x = np.tanh(rng.randn(4, 6).astype(np.float32))
    y = rng.randint(0, 6, (4,)).astype(np.int64)
    # m1=1, m2=0, m3=0 => plain scaled softmax CE
    got = F.margin_cross_entropy(paddle.to_tensor(x), paddle.to_tensor(y),
                                 margin1=1.0, margin2=0.0, margin3=0.0,
                                 scale=10.0).numpy()
    z = x * 10.0
    lse = np.log(np.exp(z).sum(-1))
    exp = np.mean(lse - z[np.arange(4), y])
    np.testing.assert_allclose(got, exp, rtol=2e-3)


def test_rnnt_loss_against_bruteforce():
    """Tiny lattice: compare vs exhaustive path enumeration."""
    rng = np.random.RandomState(0)
    B, T, U, V = 1, 3, 2, 4
    logits = rng.randn(B, T, U + 1, V).astype(np.float32)
    label = np.array([[1, 2]], np.int64)
    got = F.rnnt_loss(paddle.to_tensor(logits), paddle.to_tensor(label),
                      paddle.to_tensor(np.array([T], np.int64)),
                      paddle.to_tensor(np.array([U], np.int64)),
                      blank=0, reduction="none").numpy()

    # brute force over all monotone paths
    lp = logits[0] - np.log(np.exp(logits[0]).sum(-1, keepdims=True))
    import itertools
    total = -np.inf
    # path = sequence of T blanks + U emits interleaved; enumerate emit positions
    for emit_t in itertools.product(range(T), repeat=U):
        if not all(emit_t[i] <= emit_t[i + 1] for i in range(U - 1)):
            continue
        s = 0.0
        u = 0
        for t in range(T):
            while u < U and emit_t[u] == t:
                s += lp[t, u, label[0, u]]
                u += 1
            s += lp[t, u, 0]  # blank advances t (final blank at t, u)
        total = np.logaddexp(total, s)
    np.testing.assert_allclose(got[0], -total, rtol=1e-4)


def test_hsigmoid_loss_learns():
    import paddle_tpu.nn as nn
    paddle.framework.random.seed(0)
    layer = nn.HSigmoidLoss(8, 8)
    opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=layer.parameters())
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 8, (16,)).astype(np.int64)
    losses = []
    for _ in range(10):
        loss = layer(paddle.to_tensor(x), paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_class_center_sample():
    y = np.array([3, 3, 5, 9], np.int64)
    remapped, sampled = F.class_center_sample(paddle.to_tensor(y), 20, 6)
    s = sampled.numpy()
    assert set([3, 5, 9]).issubset(set(s.tolist())) and len(s) == 6
    r = remapped.numpy()
    np.testing.assert_array_equal(s[r], y)


# ---------------------------------------------------------------------------
# segment / graph ops, unpool, decode, autograd, distribution
# ---------------------------------------------------------------------------

def test_segment_ops():
    x = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]], np.float32))
    ids = paddle.to_tensor(np.array([0, 0, 1], np.int32))
    np.testing.assert_allclose(incubate.segment_sum(x, ids).numpy(), [[4, 6], [5, 6]])
    np.testing.assert_allclose(incubate.segment_mean(x, ids).numpy(), [[2, 3], [5, 6]])
    np.testing.assert_allclose(incubate.segment_max(x, ids).numpy(), [[3, 4], [5, 6]])
    np.testing.assert_allclose(incubate.segment_min(x, ids).numpy(), [[1, 2], [5, 6]])


def test_graph_send_recv():
    x = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]], np.float32))
    src = paddle.to_tensor(np.array([0, 1, 2], np.int32))
    dst = paddle.to_tensor(np.array([1, 1, 0], np.int32))
    out = incubate.graph_send_recv(x, src, dst, "sum")
    np.testing.assert_allclose(out.numpy(), [[5, 6], [4, 6], [0, 0]])


def test_max_unpool_1d_3d():
    x = paddle.to_tensor(np.array([[[5.0, 7.0]]], np.float32))
    idx = paddle.to_tensor(np.array([[[1, 2]]], np.int32))
    out = F.max_unpool1d(x, idx, kernel_size=2).numpy()
    np.testing.assert_allclose(out, [[[0, 5, 7, 0]]])
    x3 = paddle.to_tensor(np.ones((1, 1, 1, 1, 1), np.float32) * 9)
    i3 = paddle.to_tensor(np.array([[[[[3]]]]], np.int32))
    out3 = F.max_unpool3d(x3, i3, kernel_size=2).numpy()
    assert out3.shape == (1, 1, 2, 2, 2) and out3.reshape(-1)[3] == 9


def test_jacobian_hessian():
    x = np.array([1.0, 2.0], np.float32)
    jac = paddle.autograd.jacobian(lambda t: (t * t), paddle.to_tensor(x))
    np.testing.assert_allclose(jac.numpy(), np.diag(2 * x), rtol=1e-5)
    h = paddle.autograd.hessian(lambda t: (t * t), paddle.to_tensor(x))
    np.testing.assert_allclose(h.numpy(), 2 * np.eye(2), rtol=1e-5)


def test_distribution_additions():
    from paddle_tpu.distribution import (Cauchy, Independent, Normal,
                                         kl_divergence, register_kl)
    c = Cauchy(0.0, 1.0)
    np.testing.assert_allclose(c.log_prob(paddle.to_tensor(0.0)).numpy(),
                               -np.log(np.pi), rtol=1e-4)
    np.testing.assert_allclose(c.cdf(paddle.to_tensor(0.0)).numpy(), 0.5,
                               atol=1e-6)
    kl = kl_divergence(Cauchy(0.0, 1.0), Cauchy(0.0, 1.0))
    np.testing.assert_allclose(kl.numpy(), 0.0, atol=1e-6)
    ind = Independent(Normal(np.zeros(3, np.float32), np.ones(3, np.float32)), 1)
    lp = ind.log_prob(paddle.to_tensor(np.zeros(3, np.float32)))
    np.testing.assert_allclose(lp.numpy(), 3 * (-0.5 * np.log(2 * np.pi)),
                               rtol=1e-5)

    class _Dummy(Normal):
        pass

    @register_kl(_Dummy, _Dummy)
    def _kl_dummy(p, q):
        return paddle.to_tensor(np.float32(42.0))

    got = kl_divergence(_Dummy(0.0, 1.0), _Dummy(0.0, 1.0))
    np.testing.assert_allclose(got.numpy(), 42.0)


def test_beam_search_decode_greedy_path():
    """Deterministic cell that always prefers token (state+1): beam search with
    beam 1-hot start must follow the argmax chain and stop at end_token."""
    import paddle_tpu.nn as nn
    V = 5

    def cell(inp, states):
        # states: counter Tensor [B*W]; prefer token = min(counter+1, 4)
        cnt = states
        nxt = np.minimum(np.asarray(cnt.numpy()) + 1, 4)
        logits = np.full((len(nxt), V), -5.0, np.float32)
        logits[np.arange(len(nxt)), nxt] = 5.0
        return paddle.to_tensor(logits), paddle.to_tensor(
            np.asarray(nxt, np.int64))

    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=4, beam_size=2)
    init = paddle.to_tensor(np.zeros(2 * 2, np.int64))  # B=2, W=2
    out, state = nn.dynamic_decode(dec, init, max_step_num=8)
    seq = out.numpy()[:, :, 0]  # best beam
    np.testing.assert_array_equal(seq[0], [1, 2, 3, 4])


def test_sparse_attention_causal_csr():
    rng = np.random.RandomState(0)
    B, H, T, D = 1, 2, 4, 8
    q, k, v = (rng.randn(B, H, T, D).astype(np.float32) for _ in range(3))
    off = np.tile(np.cumsum([0] + [t + 1 for t in range(T)]).astype(np.int32),
                  (B, H, 1))
    cols = np.tile(np.concatenate([np.arange(t + 1) for t in range(T)])
                   .astype(np.int32), (B, H, 1))
    out = F.sparse_attention(*[paddle.to_tensor(t)
                               for t in (q, k, v, off, cols)]).numpy()
    s = np.einsum("bhtd,bhsd->bhts", q, k) / np.sqrt(D)
    s = np.where(np.tril(np.ones((T, T), bool)), s, -1e30)
    w = np.exp(s - s.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    np.testing.assert_allclose(out, np.einsum("bhts,bhsd->bhtd", w, v),
                               atol=1e-5)


def test_lu_unpack_batched():
    rng = np.random.RandomState(0)
    A = rng.randn(2, 4, 4).astype(np.float32)
    lu, piv = paddle.linalg.lu(paddle.to_tensor(A))
    P, L, U = paddle.linalg.lu_unpack(lu, piv)
    np.testing.assert_allclose(
        np.einsum("bij,bjk,bkl->bil", P.numpy(), L.numpy(), U.numpy()), A,
        atol=1e-4)


def test_hsigmoid_is_normalized_distribution():
    rng = np.random.RandomState(0)
    for C in (3, 5, 8):
        wt = rng.randn(C - 1, 4).astype(np.float32)
        xx = rng.randn(1, 4).astype(np.float32)
        ps = [np.exp(-float(F.hsigmoid_loss(
            paddle.to_tensor(xx), paddle.to_tensor(np.array([c], np.int64)),
            C, paddle.to_tensor(wt)).numpy())) for c in range(C)]
        np.testing.assert_allclose(sum(ps), 1.0, rtol=1e-5)


def test_multi_margin_weight_uses_target_class():
    x = np.array([[0.1, 0.9, 0.3]], np.float32)
    y = np.array([1], np.int64)
    w = np.array([1.0, 5.0, 1.0], np.float32)
    got = float(F.multi_margin_loss(paddle.to_tensor(x), paddle.to_tensor(y),
                                    weight=paddle.to_tensor(w)).numpy())
    exp = 5 * (max(0, 1 - 0.9 + 0.1) + max(0, 1 - 0.9 + 0.3)) / 3
    np.testing.assert_allclose(got, exp, rtol=1e-5)
