"""Test config: force the CPU backend with 8 virtual devices so multi-chip sharding
paths compile and execute without TPU hardware (the reference's fake-device CI pattern,
`test/custom_runtime/`)."""
import os

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # tier-1 runs `-m "not slow"` (ROADMAP): 'slow' holds the compile-heavy
    # deep parallel-combo parity tests that would blow the tier-1 time budget
    config.addinivalue_line("markers", "slow: excluded from the tier-1 suite")
