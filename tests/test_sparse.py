"""paddle.sparse parity (ref python/paddle/sparse/ + test/legacy_test sparse
op tests): COO/CSR creation, conversions, elementwise, matmul family,
autograd through values, and sparse.nn layers."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.sparse as sparse


def _coo():
    indices = np.array([[0, 1, 2], [1, 2, 0]], np.int64)
    values = np.array([1.0, 2.0, 3.0], np.float32)
    return sparse.sparse_coo_tensor(indices, values, [3, 3])


def test_coo_create_and_to_dense():
    s = _coo()
    assert s.is_sparse_coo() and s.nnz() == 3
    dense = s.to_dense().numpy()
    exp = np.zeros((3, 3), np.float32)
    exp[0, 1], exp[1, 2], exp[2, 0] = 1, 2, 3
    np.testing.assert_allclose(dense, exp)


def test_csr_create_and_roundtrip():
    crows = np.array([0, 2, 3, 5], np.int64)
    cols = np.array([0, 2, 1, 0, 2], np.int64)
    vals = np.array([1., 2., 3., 4., 5.], np.float32)
    s = sparse.sparse_csr_tensor(crows, cols, vals, [3, 3])
    exp = np.array([[1, 0, 2], [0, 3, 0], [4, 0, 5]], np.float32)
    np.testing.assert_allclose(s.to_dense().numpy(), exp)
    coo = s.to_sparse_coo()
    np.testing.assert_allclose(coo.to_dense().numpy(), exp)
    back = coo.to_sparse_csr()
    np.testing.assert_allclose(back.to_dense().numpy(), exp)
    np.testing.assert_array_equal(back.crows().numpy(), crows)


def test_dense_tensor_to_sparse_methods():
    d = paddle.to_tensor(np.array([[0., 5.], [7., 0.]], np.float32))
    coo = d.to_sparse_coo()
    assert coo.nnz() == 2
    np.testing.assert_allclose(coo.to_dense().numpy(), d.numpy())
    csr = d.to_sparse_csr()
    np.testing.assert_allclose(csr.to_dense().numpy(), d.numpy())


def test_coalesce_merges_duplicates():
    indices = np.array([[0, 0, 1], [1, 1, 0]], np.int64)
    s = sparse.sparse_coo_tensor(indices, np.array([1., 2., 3.], np.float32),
                                 [2, 2])
    c = sparse.coalesce(s)
    assert c.nnz() == 2
    np.testing.assert_allclose(c.to_dense().numpy(), [[0, 3], [3, 0]])


def test_unary_preserves_structure():
    s = _coo()
    out = sparse.square(s)
    assert out.is_sparse_coo() and out.nnz() == 3
    np.testing.assert_allclose(out.values().numpy(), [1., 4., 9.])
    np.testing.assert_allclose(sparse.neg(s).values().numpy(), [-1., -2., -3.])


def test_binary_same_pattern():
    a, b = _coo(), _coo()
    out = sparse.add(a, b)
    np.testing.assert_allclose(out.values().numpy(), [2., 4., 6.])
    m = sparse.multiply(a, b)
    np.testing.assert_allclose(m.values().numpy(), [1., 4., 9.])


def test_sparse_matmul_and_mv():
    s = _coo()
    d = np.arange(9, dtype=np.float32).reshape(3, 3)
    out = sparse.matmul(s, paddle.to_tensor(d))
    np.testing.assert_allclose(out.numpy(), s.numpy() @ d, atol=1e-5)
    v = np.array([1., 2., 3.], np.float32)
    mv = sparse.mv(s, paddle.to_tensor(v))
    np.testing.assert_allclose(mv.numpy(), s.numpy() @ v, atol=1e-5)


def test_masked_matmul_sddmm():
    rng = np.random.RandomState(0)
    a = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(4, 3).astype(np.float32)
    mask = _coo()
    out = sparse.masked_matmul(paddle.to_tensor(a), paddle.to_tensor(b), mask)
    assert out.is_sparse_coo()
    full = a @ b
    exp = np.array([full[0, 1], full[1, 2], full[2, 0]])
    np.testing.assert_allclose(out.values().numpy(), exp, atol=1e-5)


def test_grad_flows_through_sparse_values():
    indices = np.array([[0, 1], [1, 0]], np.int64)
    vals = paddle.to_tensor(np.array([2.0, 3.0], np.float32))
    vals.stop_gradient = False
    s = sparse.SparseCooTensor(indices, vals, (2, 2))
    d = np.ones((2, 2), np.float32)
    out = sparse.matmul(s, paddle.to_tensor(d))
    out.sum().backward()
    # d out.sum() / d val_k = row-sum of dense = 2 for each
    np.testing.assert_allclose(vals.grad.numpy(), [2.0, 2.0])


def test_sparse_nn_activations_and_softmax():
    import paddle_tpu.sparse.nn as snn
    s = sparse.sparse_coo_tensor(np.array([[0, 1], [0, 1]], np.int64),
                                 np.array([-1.0, 2.0], np.float32), [2, 2])
    out = snn.ReLU()(s)
    np.testing.assert_allclose(out.values().numpy(), [0.0, 2.0])
    out6 = snn.functional.relu6(sparse.sparse_coo_tensor(
        np.array([[0], [0]], np.int64), np.array([9.0], np.float32), [1, 1]))
    np.testing.assert_allclose(out6.values().numpy(), [6.0])
    # csr softmax: single fully-dense row == dense softmax
    crows = np.array([0, 3], np.int64)
    cols = np.array([0, 1, 2], np.int64)
    vals = np.array([1.0, 2.0, 3.0], np.float32)
    sm = snn.Softmax()(sparse.sparse_csr_tensor(crows, cols, vals, [1, 3]))
    e = np.exp(vals - vals.max())
    np.testing.assert_allclose(sm.values().numpy(), e / e.sum(), rtol=1e-5)


def test_subm_conv_preserves_pattern():
    import paddle_tpu.sparse.nn as snn
    rng = np.random.RandomState(0)
    dense = np.zeros((1, 5, 5, 2), np.float32)   # NHWC
    dense[0, 1, 1] = rng.randn(2)
    dense[0, 3, 2] = rng.randn(2)
    x = paddle.to_tensor(dense).to_sparse_coo(3)
    conv = snn.SubmConv2D(2, 4, kernel_size=3, padding=1)
    out = conv(x)
    assert out.is_sparse_coo()
    # pattern preserved: same active sites
    np.testing.assert_array_equal(np.asarray(out.indices().numpy()),
                                  np.asarray(x.indices().numpy()))
    assert out.shape[-1] == 4


def test_sparse_conv3d_runs():
    import paddle_tpu.sparse.nn as snn
    dense = np.zeros((1, 4, 4, 4, 2), np.float32)
    dense[0, 1, 1, 1] = [1.0, -1.0]
    x = paddle.to_tensor(dense).to_sparse_coo(4)
    conv = snn.Conv3D(2, 3, kernel_size=3, padding=1)
    out = conv(x)
    d = out.to_dense().numpy()
    assert d.shape == (1, 4, 4, 4, 3)
    assert np.isfinite(d).all()


def test_is_same_shape_and_cast():
    a, b = _coo(), _coo()
    assert sparse.is_same_shape(a, b)
    c = sparse.cast(a, value_dtype="float64")
    assert "float64" in str(c.dtype) or "f64" in str(c.dtype) or \
        c.values().numpy().dtype == np.float32  # x64 disabled: stays f32
