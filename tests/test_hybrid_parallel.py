"""Hybrid-parallel compiled trainer tests on the 8-device virtual mesh (reference
category: `test/collective/fleet/hybrid_parallel_*` — parallel-vs-serial loss parity)."""
import numpy as np
import pytest

import jax

from paddle_tpu.models.gpt import GPTConfig, gpt_tiny
from paddle_tpu.parallel import HybridParallelTrainer, MeshConfig

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


def _data(cfg, batch=8, seq=32):
    rng = np.random.RandomState(0)
    tok = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def _losses(trainer, tok, lab, n=3):
    return [float(trainer.train_step(tok, lab)) for _ in range(n)]


def test_dp_mp_zero_matches_single_device():
    cfg = gpt_tiny(32)
    tok, lab = _data(cfg)
    ref = _losses(HybridParallelTrainer(cfg, MeshConfig(), seed=3), tok, lab)
    got = _losses(HybridParallelTrainer(
        cfg, MeshConfig(dp=2, mp=2, sharding_stage=1), seed=3), tok, lab)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_pipeline_matches_single_device():
    cfg = gpt_tiny(32)
    tok, lab = _data(cfg)
    ref = _losses(HybridParallelTrainer(cfg, MeshConfig(), seed=3), tok, lab)
    got = _losses(HybridParallelTrainer(
        cfg, MeshConfig(dp=2, pp=2, mp=2, micro_batches=4, sharding_stage=1),
        seed=3), tok, lab)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_remat_and_sequence_parallel_match():
    cfg = gpt_tiny(32)
    tok, lab = _data(cfg)
    ref = _losses(HybridParallelTrainer(cfg, MeshConfig(), seed=3), tok, lab)
    got = _losses(HybridParallelTrainer(
        cfg, MeshConfig(dp=2, mp=2, sequence_parallel=True, remat=True), seed=3),
        tok, lab)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_loss_decreases_under_pipeline():
    cfg = gpt_tiny(32)
    tok, lab = _data(cfg)
    tr = HybridParallelTrainer(cfg, MeshConfig(dp=1, pp=2, mp=1, micro_batches=2),
                               learning_rate=1e-3, seed=0)
    losses = _losses(tr, tok, lab, n=10)
    assert losses[-1] < losses[0]


def test_param_shardings_are_applied():
    cfg = gpt_tiny(32)
    tr = HybridParallelTrainer(cfg, MeshConfig(dp=2, pp=2, mp=2, sharding_stage=1),
                               seed=0)
    qkv = tr.params["blocks"]["qkv_w"]
    spec = qkv.sharding.spec
    assert spec[0] == "pp" and spec[2] == "mp"
    # ZeRO: adam moment of a param with a free axis picks up 'dp'
    m_wte = tr.opt_state["m"]["wte"]
    assert "dp" in tuple(m_wte.sharding.spec)


@pytest.mark.slow      # deep-combo compile cost; tier-1 keeps a cheap representative
def test_graft_entry_dryrun():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "graft_entry", __file__.rsplit("/tests/", 1)[0] + "/__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)
    fn, args = mod.entry()
    out = jax.eval_shape(fn, *args)
    assert out.shape[0] == 1


# ---- ZeRO-2/3 over the dedicated 'sharding' axis ----

def _leaf_local_bytes(tree):
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shard = leaf.addressable_shards[0]
        total += shard.data.size * shard.data.dtype.itemsize
    return total


def test_zero3_param_and_moment_bytes_shrink():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import gpt_tiny, count_params
    from paddle_tpu.parallel import HybridParallelTrainer, MeshConfig

    config = gpt_tiny(64)
    t3 = HybridParallelTrainer(
        config, MeshConfig(sharding=4, mp=2, sharding_stage=3),
        devices=jax.devices()[:8])
    t0 = HybridParallelTrainer(
        config, MeshConfig(dp=8, sharding_stage=0), devices=jax.devices()[:8])

    full_p = _leaf_local_bytes(t0.params)      # dp: replicated params
    z3_p = _leaf_local_bytes(t3.params)
    # sharding=4 x mp=2: most tensors split 8x; small norm vectors may not split
    assert z3_p < 0.25 * full_p, f"stage-3 params not sharded: {z3_p} vs {full_p}"

    full_m = _leaf_local_bytes(t0.opt_state["m"])
    z3_m = _leaf_local_bytes(t3.opt_state["m"])
    assert z3_m < 0.25 * full_m, f"stage-3 moments not sharded: {z3_m} vs {full_m}"


@pytest.mark.slow      # deep-combo compile cost; tier-1 keeps a cheap representative
def test_zero_stages_loss_parity():
    import jax
    import numpy as np
    from paddle_tpu.models.gpt import gpt_tiny
    from paddle_tpu.parallel import HybridParallelTrainer, MeshConfig

    config = gpt_tiny(64)
    rng = np.random.RandomState(0)
    tok = rng.randint(0, config.vocab_size, (8, 64)).astype(np.int32)
    lab = np.roll(tok, -1, axis=1).astype(np.int32)

    losses = {}
    for name, cfg in [
        ("dp8_z1", MeshConfig(dp=8, sharding_stage=1)),
        ("sh4mp2_z2", MeshConfig(sharding=4, mp=2, sharding_stage=2)),
        ("sh4mp2_z3", MeshConfig(sharding=4, mp=2, sharding_stage=3)),
        ("dp2sh2mp2_z3", MeshConfig(dp=2, sharding=2, mp=2, sharding_stage=3)),
    ]:
        tr = HybridParallelTrainer(config, cfg, devices=jax.devices()[:8])
        ls = [float(tr.train_step(tok, lab)) for _ in range(3)]
        losses[name] = ls
    base = losses["dp8_z1"]
    for name, ls in losses.items():
        np.testing.assert_allclose(ls, base, rtol=2e-4,
                                   err_msg=f"{name} diverged: {ls} vs {base}")


def test_zero3_with_pp_and_remat():
    import jax
    import numpy as np
    from paddle_tpu.models.gpt import gpt_tiny
    from paddle_tpu.parallel import HybridParallelTrainer, MeshConfig

    config = gpt_tiny(64)
    tr = HybridParallelTrainer(
        config,
        MeshConfig(pp=2, sharding=2, mp=2, sharding_stage=3, micro_batches=2,
                   remat=True),
        devices=jax.devices()[:8])
    rng = np.random.RandomState(1)
    tok = rng.randint(0, config.vocab_size, (8, 64)).astype(np.int32)
    lab = np.roll(tok, -1, axis=1).astype(np.int32)
    loss = float(tr.train_step(tok, lab))
    assert np.isfinite(loss)


def test_pp_untied_embeddings_and_wpe_parity():
    # round-1 verdict: PP was hard-asserted to tied-embeddings + rope only
    import jax
    import numpy as np
    from paddle_tpu.models.gpt import GPTConfig, gpt_tiny
    from paddle_tpu.parallel import HybridParallelTrainer, MeshConfig

    config = GPTConfig(vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
                       max_seq_len=64, use_rope=False, tie_word_embeddings=False)
    rng = np.random.RandomState(3)
    tok = rng.randint(0, config.vocab_size, (8, 64)).astype(np.int32)
    lab = np.roll(tok, -1, axis=1).astype(np.int32)
    lab[:, -5:] = -100  # uneven masking across microbatches

    single = HybridParallelTrainer(config, MeshConfig(), devices=jax.devices()[:1])
    pp = HybridParallelTrainer(
        config, MeshConfig(pp=2, mp=2, micro_batches=2),
        devices=jax.devices()[:4])
    for _ in range(3):
        l0 = float(single.train_step(tok, lab))
        l1 = float(pp.train_step(tok, lab))
        np.testing.assert_allclose(l1, l0, rtol=2e-4)


def test_pp4_parity():
    import jax
    import numpy as np
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.parallel import HybridParallelTrainer, MeshConfig

    config = GPTConfig(vocab_size=256, hidden_size=64, num_layers=8, num_heads=4,
                       max_seq_len=32)
    rng = np.random.RandomState(4)
    tok = rng.randint(0, config.vocab_size, (8, 32)).astype(np.int32)
    lab = np.roll(tok, -1, axis=1).astype(np.int32)

    single = HybridParallelTrainer(config, MeshConfig(), devices=jax.devices()[:1])
    pp4 = HybridParallelTrainer(
        config, MeshConfig(pp=4, micro_batches=4, remat=True),
        devices=jax.devices()[:4])
    for _ in range(2):
        l0 = float(single.train_step(tok, lab))
        l1 = float(pp4.train_step(tok, lab))
        np.testing.assert_allclose(l1, l0, rtol=2e-4)


@pytest.mark.slow      # deep-combo compile cost; tier-1 keeps a cheap representative
def test_interleaved_virtual_pipeline_matches_single():
    """vpp>1 (ref PipelineParallelWithInterleave :822): non-contiguous layer
    chunks per stage, Megatron closed-form schedule; parity vs single chip."""
    import jax
    from paddle_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=8, num_heads=4,
                    max_seq_len=64)
    rng = np.random.RandomState(0)
    tok = rng.randint(0, cfg.vocab_size, (8, 64)).astype(np.int32)
    lab = np.roll(tok, -1, 1).astype(np.int32)
    ref = _losses(HybridParallelTrainer(cfg, MeshConfig(), seed=3,
                                        devices=jax.devices()[:1]), tok, lab)
    for mc, n in ((MeshConfig(pp=2, vpp=2, micro_batches=4), 2),
                  (MeshConfig(pp=4, vpp=2, micro_batches=4, remat=True), 4),
                  (MeshConfig(dp=2, pp=2, vpp=2, mp=2, micro_batches=2), 8)):
        got = _losses(HybridParallelTrainer(cfg, mc, seed=3,
                                            devices=jax.devices()[:n]),
                      tok, lab)
        np.testing.assert_allclose(got, ref, rtol=1e-4)


def test_interleave_divisibility_asserts():
    import jax
    import pytest as _pytest
    from paddle_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=6, num_heads=4,
                    max_seq_len=64)
    tr = HybridParallelTrainer(cfg, MeshConfig(pp=2, vpp=2, micro_batches=2),
                               seed=0, devices=jax.devices()[:2])
    tok = np.zeros((4, 64), np.int32)
    with _pytest.raises(AssertionError, match="divide over pp\\*vpp"):
        tr.train_step(tok, tok)
