"""Hybrid-parallel compiled trainer tests on the 8-device virtual mesh (reference
category: `test/collective/fleet/hybrid_parallel_*` — parallel-vs-serial loss parity)."""
import numpy as np
import pytest

import jax

from paddle_tpu.models.gpt import GPTConfig, gpt_tiny
from paddle_tpu.parallel import HybridParallelTrainer, MeshConfig

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


def _data(cfg, batch=8, seq=32):
    rng = np.random.RandomState(0)
    tok = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def _losses(trainer, tok, lab, n=3):
    return [float(trainer.train_step(tok, lab)) for _ in range(n)]


def test_dp_mp_zero_matches_single_device():
    cfg = gpt_tiny(32)
    tok, lab = _data(cfg)
    ref = _losses(HybridParallelTrainer(cfg, MeshConfig(), seed=3), tok, lab)
    got = _losses(HybridParallelTrainer(
        cfg, MeshConfig(dp=2, mp=2, sharding_stage=1), seed=3), tok, lab)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_pipeline_matches_single_device():
    cfg = gpt_tiny(32)
    tok, lab = _data(cfg)
    ref = _losses(HybridParallelTrainer(cfg, MeshConfig(), seed=3), tok, lab)
    got = _losses(HybridParallelTrainer(
        cfg, MeshConfig(dp=2, pp=2, mp=2, micro_batches=4, sharding_stage=1),
        seed=3), tok, lab)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_remat_and_sequence_parallel_match():
    cfg = gpt_tiny(32)
    tok, lab = _data(cfg)
    ref = _losses(HybridParallelTrainer(cfg, MeshConfig(), seed=3), tok, lab)
    got = _losses(HybridParallelTrainer(
        cfg, MeshConfig(dp=2, mp=2, sequence_parallel=True, remat=True), seed=3),
        tok, lab)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_loss_decreases_under_pipeline():
    cfg = gpt_tiny(32)
    tok, lab = _data(cfg)
    tr = HybridParallelTrainer(cfg, MeshConfig(dp=1, pp=2, mp=1, micro_batches=2),
                               learning_rate=1e-3, seed=0)
    losses = _losses(tr, tok, lab, n=10)
    assert losses[-1] < losses[0]


def test_param_shardings_are_applied():
    cfg = gpt_tiny(32)
    tr = HybridParallelTrainer(cfg, MeshConfig(dp=2, pp=2, mp=2, sharding_stage=1),
                               seed=0)
    qkv = tr.params["blocks"]["qkv_w"]
    spec = qkv.sharding.spec
    assert spec[0] == "pp" and spec[2] == "mp"
    # ZeRO: adam moment of a param with a free axis picks up 'dp'
    m_wte = tr.opt_state["m"]["wte"]
    assert "dp" in tuple(m_wte.sharding.spec)


def test_graft_entry_dryrun():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "graft_entry", __file__.rsplit("/tests/", 1)[0] + "/__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)
    fn, args = mod.entry()
    out = jax.eval_shape(fn, *args)
    assert out.shape[0] == 1
