"""paddle.inference Predictor + paddle.quantization QAT/PTQ
(ref analysis_predictor.cc, quantization/imperative/qat.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _model():
    paddle.framework.random.seed(7)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def _saved_model(tmp_path):
    from paddle_tpu.static import InputSpec
    model = _model()
    path = str(tmp_path / "m")
    paddle.jit.save(model, path, input_spec=[InputSpec([4, 8], "float32")])
    return model, path


def test_predictor_serves_saved_model(tmp_path):
    model, path = _saved_model(tmp_path)
    from paddle_tpu.inference import Config, create_predictor
    cfg = Config(path + ".pdmodel", path + ".pdiparams")
    pred = create_predictor(cfg)
    names = pred.get_input_names()
    assert len(names) == 1
    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    h = pred.get_input_handle(names[0])
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    ref = model(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_predictor_run_list_api(tmp_path):
    _, path = _saved_model(tmp_path)
    from paddle_tpu.inference import Config, create_predictor
    pred = create_predictor(Config(path))
    x = np.ones((4, 8), np.float32)
    outs = pred.run([x])
    assert outs[0].shape == (4, 4)


def test_qat_trains_and_converts():
    from paddle_tpu.quantization import QAT, Int8Linear, QuantedLinear
    import paddle_tpu.nn.functional as F
    model = _model()
    qat = QAT()
    model = qat.quantize(model)
    assert any(isinstance(l, QuantedLinear)
               for l in model._sub_layers.values())
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=model.parameters())
    rng = np.random.RandomState(0)
    X = rng.randn(32, 8).astype(np.float32)
    Y = rng.randint(0, 4, (32,)).astype(np.int64)
    losses = []
    for _ in range(15):
        loss = F.cross_entropy(model(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0], losses[::5]

    converted = qat.convert(model)
    assert any(isinstance(l, Int8Linear)
               for l in converted._sub_layers.values())
    out_q = converted(paddle.to_tensor(X)).numpy()
    assert np.isfinite(out_q).all()


def test_ptq_calibrate_convert_close_to_fp():
    from paddle_tpu.quantization import PTQ, Int8Linear
    model = _model()
    rng = np.random.RandomState(0)
    X = rng.randn(16, 8).astype(np.float32)
    fp_out = model(paddle.to_tensor(X)).numpy()
    ptq = PTQ()
    model = ptq.quantize(model)
    model(paddle.to_tensor(X))          # calibration pass
    model = ptq.convert(model)
    assert any(isinstance(l, Int8Linear)
               for l in model._sub_layers.values())
    q_out = model(paddle.to_tensor(X)).numpy()
    # int8 weight-only quantization: small relative error vs fp
    rel = np.abs(q_out - fp_out).max() / (np.abs(fp_out).max() + 1e-6)
    assert rel < 0.05, rel


def test_fake_quant_ste_gradient():
    from paddle_tpu.quantization import fake_quant
    x = paddle.to_tensor(np.linspace(-1, 1, 16).astype(np.float32))
    x.stop_gradient = False
    y = fake_quant(x, paddle.to_tensor(np.float32(1.0)))
    # values land on the int8 grid
    q = y.numpy() * 127
    np.testing.assert_allclose(q, np.round(q), atol=1e-4)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones(16))  # STE identity


def test_int8_quantized_model_serves_through_predictor(tmp_path):
    from paddle_tpu.quantization import ImperativeQuantAware
    from paddle_tpu.static import InputSpec
    model = _model()
    iqa = ImperativeQuantAware()
    model = iqa.quantize(model)
    model(paddle.to_tensor(np.ones((4, 8), np.float32)))   # init scales
    path = str(tmp_path / "q")
    iqa.save_quantized_model(model, path,
                             input_spec=[InputSpec([4, 8], "float32")])
    from paddle_tpu.inference import Config, create_predictor
    pred = create_predictor(Config(path))
    outs = pred.run([np.ones((4, 8), np.float32)])
    assert np.isfinite(outs[0]).all()


def test_onnx_export_descope_message():
    with pytest.raises(NotImplementedError, match="StableHLO"):
        paddle.onnx.export(_model(), "x")
