"""Auto-parallel Engine + distributed checkpoint resharding
(ref auto_parallel/static/engine.py:55, dist_saver.py, converter.py).

The VERDICT acceptance test: train on mesh (dp2, mp2), save, resume on a
DIFFERENT mesh (dp4 / mp1) — losses continue on-curve vs an uninterrupted run.
"""
import jax
import numpy as np
import pytest

from paddle_tpu.distributed.auto_parallel import Engine
from paddle_tpu.models.gpt import gpt_tiny
from paddle_tpu.parallel import HybridParallelTrainer, MeshConfig


def _data(cfg, n=32, S=64, seed=0):
    rng = np.random.RandomState(seed)
    tok = rng.randint(0, cfg.vocab_size, (n, S)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def test_engine_fit_evaluate_predict():
    cfg = gpt_tiny(64)
    tok, lab = _data(cfg)
    eng = Engine(config=cfg, mesh_config=MeshConfig(dp=2, mp=2),
                 devices=jax.devices()[:4], seed=3)
    hist = eng.fit((tok, lab), epochs=2, batch_size=8, verbose=0)
    assert len(hist["loss"]) == 8
    assert hist["loss"][-1] < hist["loss"][0]
    ev = eng.evaluate((tok[:8], lab[:8]), verbose=0)
    assert np.isfinite(ev)
    logits = eng.predict(tok[:4], batch_size=4)
    assert logits.shape == (4, 64, cfg.vocab_size)


def test_checkpoint_reshard_resume_on_curve(tmp_path):
    """Save on (dp2, mp2), resume on (dp4) and on (mp2): both continue exactly
    on the uninterrupted loss curve."""
    cfg = gpt_tiny(64)
    tok, lab = _data(cfg, n=8)

    # uninterrupted reference: 6 steps on (dp2, mp2)
    ref = Engine(config=cfg, mesh_config=MeshConfig(dp=2, mp=2),
                 devices=jax.devices()[:4], seed=3)
    ref_losses = [float(ref.trainer.train_step(tok, lab)) for _ in range(6)]

    # interrupted: 3 steps, save, resume on two different meshes
    a = Engine(config=cfg, mesh_config=MeshConfig(dp=2, mp=2),
               devices=jax.devices()[:4], seed=3)
    first = [float(a.trainer.train_step(tok, lab)) for _ in range(3)]
    np.testing.assert_allclose(first, ref_losses[:3], rtol=1e-5)
    path = str(tmp_path / "ckpt")
    a.save(path)

    for mesh_cfg, ndev in ((MeshConfig(dp=4), 4), (MeshConfig(mp=2), 2)):
        b = Engine(config=cfg, mesh_config=mesh_cfg,
                   devices=jax.devices()[:ndev], seed=999)  # different init
        b.load(path)
        rest = [float(b.trainer.train_step(tok, lab)) for _ in range(3)]
        np.testing.assert_allclose(rest, ref_losses[3:], rtol=2e-4)


def test_checkpoint_metadata_written(tmp_path):
    cfg = gpt_tiny(64)
    eng = Engine(config=cfg, mesh_config=MeshConfig(mp=2, sharding=2,
                                                    sharding_stage=2),
                 devices=jax.devices()[:4], seed=0)
    path = str(tmp_path / "meta")
    eng.save(path)
    from paddle_tpu.distributed.checkpoint import saved_dist_attr
    meta = saved_dist_attr(path)
    assert meta["mesh"]["axes"] == ["dp", "pp", "sharding", "mp", "ep", "cp"]
    # qkv weight is mp-sharded on its last dim
    qkv = meta["leaves"]["params/blocks/qkv_w"]
    assert qkv[-1] == "mp"


def test_checkpoint_without_optimizer(tmp_path):
    cfg = gpt_tiny(64)
    tok, lab = _data(cfg, n=8)
    a = Engine(config=cfg, mesh_config=MeshConfig(), devices=jax.devices()[:1],
               seed=3)
    a.trainer.train_step(tok, lab)
    path = str(tmp_path / "infer_only")
    a.save(path, training=False)
    b = Engine(config=cfg, mesh_config=MeshConfig(), devices=jax.devices()[:1],
               seed=7)
    b.load(path, load_optimizer=False)
    la = float(a.trainer.eval_loss(tok, lab))
    lb = float(b.trainer.eval_loss(tok, lab))
    np.testing.assert_allclose(la, lb, rtol=1e-5)


def test_load_optimizer_mismatch_paths(tmp_path):
    """Checkpoint without optimizer loads with default flags and vice versa."""
    cfg = gpt_tiny(64)
    tok, lab = _data(cfg, n=8)
    a = Engine(config=cfg, mesh_config=MeshConfig(), devices=jax.devices()[:1],
               seed=3)
    a.trainer.train_step(tok, lab)
    p1 = str(tmp_path / "no_opt")
    a.save(p1, training=False)
    b = Engine(config=cfg, mesh_config=MeshConfig(), devices=jax.devices()[:1],
               seed=9)
    b.load(p1)          # load_optimizer=True but checkpoint has no opt: fine
    np.testing.assert_allclose(float(b.trainer.eval_loss(tok, lab)),
                               float(a.trainer.eval_loss(tok, lab)), rtol=1e-5)
    p2 = str(tmp_path / "with_opt")
    a.save(p2, training=True)
    c = Engine(config=cfg, mesh_config=MeshConfig(), devices=jax.devices()[:1],
               seed=11)
    c.load(p2, load_optimizer=False)   # opt present but skipped: fine
    np.testing.assert_allclose(float(c.trainer.eval_loss(tok, lab)),
                               float(a.trainer.eval_loss(tok, lab)), rtol=1e-5)


def test_predict_includes_tail_batch():
    cfg = gpt_tiny(64)
    tok, _ = _data(cfg, n=10)
    eng = Engine(config=cfg, mesh_config=MeshConfig(),
                 devices=jax.devices()[:1], seed=0)
    out = eng.predict(tok, batch_size=4)
    assert out.shape[0] == 10
