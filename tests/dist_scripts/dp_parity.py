"""DataParallel loss/param parity trainer (the reference TestDistBase pattern:
`test/legacy_test/test_dist_base.py:962` — parallel run must match serial).

Every rank trains the same seeded MLP on its contiguous batch shard under
`dist.DataParallel` (per-param allreduce hooks); rank prints a JSON line with
its losses and a parameter checksum.  The parent test recomputes the serial
(full-batch, single-process) run and asserts the checksums agree.
"""
import json
import os

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


def build_model():
    import paddle_tpu.nn as nn
    paddle.framework.random.seed(1234)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def run(world, rank):
    import paddle_tpu.nn.functional as F
    model = build_model()
    if world > 1:
        model = dist.DataParallel(model)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    rng = np.random.RandomState(0)
    X = rng.randn(16, 8).astype(np.float32)
    Y = rng.randint(0, 4, (16,)).astype(np.int64)
    per = 16 // world
    xs = X[rank * per:(rank + 1) * per]
    ys = Y[rank * per:(rank + 1) * per]
    losses = []
    for _ in range(3):
        out = model(paddle.to_tensor(xs))
        loss = F.cross_entropy(out, paddle.to_tensor(ys))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss._data))
    ps = sum(float(np.abs(np.asarray(p._data)).sum())
             for p in model.parameters())
    return losses, ps


def main():
    env = dist.init_parallel_env()
    losses, ps = run(env.world_size, env.rank)
    print("DPRESULT " + json.dumps(
        {"rank": env.rank, "losses": losses, "param_sum": ps}), flush=True)


if __name__ == "__main__":
    main()
