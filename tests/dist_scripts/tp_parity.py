"""Eager tensor-parallel (mpu) trainer for the multi-process harness:
Column->Row parallel MLP + VocabParallelEmbedding across 2 REAL processes must
match the serial model (ref hybrid_parallel_mp_model.py test pattern)."""
import json
import os

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet


D_IN, D_HID, VOCAB = 8, 16, 12


def _full_weights():
    rng = np.random.RandomState(42)
    return {
        "emb": rng.randn(VOCAB, D_IN).astype(np.float32) * 0.1,
        "w1": rng.randn(D_IN, D_HID).astype(np.float32) * 0.1,
        "b1": rng.randn(D_HID).astype(np.float32) * 0.1,
        "w2": rng.randn(D_HID, D_IN).astype(np.float32) * 0.1,
        "b2": rng.randn(D_IN).astype(np.float32) * 0.1,
    }


def serial_forward_backward(ids):
    import jax.numpy as jnp
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    w = _full_weights()
    emb = paddle.to_tensor(w["emb"])
    emb.stop_gradient = False
    x = F.embedding(paddle.to_tensor(ids), emb)
    h = paddle.to_tensor(w["w1"])
    h.stop_gradient = False
    out = F.relu(paddle.matmul(x, h) + paddle.to_tensor(w["b1"]))
    w2 = paddle.to_tensor(w["w2"])
    out = paddle.matmul(out, w2) + paddle.to_tensor(w["b2"])
    loss = (out * out).mean()
    loss.backward()
    return float(loss._data), np.asarray(emb.grad._data)


def main():
    import jax.numpy as jnp
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed.fleet.layers.mpu import (
        ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)

    env = dist.init_parallel_env()
    world, rank = env.world_size, env.rank
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": world,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": 1, "mp_configs": {},
                               "pp_configs": {}}
    fleet.init(is_collective=True, strategy=strategy)

    w = _full_weights()
    emb = VocabParallelEmbedding(VOCAB, D_IN)
    col = ColumnParallelLinear(D_IN, D_HID, has_bias=True, gather_output=False)
    row = RowParallelLinear(D_HID, D_IN, has_bias=True, input_is_parallel=True)
    # load the SERIAL weights' shards
    per_v = VOCAB // world
    emb.weight.set_value(w["emb"][rank * per_v:(rank + 1) * per_v])
    per_h = D_HID // world
    col.weight.set_value(w["w1"][:, rank * per_h:(rank + 1) * per_h])
    col.bias.set_value(w["b1"][rank * per_h:(rank + 1) * per_h])
    row.weight.set_value(w["w2"][rank * per_h:(rank + 1) * per_h])
    row.bias.set_value(w["b2"])

    rng = np.random.RandomState(0)
    ids = rng.randint(0, VOCAB, (4, 6)).astype(np.int32)
    x = emb(paddle.to_tensor(ids))
    h = F.relu(col(x))
    out = row(h)
    loss = (out * out).mean()
    loss.backward()
    # embedding grad shard must equal the serial grad's shard
    serial_loss, serial_emb_grad = serial_forward_backward(ids)
    my_grad = np.asarray(emb.weight.grad._data)
    expect = serial_emb_grad[rank * per_v:(rank + 1) * per_v]
    ok_grad = bool(np.allclose(my_grad, expect, rtol=1e-4, atol=1e-5))
    print("TPRESULT " + json.dumps(
        {"rank": rank, "loss": float(loss._data), "serial_loss": serial_loss,
         "grad_ok": ok_grad}), flush=True)


if __name__ == "__main__":
    main()
