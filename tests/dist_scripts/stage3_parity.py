"""GroupShardedStage3 (+offload) trainer for the multi-process harness:
param-sharded training must match the serial run, and each rank's resident
param bytes must shrink ~world x (ref group_sharded_stage3.py)."""
import json
import os

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


def build():
    import paddle_tpu.nn as nn
    paddle.framework.random.seed(77)
    return nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 4))


def run(world, rank, offload):
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    model = build()
    full_bytes = sum(p._data.size * p._data.dtype.itemsize
                     for p in model.parameters())
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    if world > 1:
        model, opt, _ = group_sharded_parallel(model, opt, "p_g_os",
                                               offload=offload)
        resident = sum(p._data.size * p._data.dtype.itemsize
                       for p in model.parameters())
    else:
        resident = full_bytes
    rng = np.random.RandomState(3)
    X = rng.randn(16, 16).astype(np.float32)
    Y = rng.randint(0, 4, (16,)).astype(np.int64)
    losses = []
    for _ in range(3):
        out = model(paddle.to_tensor(X))
        loss = F.cross_entropy(out, paddle.to_tensor(Y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss._data))
    sd = model.state_dict()
    ps = sum(float(np.abs(np.asarray(v._data)).sum()) for v in sd.values())
    return losses, ps, full_bytes, resident


def main():
    env = dist.init_parallel_env()
    offload = os.environ.get("STAGE3_OFFLOAD", "0") == "1"
    losses, ps, full, resident = run(env.world_size, env.rank, offload)
    print("S3RESULT " + json.dumps(
        {"rank": env.rank, "losses": losses, "param_sum": ps,
         "full_bytes": full, "resident_bytes": resident}), flush=True)


if __name__ == "__main__":
    main()
