"""Per-rank collective checks, launched as a local multi-process cluster by
tests/test_multiprocess_dist.py through the launch CLI (the reference's
`test/collective/collective_*_api.py` scripts run under TestDistBase).

Each rank exercises the eager collective surface across real processes and
prints `RANK <r> COLLECTIVES OK` on success.
"""
import os
import sys

# one virtual CPU device per process (overrides any inherited 8-device flag —
# repeated absl flags: last one wins)
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=1"

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


def main():
    env = dist.init_parallel_env()
    rank, world = env.rank, env.world_size
    import jax
    assert jax.process_count() == world, (jax.process_count(), world)

    # all_reduce(SUM)
    t = paddle.to_tensor(np.array([float(rank + 1)], np.float32))
    dist.all_reduce(t)
    assert float(t._data[0]) == world * (world + 1) / 2, np.asarray(t._data)

    # all_reduce(MAX)
    t = paddle.to_tensor(np.array([float(rank)], np.float32))
    dist.all_reduce(t, op=dist.ReduceOp.MAX)
    assert float(t._data[0]) == world - 1

    # all_gather
    outs = []
    dist.all_gather(outs, paddle.to_tensor(np.array([rank], np.int32)))
    assert [int(o._data[0]) for o in outs] == list(range(world))

    # broadcast from rank 1
    b = paddle.to_tensor(np.array([rank * 10.0], np.float32))
    dist.broadcast(b, src=1)
    assert float(b._data[0]) == 10.0

    # alltoall: rank r sends slot j = r*world + j; receives [j*world + r]
    ins = [paddle.to_tensor(np.array([rank * world + j], np.int32))
           for j in range(world)]
    outs2 = []
    dist.alltoall(outs2, ins)
    assert [int(o._data[0]) for o in outs2] == \
        [j * world + rank for j in range(world)]

    # reduce_scatter
    rs_in = [paddle.to_tensor(np.array([float(j)], np.float32))
             for j in range(world)]
    rs_out = paddle.to_tensor(np.zeros(1, np.float32))
    dist.reduce_scatter(rs_out, rs_in)
    assert float(rs_out._data[0]) == rank * world

    # object collective
    objs = []
    dist.all_gather_object(objs, {"rank": rank, "msg": "hi" * (rank + 1)})
    assert [o["rank"] for o in objs] == list(range(world))

    # matched-pair send/recv: 0 -> last
    if world >= 2:
        last = world - 1
        if rank == 0:
            dist.send(paddle.to_tensor(np.array([123.5], np.float32)), dst=last)
        elif rank == last:
            r = paddle.to_tensor(np.zeros(1, np.float32))
            dist.recv(r, src=0)
            assert float(r._data[0]) == 123.5

    dist.barrier()
    print(f"RANK {rank} COLLECTIVES OK", flush=True)


if __name__ == "__main__":
    main()
