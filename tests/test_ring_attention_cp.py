"""Ring attention (context parallelism) + varlen segment attention.

Ring attention is the SURVEY §7.10 beyond-reference long-context mechanism;
varlen parity target is `nn/functional/flash_attention.py:200`
(flash_attn_unpadded).  CPU runs exercise the XLA paths; the Pallas varlen
kernel itself is driven on real TPU (same numerics oracle).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import paddle_tpu as paddle
from paddle_tpu.incubate.kernels.flash_attention import (
    attention_xla, attention_xla_segmented)
from paddle_tpu.models.gpt import gpt_tiny
from paddle_tpu.parallel import HybridParallelTrainer, MeshConfig
from paddle_tpu.parallel.ring_attention import ring_attention


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.RandomState(0)
    return tuple(jnp.asarray(rng.randn(2, 64, 4, 16).astype(np.float32))
                 for _ in range(3))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(qkv, causal):
    q, k, v = qkv
    mesh = Mesh(np.array(jax.devices()[:4]), ("cp",))
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = attention_xla(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_grads(qkv):
    q, k, v = qkv
    mesh = Mesh(np.array(jax.devices()[:4]), ("cp",))
    for arg in range(3):
        g1 = jax.grad(lambda *a: (ring_attention(*a, mesh) ** 2).sum(),
                      argnums=arg)(q, k, v)
        g2 = jax.grad(lambda *a: (attention_xla(*a, causal=True) ** 2).sum(),
                      argnums=arg)(q, k, v)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=2e-4)


def test_cp_trainer_matches_single():
    cfg = gpt_tiny(128)
    rng = np.random.RandomState(0)
    tok = rng.randint(0, cfg.vocab_size, (4, 128)).astype(np.int32)
    lab = np.roll(tok, -1, 1).astype(np.int32)
    ref = HybridParallelTrainer(cfg, MeshConfig(), seed=3,
                                devices=jax.devices()[:1])
    rl = [float(ref.train_step(tok, lab)) for _ in range(3)]
    t = HybridParallelTrainer(cfg, MeshConfig(cp=4), seed=3,
                              devices=jax.devices()[:4])
    cl = [float(t.train_step(tok, lab)) for _ in range(3)]
    np.testing.assert_allclose(cl, rl, rtol=1e-4)


def test_cp_composes_with_dp_mp_remat():
    cfg = gpt_tiny(128)
    rng = np.random.RandomState(0)
    tok = rng.randint(0, cfg.vocab_size, (4, 128)).astype(np.int32)
    lab = np.roll(tok, -1, 1).astype(np.int32)
    ref = HybridParallelTrainer(cfg, MeshConfig(), seed=3,
                                devices=jax.devices()[:1])
    rl = [float(ref.train_step(tok, lab)) for _ in range(3)]
    t = HybridParallelTrainer(cfg, MeshConfig(dp=2, cp=2, mp=2, remat=True),
                              seed=3, devices=jax.devices()[:8])
    cl = [float(t.train_step(tok, lab)) for _ in range(3)]
    np.testing.assert_allclose(cl, rl, rtol=1e-4)


def test_cp_nonrope_positions():
    cfg = gpt_tiny(128)
    cfg.use_rope = False
    rng = np.random.RandomState(0)
    tok = rng.randint(0, cfg.vocab_size, (4, 128)).astype(np.int32)
    lab = np.roll(tok, -1, 1).astype(np.int32)
    ref = HybridParallelTrainer(cfg, MeshConfig(), seed=3,
                                devices=jax.devices()[:1])
    rl = [float(ref.train_step(tok, lab)) for _ in range(2)]
    t = HybridParallelTrainer(cfg, MeshConfig(cp=2), seed=3,
                              devices=jax.devices()[:2])
    cl = [float(t.train_step(tok, lab)) for _ in range(2)]
    np.testing.assert_allclose(cl, rl, rtol=1e-4)


# ---------------------------------------------------------------------------
# varlen / segment attention (XLA path; Pallas kernel driven on TPU)
# ---------------------------------------------------------------------------

def test_segment_attention_blocks_cross_segment():
    rng = np.random.RandomState(0)
    B, S, H, D = 1, 32, 2, 8
    q, k, v = (jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
               for _ in range(3))
    seg = jnp.asarray(np.repeat([[0, 1]], 16, axis=1).reshape(1, 32))
    out = attention_xla_segmented(q, k, v, seg, seg, False, D ** -0.5)
    # segment 0's output must be independent of segment 1's k/v
    v2 = v.at[:, 16:].set(0.0)
    out2 = attention_xla_segmented(q, k, v2, seg, seg, False, D ** -0.5)
    np.testing.assert_allclose(np.asarray(out[:, :16]),
                               np.asarray(out2[:, :16]), atol=1e-6)
    assert not np.allclose(np.asarray(out[:, 16:]), np.asarray(out2[:, 16:]))


def test_flash_attn_unpadded_matches_per_sequence():
    import paddle_tpu.nn.functional as F
    rng = np.random.RandomState(0)
    H, D = 2, 8
    lens = [5, 9, 3]
    total = sum(lens)
    packed = rng.randn(total, H, D).astype(np.float32)
    cu = np.cumsum([0] + lens).astype(np.int32)
    out, _ = F.flash_attn_unpadded(
        paddle.to_tensor(packed), paddle.to_tensor(packed),
        paddle.to_tensor(packed), paddle.to_tensor(cu), paddle.to_tensor(cu),
        max(lens), max(lens), scale=D ** -0.5, causal=True)
    out = out.numpy()
    # reference: run each sequence separately
    for i, L in enumerate(lens):
        s, e = cu[i], cu[i + 1]
        seq = jnp.asarray(packed[s:e])[None]
        ref = attention_xla(seq, seq, seq, causal=True, scale=D ** -0.5)
        np.testing.assert_allclose(out[s:e], np.asarray(ref[0]), atol=1e-5)


def test_flash_attention_segment_ids_api():
    import paddle_tpu.nn.functional as F
    rng = np.random.RandomState(0)
    B, S, H, D = 2, 32, 2, 8
    x = rng.randn(B, S, H, D).astype(np.float32)
    seg = np.zeros((B, S), np.int32)
    seg[:, 16:] = 1
    q = paddle.to_tensor(x)
    out, _ = F.flash_attention(q, q, q, causal=True,
                               segment_ids=paddle.to_tensor(seg))
    ref = attention_xla_segmented(jnp.asarray(x), jnp.asarray(x),
                                  jnp.asarray(x), jnp.asarray(seg),
                                  jnp.asarray(seg), True, D ** -0.5)
    np.testing.assert_allclose(out.numpy(), np.asarray(ref), atol=1e-5)


@pytest.mark.slow      # deepest cp x pp combo (~38 s compile), like the PR-1
def test_cp_composes_with_pipeline():   # deep-combo parity moves to slow tier
    """cp folded into the pp manual region: ring attention inside pipeline
    ticks, per-shard RoPE offsets, CE folds cp into its manual seq axes."""
    from paddle_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
                    max_seq_len=128)
    rng = np.random.RandomState(0)
    tok = rng.randint(0, cfg.vocab_size, (4, 128)).astype(np.int32)
    lab = np.roll(tok, -1, 1).astype(np.int32)
    ref = HybridParallelTrainer(cfg, MeshConfig(), seed=3,
                                devices=jax.devices()[:1])
    rl = [float(ref.train_step(tok, lab)) for _ in range(3)]
    for mc, n in ((MeshConfig(pp=2, cp=2, micro_batches=2), 4),
                  (MeshConfig(dp=2, pp=2, cp=2, micro_batches=2, remat=True), 8),
                  (MeshConfig(pp=2, cp=2, vpp=2, micro_batches=2), 4)):
        t = HybridParallelTrainer(cfg, mc, seed=3, devices=jax.devices()[:n])
        cl = [float(t.train_step(tok, lab)) for _ in range(3)]
        np.testing.assert_allclose(cl, rl, rtol=1e-4)
