"""Op unit tests vs numpy (reference category: `test/legacy_test/` OpTest files)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad, check_output

RNG = np.random.RandomState(42)


def data(*shape):
    return RNG.rand(*shape).astype(np.float32)


UNARY_CASES = [
    (paddle.exp, np.exp), (paddle.log, lambda x: np.log(x + 1.0)),
    (paddle.sqrt, np.sqrt), (paddle.tanh, np.tanh), (paddle.abs, np.abs),
    (paddle.floor, np.floor), (paddle.ceil, np.ceil), (paddle.sin, np.sin),
    (paddle.cos, np.cos), (paddle.square, np.square),
    (paddle.rsqrt, lambda x: 1.0 / np.sqrt(x)),
    (paddle.reciprocal, lambda x: 1.0 / x), (paddle.expm1, np.expm1),
    (paddle.log1p, np.log1p), (paddle.sign, np.sign),
]


@pytest.mark.parametrize("pfn,nfn", UNARY_CASES,
                         ids=[f.__name__ for f, _ in UNARY_CASES])
def test_unary(pfn, nfn):
    x = data(3, 4) + 0.1
    if pfn is paddle.log:
        check_output(lambda t: pfn(t + 1.0), nfn, [x])
    else:
        check_output(pfn, nfn, [x])


BINARY_CASES = [
    (paddle.add, np.add), (paddle.subtract, np.subtract),
    (paddle.multiply, np.multiply), (paddle.divide, np.divide),
    (paddle.maximum, np.maximum), (paddle.minimum, np.minimum),
    (paddle.pow, np.power), (paddle.atan2, np.arctan2),
]


@pytest.mark.parametrize("pfn,nfn", BINARY_CASES,
                         ids=[f.__name__ for f, _ in BINARY_CASES])
def test_binary(pfn, nfn):
    x = data(3, 4) + 0.5
    y = data(3, 4) + 0.5
    check_output(pfn, nfn, [x, y])


def test_broadcasting():
    check_output(paddle.add, np.add, [data(3, 1, 4), data(2, 1)])


def test_matmul():
    check_output(paddle.matmul, np.matmul, [data(4, 5), data(5, 6)])
    check_output(lambda a, b: paddle.matmul(a, b, transpose_y=True),
                 lambda a, b: a @ b.T, [data(4, 5), data(6, 5)])
    check_output(paddle.matmul, np.matmul, [data(2, 3, 4), data(2, 4, 5)])


def test_reductions():
    x = data(3, 4, 5)
    check_output(lambda t: paddle.sum(t), lambda a: np.sum(a), [x])
    check_output(lambda t: paddle.sum(t, axis=1), lambda a: np.sum(a, 1), [x])
    check_output(lambda t: paddle.mean(t, axis=[0, 2]),
                 lambda a: np.mean(a, (0, 2)), [x])
    check_output(lambda t: paddle.max(t, axis=1, keepdim=True),
                 lambda a: np.max(a, 1, keepdims=True), [x])
    check_output(lambda t: paddle.prod(t, axis=-1), lambda a: np.prod(a, -1), [x])
    check_output(lambda t: paddle.logsumexp(t, axis=1),
                 lambda a: np.log(np.sum(np.exp(a), 1)), [x])


def test_cumsum():
    x = data(3, 4)
    check_output(lambda t: paddle.cumsum(t, axis=1), lambda a: np.cumsum(a, 1), [x])
    check_output(lambda t: paddle.cumsum(t), lambda a: np.cumsum(a.reshape(-1)), [x])


def test_clip_scale():
    x = data(3, 4)
    check_output(lambda t: paddle.clip(t, 0.2, 0.8), lambda a: np.clip(a, 0.2, 0.8), [x])
    check_output(lambda t: paddle.scale(t, 2.0, 1.0), lambda a: a * 2 + 1, [x])


def test_stat():
    x = data(4, 5)
    check_output(lambda t: paddle.var(t, axis=1), lambda a: np.var(a, 1, ddof=1), [x])
    check_output(lambda t: paddle.std(t), lambda a: np.std(a, ddof=1), [x], atol=1e-4)
    check_output(lambda t: paddle.median(t, axis=1), lambda a: np.median(a, 1), [x])


def test_grad_unary():
    check_grad(paddle.tanh, [data(3, 3)])
    check_grad(paddle.exp, [data(3, 3)])
    check_grad(lambda t: paddle.sqrt(t + 0.5), [data(3, 3)])


def test_grad_matmul():
    check_grad(paddle.matmul, [data(3, 4), data(4, 2)], input_idx=0)
    check_grad(paddle.matmul, [data(3, 4), data(4, 2)], input_idx=1)


def test_grad_reduction():
    check_grad(lambda t: paddle.mean(t, axis=0), [data(4, 3)])
    check_grad(lambda t: paddle.max(t, axis=1), [data(4, 3)])
