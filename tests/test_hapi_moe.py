"""hapi Model + MoE + metrics tests (reference: `test/legacy_test/test_model.py`,
moe tests in `test/collective/`)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import EarlyStopping, Model
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy


class XorDataset(Dataset):
    """Cleanly separable 2-class problem."""

    def __init__(self, n=256, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.rand(n, 8).astype(np.float32)
        self.y = (self.x[:, 0] > 0.5).astype(np.int64)
        self.x[:, 0] = self.x[:, 0] * 4 - 2  # amplify signal feature

    def __getitem__(self, i):
        return self.x[i], self.y[i:i + 1]

    def __len__(self):
        return len(self.x)


def test_model_fit_evaluate_predict(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = Model(net)
    model.prepare(optimizer=paddle.optimizer.Adam(1e-2, parameters=net.parameters()),
                  loss=nn.CrossEntropyLoss(), metrics=Accuracy())
    model.fit(XorDataset(), epochs=4, batch_size=32, verbose=0)
    logs = model.evaluate(XorDataset(seed=1), batch_size=64)
    assert logs["acc"] > 0.9, logs
    preds = model.predict(XorDataset(64), batch_size=32, stack_outputs=True)
    assert preds[0].shape == (64, 2)
    # save/load roundtrip
    model.save(str(tmp_path / "ckpt"))
    net2 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    m2 = Model(net2)
    m2.prepare(optimizer=paddle.optimizer.Adam(1e-2, parameters=net2.parameters()),
               loss=nn.CrossEntropyLoss(), metrics=Accuracy())
    m2.load(str(tmp_path / "ckpt"))
    logs2 = m2.evaluate(XorDataset(seed=1), batch_size=64)
    np.testing.assert_allclose(logs2["acc"], logs["acc"])


def test_early_stopping():
    paddle.seed(1)
    net = nn.Linear(8, 2)
    model = Model(net)
    model.prepare(optimizer=paddle.optimizer.SGD(0.0, parameters=net.parameters()),
                  loss=nn.CrossEntropyLoss())
    es = EarlyStopping(monitor="loss", patience=1, min_delta=1e9)  # stop immediately
    model.fit(XorDataset(64), epochs=10, batch_size=32, verbose=0, callbacks=[es])
    assert model.stop_training


def test_moe_layer_routes_and_learns():
    paddle.seed(0)
    from paddle_tpu.incubate.distributed.models.moe import MoELayer
    experts = [nn.Linear(8, 8) for _ in range(4)]
    moe = MoELayer(d_model=8, experts=experts, gate="switch")
    x = paddle.to_tensor(np.random.rand(16, 8).astype(np.float32), stop_gradient=False)
    out = moe(x)
    assert out.shape == [16, 8]
    aux = moe.gate.get_loss()
    assert aux is not None
    total = out.sum() + aux * 0.01
    total.backward()
    grads = [e.weight.grad for e in experts]
    assert any(g is not None for g in grads)
    assert moe.gate.gate_weight.grad is not None


def test_moe_capacity_drops_overflow():
    paddle.seed(0)
    from paddle_tpu.incubate.distributed.models.moe import MoELayer
    experts = [nn.Linear(4, 4) for _ in range(2)]
    moe = MoELayer(d_model=4, experts=experts, gate="naive", topk=1,
                   capacity_factor=0.5)
    x = paddle.to_tensor(np.random.rand(32, 4).astype(np.float32))
    out = moe(x)  # with tight capacity some tokens drop to zero output
    assert out.shape == [32, 4]


def test_summary_and_flops():
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    info = paddle.summary(net, (1, 8))
    assert info["total_params"] == 8 * 16 + 16 + 16 * 2 + 2
    f = paddle.flops(net, (1, 8))
    assert f == 2 * (8 * 16 + 16 * 2)
